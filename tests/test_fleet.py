"""Tests for the fleet/ subsystem (ISSUE 7).

The load-bearing properties, each tested directly:

- token bucket: over any window of a simulated clock, grants never exceed
  ``burst + rate * window`` (no tenant ever exceeds its rate), and a
  starved tenant recovers as soon as tokens refill;
- tenant admission: over-quota is a typed :class:`QuotaError` (429) with
  ``serve_shed_total{cause="quota",tenant=...}`` incremented and a
  bucket-derived ``retry_after_s``; SLO classes map to deadlines that feed
  the engine's existing timeout machinery;
- LRU pager: eviction order and byte accounting against stub entries;
  a model that can never fit is a typed ``CapacityError``; concurrent
  page-ins of one model dedupe to a single activation;
- lease-drain eviction: a victim's in-flight request completes (with the
  right params) BEFORE the incoming model's activation finishes;
- paging correctness: >= 3 models under a budget smaller than their sum
  serve concurrent traffic with zero wrong-params responses, and a
  paged-out model's next request pages it back in and answers correctly
  (predict and generate), with generation numbers continuing across the
  page cycle;
- zero recompiles on re-activation when an ``aot_store`` is attached:
  the per-model compile-miss counters stay flat across page-out/page-in;
- front door: routed predict/generate, ``X-Tenant``, 404 on unknown
  models, 429 + ``Retry-After`` on quota sheds, ``/v1/fleet`` status.
"""

import concurrent.futures as cf
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.aot import AotStore
from deeplearning4j_tpu.fleet import (FleetRegistry, FleetServer, QuotaError,
                                      TenantTable, TokenBucket, WeightPager)
from deeplearning4j_tpu.nn.layers import Dense, Output
from deeplearning4j_tpu.nn.model import NetConfig, Sequential
from deeplearning4j_tpu.obs.metrics import MetricsRegistry
from deeplearning4j_tpu.serve import CapacityError


def _dense_model(n_in=4, n_out=3, seed=0):
    m = Sequential(NetConfig(seed=seed),
                   [Dense(n_out=6, activation="tanh"),
                    Output(n_out=n_out, loss="mcxent", activation="softmax")],
                   (n_in,))
    m.init()
    return m


def _slow_forward(model, delay_s):
    def fwd(params, state, x):
        time.sleep(delay_s)
        y, _ = model.forward(params, state, x, training=False)
        return np.asarray(y)

    return fwd


def _lm(seed=0):
    from deeplearning4j_tpu.models import CausalLM

    m = CausalLM(seed=seed, input_shape=(16,), num_layers=2, d_model=32,
                 num_heads=4, vocab=50).build()
    m.init()
    return m


def _weight_bytes(model) -> int:
    return sum(int(np.asarray(leaf).nbytes)
               for leaf in jax.tree.leaves((model.params, model.state)))


class TestTokenBucket:
    def test_rate_is_never_exceeded_over_any_window(self):
        """Property: with a simulated clock and adversarially bursty
        arrivals, the number of grants inside ANY window [t_i, t_j] is
        bounded by burst + rate * (t_j - t_i)."""
        rng = np.random.RandomState(7)
        rate, burst = 10.0, 5.0
        bucket = TokenBucket(rate, burst)
        now, grants = 0.0, []
        for _ in range(1500):
            # mix of dense bursts and lulls
            now += float(rng.exponential(0.02 if rng.rand() < 0.8 else 0.5))
            if bucket.take(now=now):
                grants.append(now)
        assert len(grants) > 50  # the clock advanced; real traffic flowed
        for i in range(len(grants)):
            for j in range(i, len(grants)):
                window = grants[j] - grants[i]
                allowed = burst + rate * window
                count = j - i + 1
                assert count <= allowed + 1e-9, \
                    f"{count} grants in {window:.3f}s exceeds {allowed:.2f}"

    def test_starved_bucket_recovers(self):
        bucket = TokenBucket(rate_per_s=2.0, burst=2.0)
        now = 0.0
        assert bucket.take(now=now) and bucket.take(now=now)
        assert not bucket.take(now=now)          # starved
        assert bucket.wait_s(now=now) == pytest.approx(0.5)
        now += 0.6                               # one token refilled
        assert bucket.take(now=now)              # recovered
        assert not bucket.take(now=now)
        now += 10.0                              # refill caps at burst
        assert bucket.tokens <= bucket.burst
        assert bucket.take(now=now) and bucket.take(now=now)
        assert not bucket.take(now=now)

    def test_rejects_nonpositive_config(self):
        with pytest.raises(ValueError):
            TokenBucket(0.0, 1.0)
        with pytest.raises(ValueError):
            TokenBucket(1.0, 0.0)


class TestTenantTable:
    def test_quota_shed_is_typed_and_counted(self):
        metrics = MetricsRegistry()
        table = TenantTable(metrics=metrics)
        table.register("free", rate_per_s=1.0, burst=2.0, slo="batch")
        now = 0.0
        assert table.admit("free", model="m", now=now).name == "batch"
        assert table.admit("free", model="m", now=now).name == "batch"
        with pytest.raises(QuotaError) as ei:
            table.admit("free", model="m", now=now)
        assert ei.value.http_status == 429
        assert ei.value.cause == "quota"
        assert ei.value.retry_after_s == pytest.approx(1.0)
        shed = metrics.counter(
            "serve_shed_total",
            {"cause": "quota", "tenant": "free", "model": "m"})
        assert shed.value == 1
        assert table.stats()["free"]["shed"] == 1
        assert table.stats()["free"]["admitted"] == 2
        # refill: the starved tenant recovers
        assert table.admit("free", model="m", now=now + 1.5).name == "batch"

    def test_unknown_tenant_gets_default_policy(self):
        table = TenantTable(default_rate_per_s=2.0, default_burst=1.0)
        slo = table.admit("never-seen-before", now=0.0)
        assert slo.name == "standard" and slo.deadline_ms == 5000.0
        with pytest.raises(QuotaError):
            table.admit("never-seen-before", now=0.0)

    def test_slo_classes_map_to_deadlines(self):
        table = TenantTable()
        table.register("vip", rate_per_s=100, slo="gold")
        table.register("bulk", rate_per_s=100, slo="batch")
        assert table.admit("vip", now=0.0).deadline_ms == 1000.0
        assert table.admit("bulk", now=0.0).deadline_ms is None
        with pytest.raises(ValueError):
            table.register("x", rate_per_s=1, slo="platinum")


class _StubEntry:
    """Duck-typed pager entry recording its lifecycle."""

    def __init__(self, name, nbytes, log, delay_s=0.0):
        self.name = name
        self.weight_bytes = nbytes
        self._log = log
        self._delay = delay_s

    def activate(self):
        if self._delay:
            time.sleep(self._delay)
        self._log.append(("in", self.name))

    def deactivate(self):
        self._log.append(("out", self.name))


class TestWeightPager:
    def test_lru_eviction_order_and_accounting(self):
        log = []
        pager = WeightPager(budget_bytes=250)
        a, b, c = (_StubEntry(n, 100, log) for n in "abc")
        pager.ensure(a)
        pager.ensure(b)
        pager.ensure(c)        # over budget: evicts a (LRU)
        assert log == [("in", "a"), ("in", "b"), ("in", "c"), ("out", "a")] \
            or log == [("in", "a"), ("in", "b"), ("out", "a"), ("in", "c")]
        assert pager.resident() == ["b", "c"]
        pager.ensure(b)        # touch: b becomes MRU
        pager.ensure(a)        # evicts c, NOT b
        assert pager.resident() == ["b", "a"]
        assert pager.stats()["resident_bytes"] == 200
        assert pager.stats()["page_ins"] == 4
        assert pager.stats()["page_outs"] == 2

    def test_model_bigger_than_budget_is_typed(self):
        pager = WeightPager(budget_bytes=100)
        with pytest.raises(CapacityError):
            pager.ensure(_StubEntry("huge", 101, []))

    def test_concurrent_ensures_dedupe_to_one_activation(self):
        log = []
        pager = WeightPager(budget_bytes=1000)
        e = _StubEntry("m", 10, log, delay_s=0.05)
        with cf.ThreadPoolExecutor(8) as ex:
            list(ex.map(lambda _: pager.ensure(e), range(8)))
        assert log == [("in", "m")]  # exactly one page-in
        assert pager.stats()["page_ins"] == 1


class TestFleetPaging:
    def test_eviction_blocks_on_live_leases(self):
        """The pager may only drop a victim's params after every in-flight
        batch against them retires — the hot-swap drain discipline."""
        ma, mb = _dense_model(seed=1), _dense_model(seed=2)
        wb = _weight_bytes(ma)
        fleet = FleetRegistry(hbm_budget_bytes=wb + wb // 2)  # one resident
        fleet.add("a", ma, engine_opts={
            "batch_buckets": (1, 2), "forward": _slow_forward(ma, 0.3)})
        fleet.add("b", mb, engine_opts={"batch_buckets": (1, 2)})
        x = np.random.RandomState(0).rand(1, 4).astype(np.float32)
        fleet.ensure("a")
        done = {}

        def slow_request():
            res = fleet.predict("a", x, tenant="t")
            done["a"] = (time.perf_counter(), res.output)

        t = threading.Thread(target=slow_request)
        t.start()
        time.sleep(0.1)  # request admitted, forward mid-sleep
        res_b = fleet.predict("b", x, tenant="t")   # forces eviction of a
        t_b = time.perf_counter()
        t.join(10)
        assert "a" in done, "victim's in-flight request was dropped"
        t_a, out_a = done["a"]
        # the victim's batch completed BEFORE b's page-in finished serving
        assert t_a <= t_b, "eviction did not wait for live leases"
        np.testing.assert_allclose(
            out_a, np.asarray(ma.output(x)), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            res_b.output, np.asarray(mb.output(x)), rtol=1e-4, atol=1e-5)
        assert fleet.pager.resident() == ["b"]

    def test_three_models_under_budget_concurrent_purity(self):
        """Acceptance: >= 3 named models under a budget smaller than their
        sum, LRU churn under concurrent traffic, ZERO wrong-params
        responses."""
        models = {n: _dense_model(seed=s)
                  for n, s in (("alpha", 1), ("beta", 2), ("gamma", 3))}
        wb = _weight_bytes(models["alpha"])
        fleet = FleetRegistry(hbm_budget_bytes=2 * wb + wb // 2)  # fits 2/3
        for n, m in models.items():
            fleet.add(n, m, engine_opts={"batch_buckets": (1, 2, 4)})
        rng = np.random.RandomState(0)
        xs = {n: rng.rand(2, 4).astype(np.float32) for n in models}
        refs = {n: np.asarray(models[n].output(xs[n])) for n in models}
        names = sorted(models) * 8
        rng.shuffle(names)

        def fire(name):
            res = fleet.predict(name, xs[name], tenant="t")
            np.testing.assert_allclose(res.output, refs[name],
                                       rtol=1e-4, atol=1e-5)
            return name

        with cf.ThreadPoolExecutor(6) as ex:
            assert sorted(ex.map(fire, names)) == sorted(names)
        stats = fleet.pager.stats()
        assert stats["page_outs"] >= 1, "budget never forced an eviction"
        assert len(fleet.pager.resident()) <= 2
        fleet.shutdown()

    def test_paged_out_model_pages_back_in_generate(self):
        """A paged-out LM's next generate pages it back in, decodes
        correctly, and its generation counter continues (never resets)."""
        from deeplearning4j_tpu.nn.generation import generate as refgen

        la, lb = _lm(seed=0), _lm(seed=1)
        wb = _weight_bytes(la)
        fleet = FleetRegistry(hbm_budget_bytes=wb + wb // 2)  # one resident
        gen_opts = {"slots": 2, "capacity": 32, "prefill_chunk": 8}
        fleet.add("a", la, input_dtype=np.int32, gen_opts=gen_opts)
        fleet.add("b", lb, input_dtype=np.int32, gen_opts=gen_opts)
        prompt = np.asarray([1, 2, 3, 4], np.int32)
        want_a = refgen(la, prompt[None], 4, temperature=0.0)[0].tolist()
        want_b = refgen(lb, prompt[None], 4, temperature=0.0)[0].tolist()

        toks = fleet.generate("a", prompt, 4, tenant="t", temperature=0.0)
        assert toks.tolist() == want_a
        gen_before = fleet.get("a").info()["generation"]
        toks = fleet.generate("b", prompt, 4, tenant="t", temperature=0.0)
        assert toks.tolist() == want_b
        assert not fleet.get("a").resident          # a was paged out
        toks = fleet.generate("a", prompt, 4, tenant="t", temperature=0.0)
        assert toks.tolist() == want_a              # paged back in, correct
        assert fleet.get("a").info()["generation"] > gen_before
        fleet.shutdown()

    def test_hot_swap_survives_page_cycle(self):
        """Weights published while resident are what the next residency
        serves; generations stay monotonic across the page cycle."""
        ma, mb, donor = (_dense_model(seed=s) for s in (1, 2, 9))
        wb = _weight_bytes(ma)
        fleet = FleetRegistry(hbm_budget_bytes=wb + wb // 2)
        fleet.add("a", ma, engine_opts={"batch_buckets": (1, 2)})
        fleet.add("b", mb, engine_opts={"batch_buckets": (1, 2)})
        x = np.random.RandomState(0).rand(1, 4).astype(np.float32)
        r1 = fleet.predict("a", x, tenant="t")
        assert r1.generation == 1
        gen = fleet.publish("a", donor.params, donor.state)   # hot-swap
        assert gen == 2
        r2 = fleet.predict("a", x, tenant="t")
        np.testing.assert_allclose(
            r2.output, np.asarray(donor.output(x)), rtol=1e-4, atol=1e-5)
        assert r2.generation == 2
        fleet.predict("b", x, tenant="t")                     # pages a out
        r3 = fleet.predict("a", x, tenant="t")                # pages a in
        np.testing.assert_allclose(
            r3.output, np.asarray(donor.output(x)), rtol=1e-4, atol=1e-5)
        assert r3.generation == 3   # start_generation continued the order
        fleet.shutdown()

    def test_reactivation_zero_recompiles_with_aot_store(self, tmp_path):
        """With a shared aot_store, paging a model back in loads every
        executable from disk: the per-model compile-miss counter is flat
        across the page cycle and the store takes hits."""
        ma, mb = _dense_model(seed=1), _dense_model(seed=2)
        wb = _weight_bytes(ma)
        metrics = MetricsRegistry()
        store = AotStore(str(tmp_path / "aot"))
        fleet = FleetRegistry(hbm_budget_bytes=wb + wb // 2, metrics=metrics,
                              aot_store=store)
        opts = {"batch_buckets": (1, 2)}
        fleet.add("a", ma, engine_opts=dict(opts))
        fleet.add("b", mb, engine_opts=dict(opts))
        x = np.random.RandomState(0).rand(1, 4).astype(np.float32)
        ref = np.asarray(ma.output(x))

        def compiles(model):
            return metrics.counter("serve_compile_misses_total",
                                   {"component": "engine",
                                    "model": model}).value

        np.testing.assert_allclose(fleet.predict("a", x, tenant="t").output,
                                   ref, rtol=1e-4, atol=1e-5)
        after_first = compiles("a")
        hits0 = metrics.counter("serve_aot_hits_total",
                                {"component": "engine"}).value
        fleet.predict("b", x, tenant="t")           # pages a out
        assert not fleet.get("a").resident
        np.testing.assert_allclose(fleet.predict("a", x, tenant="t").output,
                                   ref, rtol=1e-4, atol=1e-5)
        assert compiles("a") == after_first, \
            "re-activation traced instead of loading from the AOT store"
        hits1 = metrics.counter("serve_aot_hits_total",
                                {"component": "engine"}).value
        assert hits1 > hits0, "re-activation took no AOT store hits"
        fleet.shutdown()


class TestFleetHTTP:
    def _post(self, port, path, body, tenant=None, timeout=30):
        headers = {"Content-Type": "application/json"}
        if tenant is not None:
            headers["X-Tenant"] = tenant
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
            headers=headers)
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())

    def _get(self, port, path):
        return json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10).read())

    def test_routed_front_door(self):
        ma, mb = _dense_model(seed=1), _dense_model(seed=2)
        fleet = FleetRegistry()
        fleet.tenants.register("free", rate_per_s=1.0, burst=2.0, slo="batch")
        fleet.add("a", ma, engine_opts={"batch_buckets": (1, 2)})
        fleet.add("b", mb, engine_opts={"batch_buckets": (1, 2)})
        srv = FleetServer(fleet, port=0).start()
        try:
            x = np.random.RandomState(0).rand(2, 4).astype(np.float32)
            for name, model in (("a", ma), ("b", mb)):
                out = self._post(srv.port, f"/v1/models/{name}/predict",
                                 {"ndarray": x.tolist()}, tenant="gold")
                np.testing.assert_allclose(
                    np.asarray(out["output"]), np.asarray(model.output(x)),
                    rtol=1e-4, atol=1e-5)
                assert out["model"] == name and out["generation"] >= 1

            # unknown model: typed 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._post(srv.port, "/v1/models/nope/predict",
                           {"ndarray": x.tolist()})
            assert ei.value.code == 404
            assert json.loads(ei.value.read())["cause"] == "unknown_model"

            # X-Tenant rides into quota enforcement: 429 + Retry-After
            codes = []
            for _ in range(5):
                try:
                    self._post(srv.port, "/v1/models/a/predict",
                               {"ndarray": x.tolist()}, tenant="free")
                    codes.append(200)
                except urllib.error.HTTPError as e:
                    body = json.loads(e.read())
                    codes.append((e.code, body["cause"],
                                  e.headers.get("Retry-After")))
            assert 200 in codes
            quota = [c for c in codes if c != 200]
            assert quota and all(
                c[0] == 429 and c[1] == "quota" and int(c[2]) >= 1
                for c in quota), codes

            # fleet status: models + pager + tenants in one view
            st = self._get(srv.port, "/v1/fleet")
            assert set(st["models"]) == {"a", "b"}
            assert st["models"]["a"]["resident"] is True
            assert st["pager"]["page_ins"] >= 2
            assert st["tenants"]["free"]["shed"] >= 1
            assert self._get(srv.port, "/health")["models"] == ["a", "b"]
            assert self._get(srv.port, "/ready")["status"] == "ready"
            one = self._get(srv.port, "/v1/models/a")
            assert one["model"] == "a" and one["resident"] is True

            # quota sheds + model labels land on the shared scrape
            scrape = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10
                ).read().decode()
            assert 'serve_shed_total{cause="quota"' in scrape
            assert 'tenant="free"' in scrape
            assert 'serve_lease_total{model="a",tag="engine_batch"}' in scrape
        finally:
            srv.stop()

    def test_admin_drain_pages_out_and_answers_200(self):
        # regression: the handler once called the .resident property as a
        # method, so every drain answered 400 ('bool' is not callable) and
        # callers silently fell back to stop()-time draining
        fleet = FleetRegistry()
        fleet.add("a", _dense_model(seed=1),
                  engine_opts={"batch_buckets": (1, 2)})
        srv = FleetServer(fleet, port=0).start()
        try:
            x = np.random.RandomState(0).rand(1, 4).astype(np.float32)
            self._post(srv.port, "/v1/models/a/predict",
                       {"ndarray": x.tolist()})
            out = self._post(srv.port, "/v1/admin/drain", {"model": "a"})
            assert out == {"model": "a", "resident": False}
            assert self._get(srv.port, "/v1/models/a")["resident"] is False
            # drained, not deleted: the pager pages it back in on demand
            out = self._post(srv.port, "/v1/models/a/predict",
                             {"ndarray": x.tolist()})
            assert out["model"] == "a"
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._post(srv.port, "/v1/admin/drain", {"model": "nope"})
            assert ei.value.code != 500
        finally:
            srv.stop()

    def test_generate_routes_and_sse(self):
        from deeplearning4j_tpu.nn.generation import generate as refgen

        lm = _lm(seed=0)
        fleet = FleetRegistry()
        fleet.add("lm", lm, input_dtype=np.int32,
                  gen_opts={"slots": 2, "capacity": 32})
        srv = FleetServer(fleet, port=0).start()
        try:
            prompt = [1, 2, 3]
            want = refgen(lm, np.asarray([prompt], np.int32), 3,
                          temperature=0.0)[0].tolist()
            out = self._post(srv.port, "/v1/models/lm/generate?stream=false",
                             {"prompt": prompt, "max_new_tokens": 3,
                              "temperature": 0.0})
            assert out["tokens"] == want and out["model"] == "lm"

            # default path streams SSE, token-identical
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/models/lm/generate",
                data=json.dumps({"prompt": prompt, "max_new_tokens": 3,
                                 "temperature": 0.0}).encode(),
                headers={"Content-Type": "application/json"})
            events = []
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.headers["Content-Type"] == "text/event-stream"
                for line in r:
                    if line.startswith(b"data: "):
                        events.append(json.loads(line[len(b"data: "):]))
            assert events[-1]["done"] and events[-1]["tokens"] == want
            assert [e["token"] for e in events[:-1]] == want
        finally:
            srv.stop()
