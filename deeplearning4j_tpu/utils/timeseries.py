"""Time-series / masked-reduction utilities.

Reference parity: ``util/TimeSeriesUtils.java`` (mask reshaping, last-step
extraction, time reversal) and ``util/MaskedReductionUtil.java`` (masked
max/avg/sum/pnorm pooling). The same math lives fused inside GlobalPooling /
LastTimeStep; these standalone functions are the public utility surface the
reference exposes, jit-friendly (static shapes, no data-dependent control
flow) so they compose inside any training step.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

Array = jnp.ndarray


def masked_pool(x: Array, mask: Optional[Array], mode: str = "avg",
                pnorm: int = 2) -> Array:
    """Masked reduction over the time axis of (B, T, F) — MaskedReductionUtil
    masked{Max,Avg,Sum,PNorm}TimeSeries. mask: (B, T) 1/0; None = all valid."""
    if x.ndim != 3:
        raise ValueError(f"masked_pool expects (B, T, F), got {x.shape}")
    if mask is None:
        m = jnp.ones(x.shape[:2], x.dtype)[..., None]
    else:
        m = mask.astype(x.dtype)[..., None]
    if mode == "max":
        neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
        return jnp.max(jnp.where(m > 0, x, neg), axis=1)
    if mode == "sum":
        return jnp.sum(x * m, axis=1)
    if mode == "avg":
        return jnp.sum(x * m, axis=1) / jnp.maximum(m.sum(axis=1), 1.0)
    if mode == "pnorm":
        s = jnp.sum(jnp.abs(x * m) ** pnorm, axis=1)
        return s ** (1.0 / pnorm)
    raise ValueError(f"Unknown pooling mode '{mode}'")


def pull_last_time_step(x: Array, mask: Optional[Array] = None) -> Array:
    """(B, T, F) -> (B, F): the LAST VALID step per sequence
    (TimeSeriesUtils.pullLastTimeSteps). With no mask, step T-1."""
    if x.ndim != 3:
        raise ValueError(f"pull_last_time_step expects (B, T, F), got {x.shape}")
    if mask is None:
        return x[:, -1, :]
    idx = last_time_step_index(mask)
    return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0, :]


def last_time_step_index(mask: Array) -> Array:
    """(B, T) mask -> (B,) index of each sequence's last valid step
    (TimeSeriesUtils.getLastTimeStepIndex); all-zero masks map to step 0."""
    T = mask.shape[1]
    has = mask > 0
    rev_arg = jnp.argmax(has[:, ::-1].astype(jnp.int32), axis=1)
    idx = T - 1 - rev_arg
    return jnp.where(has.any(axis=1), idx, 0)


def reverse_time_series(x: Array, mask: Optional[Array] = None) -> Array:
    """Reverse the time axis; with a mask, each sequence reverses within its
    own valid length, padding stays at the tail
    (TimeSeriesUtils.reverseTimeSeries — the Bidirectional-RNN primitive)."""
    if mask is None:
        return x[:, ::-1, ...]
    T = x.shape[1]
    lengths = jnp.sum((mask > 0).astype(jnp.int32), axis=1)  # (B,)
    t = jnp.arange(T)[None, :]                               # (1, T)
    src = lengths[:, None] - 1 - t                           # reversed index
    src = jnp.where((t < lengths[:, None]) & (src >= 0), src, t)
    return jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)


def expand_time_series_mask(mask: Array, features: int) -> Array:
    """(B, T) -> (B, T, F) broadcast of a per-step mask to per-feature
    (TimeSeriesUtils.reshapeTimeSeriesMaskToVector's inverse layout — our
    layout is feature-last, so the expansion is a broadcast, not a reshape)."""
    return jnp.broadcast_to(mask[..., None].astype(jnp.float32),
                            mask.shape + (features,))


def time_series_lengths(mask: Array) -> Array:
    """(B, T) mask -> (B,) valid lengths."""
    return jnp.sum((mask > 0).astype(jnp.int32), axis=1)
