"""Object-detection post-processing — ``nn/layers/objdetect/YoloUtils.java``
and ``DetectedObject.java`` parity.

Host-side by design: box filtering + greedy NMS is tiny, ragged, data-
dependent work (exactly what does NOT belong in a jit); the device produces
the activated (B, H, W, A*(5+C)) grid (Yolo2Output.apply) and this module
turns it into detection lists.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Sequence

import numpy as np


@dataclass
class DetectedObject:
    """One detection in GRID units (DetectedObject.java): center/size plus
    confidence = objectness * class probability."""

    center_x: float
    center_y: float
    width: float
    height: float
    confidence: float
    predicted_class: int
    class_probabilities: np.ndarray = field(repr=False)

    @property
    def top_left(self):
        return (self.center_x - self.width / 2, self.center_y - self.height / 2)

    @property
    def bottom_right(self):
        return (self.center_x + self.width / 2, self.center_y + self.height / 2)


def iou(a: DetectedObject, b: DetectedObject) -> float:
    """Intersection-over-union of two detections (YoloUtils.iou)."""
    ax1, ay1 = a.top_left
    ax2, ay2 = a.bottom_right
    bx1, by1 = b.top_left
    bx2, by2 = b.bottom_right
    iw = max(0.0, min(ax2, bx2) - max(ax1, bx1))
    ih = max(0.0, min(ay2, by2) - max(ay1, by1))
    inter = iw * ih
    union = a.width * a.height + b.width * b.height - inter
    return inter / union if union > 0 else 0.0


def non_max_suppression(objs: List[DetectedObject], iou_threshold: float = 0.4,
                        ) -> List[DetectedObject]:
    """Greedy per-class NMS (YoloUtils.nms): keep highest-confidence boxes,
    suppress same-class overlaps above the IoU threshold."""
    keep: List[DetectedObject] = []
    for obj in sorted(objs, key=lambda o: -o.confidence):
        if all(not (k.predicted_class == obj.predicted_class
                    and iou(k, obj) > iou_threshold) for k in keep):
            keep.append(obj)
    return keep


def get_predicted_objects(activated: np.ndarray, num_anchors: int,
                          conf_threshold: float = 0.5,
                          nms_threshold: float = 0.4,
                          apply_nms: bool = True) -> List[List[DetectedObject]]:
    """Decode Yolo2Output.apply's activated grid into detections per image
    (YoloUtils.getPredictedObjects). ``activated``: (B, H, W, A*(5+C)) with
    per-anchor [x, y, w, h, obj, class-probs...]; x/y are offsets within the
    cell, w/h grid-relative sizes (Yolo2Output encoding)."""
    activated = np.asarray(activated)
    B, H, W, D = activated.shape
    A = num_anchors
    C = D // A - 5
    if C < 1:
        raise ValueError(f"activated depth {D} with {A} anchors leaves no classes")
    grid = activated.reshape(B, H, W, A, 5 + C)
    out: List[List[DetectedObject]] = []
    for b in range(B):
        objs: List[DetectedObject] = []
        obj_conf = grid[b, ..., 4]                       # (H, W, A)
        cls_probs = grid[b, ..., 5:]                     # (H, W, A, C)
        conf = obj_conf[..., None] * cls_probs           # per-class confidence
        ys, xs, aa = np.nonzero(conf.max(-1) > conf_threshold)
        for y, x, a in zip(ys, xs, aa):
            cell = grid[b, y, x, a]
            c = int(np.argmax(conf[y, x, a]))
            objs.append(DetectedObject(
                center_x=float(x + cell[0]), center_y=float(y + cell[1]),
                width=float(cell[2]), height=float(cell[3]),
                confidence=float(conf[y, x, a, c]),
                predicted_class=c,
                class_probabilities=cls_probs[y, x, a].copy()))
        out.append(non_max_suppression(objs, nms_threshold) if apply_nms else objs)
    return out
