"""Shared JSON-over-http.server scaffolding for the kNN and UI daemons.

One place for the handler factory plumbing: reply encoding, port-0
resolution, background-thread serve loop, and shutdown ordering.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class JsonRequestHandler(BaseHTTPRequestHandler):
    """Quiet handler with JSON helpers; subclasses implement do_GET/do_POST
    and reach their server object via ``self.owner``."""

    owner = None  # set by the subclass closure

    def log_message(self, *a):
        pass

    def reply(self, code: int, payload, ctype: str = "application/json"):
        body = payload.encode() if isinstance(payload, str) \
            else json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def read_json(self):
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n) or b"{}")


class JsonHTTPServerMixin:
    """start()/stop() lifecycle shared by NearestNeighborsServer & UIServer.
    Subclasses set ``self.host``/``self.port`` and implement ``_handler()``
    returning a JsonRequestHandler subclass."""

    _httpd: Optional[ThreadingHTTPServer] = None
    _thread: Optional[threading.Thread] = None

    def start(self, background: bool = True):
        self._httpd = ThreadingHTTPServer((self.host, self.port), self._handler())
        self.port = self._httpd.server_address[1]  # resolves port=0
        if background:
            self._thread = threading.Thread(target=self._httpd.serve_forever,
                                            daemon=True)
            self._thread.start()
        else:
            self._httpd.serve_forever()
        return self

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
