"""Shared JSON-over-http.server scaffolding for the kNN and UI daemons.

One place for the handler factory plumbing: reply encoding, port-0
resolution, background-thread serve loop, and shutdown ordering — plus
request telemetry: any server object exposing a ``metrics`` registry
(``obs.metrics.MetricsRegistry``) gets per-endpoint request-latency
histograms and a ``GET /metrics`` Prometheus scrape for free, with no
changes to its handler code. The coupling is duck-typed so this module
stays importable without obs.
"""

from __future__ import annotations

import functools
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlsplit

PROMETHEUS_CTYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CTYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"


def _instrumented(fn, verb: str):
    """Wrap a do_GET/do_POST with request telemetry against ``owner.metrics``.

    GET /metrics is answered here (Prometheus text format) so every server
    built on this scaffolding scrapes identically. Label cardinality is the
    owner's problem: servers with parameterized paths provide
    ``_metric_route(path)`` to collapse them (e.g. ``/train/{sid}/overview``)
    — otherwise the raw path is the endpoint label.
    """

    @functools.wraps(fn)
    def wrapper(self):
        reg = getattr(self.owner, "metrics", None)
        if reg is None:
            return fn(self)
        path = urlsplit(self.path).path
        if verb == "GET" and path == "/metrics":
            # content negotiation: OpenMetrics (exemplar-capable) on request,
            # classic 0.0.4 text otherwise — exemplars are illegal in 0.0.4
            if "application/openmetrics-text" in self.headers.get("Accept", ""):
                self.reply(200, reg.to_openmetrics(), OPENMETRICS_CTYPE)
            else:
                self.reply(200, reg.to_prometheus(), PROMETHEUS_CTYPE)
            return None
        route = getattr(self.owner, "_metric_route", None)
        endpoint = route(path) if route is not None else path
        labels = {"method": verb, "endpoint": endpoint}
        t0 = time.perf_counter()
        try:
            return fn(self)
        finally:
            # handlers that trace requests leave their trace_id on the
            # handler instance; it becomes the latency exemplar
            reg.histogram("http_request_seconds", labels,
                          help="HTTP request handling latency by endpoint"
                          ).observe(time.perf_counter() - t0,
                                    trace_id=getattr(self, "_obs_trace_id",
                                                     None))
            reg.counter("http_requests_total", labels,
                        help="HTTP requests served by endpoint").inc()

    return wrapper


class JsonRequestHandler(BaseHTTPRequestHandler):
    """Quiet handler with JSON helpers; subclasses implement do_GET/do_POST
    and reach their server object via ``self.owner``.

    Every ``do_*`` method is an *error-surface boundary* for jaxlint's v5
    error-flow pass: exceptions provably reaching it must land in a typed
    or deliberately-mapped ``except`` clause, and the per-endpoint
    (exception → status) map is diffed against the committed
    ``scripts/error_budget.json`` in CI."""

    owner = None  # set by the subclass closure

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        for method in ("do_GET", "do_POST"):
            fn = cls.__dict__.get(method)
            if fn is not None and not getattr(fn, "_obs_wrapped", False):
                wrapped = _instrumented(fn, method[3:])
                wrapped._obs_wrapped = True
                setattr(cls, method, wrapped)

    def log_message(self, *a):
        pass

    def reply(self, code: int, payload, ctype: str = "application/json",
              headers: Optional[dict] = None):
        body = payload.encode() if isinstance(payload, str) \
            else json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def read_json(self):
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n) or b"{}")


class JsonHTTPServerMixin:
    """start()/stop() lifecycle shared by NearestNeighborsServer & UIServer.
    Subclasses set ``self.host``/``self.port`` and implement ``_handler()``
    returning a JsonRequestHandler subclass."""

    _httpd: Optional[ThreadingHTTPServer] = None
    _thread: Optional[threading.Thread] = None

    def start(self, background: bool = True):
        self._httpd = ThreadingHTTPServer((self.host, self.port), self._handler())
        self.port = self._httpd.server_address[1]  # resolves port=0
        if background:
            self._thread = threading.Thread(target=self._httpd.serve_forever,
                                            daemon=True)
            self._thread.start()
        else:
            self._httpd.serve_forever()
        return self

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
