"""Cluster provisioning — deeplearning4j-aws equivalent (SURVEY.md §2.4:
``aws/ec2/provision/ClusterSetup.java``, ``Ec2BoxCreator.java``).

The reference shells out to the EC2 API to create boxes and rsync a
distributed run onto them. The TPU-native counterpart provisions TPU pod
slices: this module *generates* the gcloud commands / bootstrap scripts
(deterministic, reviewable, no cloud credentials or egress needed at build
time) and can execute them when a ``runner`` is injected.
"""

from __future__ import annotations

import shlex
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence


@dataclass
class TpuPodSpec:
    """ClusterSetup config equivalent for a TPU pod slice."""

    name: str = "dl4j-tpu-pod"
    accelerator_type: str = "v5litepod-16"   # e.g. v4-32, v5litepod-256
    zone: str = "us-central2-b"
    project: Optional[str] = None
    runtime_version: str = "tpu-ubuntu2204-base"
    preemptible: bool = False
    network: Optional[str] = None
    metadata: Dict[str, str] = field(default_factory=dict)


class TpuClusterSetup:
    """Generates (and optionally runs) the provisioning command sequence.

    ``runner`` is a ``fn(cmd: List[str]) -> int``; defaults to dry-run
    (collect only), mirroring how ClusterSetup separates plan from execute.
    """

    def __init__(self, spec: TpuPodSpec,
                 runner: Optional[Callable[[List[str]], int]] = None):
        self.spec = spec
        self.runner = runner
        self.executed: List[List[str]] = []

    # --- command generation (Ec2BoxCreator.create equivalent) ---
    def create_command(self) -> List[str]:
        s = self.spec
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "create", s.name,
               f"--zone={s.zone}", f"--accelerator-type={s.accelerator_type}",
               f"--version={s.runtime_version}"]
        if s.project:
            cmd.append(f"--project={s.project}")
        if s.preemptible:
            cmd.append("--preemptible")
        if s.network:
            cmd.append(f"--network={s.network}")
        for k, v in s.metadata.items():
            cmd.append(f"--metadata={k}={v}")
        return cmd

    def delete_command(self) -> List[str]:
        s = self.spec
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "delete", s.name,
               f"--zone={s.zone}", "--quiet"]
        if s.project:
            cmd.append(f"--project={s.project}")
        return cmd

    def run_on_all_workers_command(self, remote_cmd: str) -> List[str]:
        """Distributed launch: the same command on every pod worker — the
        moral equivalent of ClusterSetup's parallel SSH provisioning."""
        s = self.spec
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", s.name,
               f"--zone={s.zone}", "--worker=all",
               f"--command={remote_cmd}"]
        if s.project:
            cmd.append(f"--project={s.project}")
        return cmd

    def bootstrap_script(self, repo_url: str, entrypoint: str = "python train.py",
                         env: Optional[Dict[str, str]] = None) -> str:
        """Worker bootstrap shell script: deps + repo + `jax.distributed`-ready
        launch (coordinator resolution is automatic on TPU pods)."""
        lines = ["#!/usr/bin/env bash", "set -euo pipefail",
                 "pip install -q 'jax[tpu]' optax flax 2>/dev/null || true",
                 f"git clone {shlex.quote(repo_url)} app || (cd app && git pull)",
                 "cd app"]
        for k, v in (env or {}).items():
            lines.append(f"export {k}={shlex.quote(v)}")
        lines.append(entrypoint)
        return "\n".join(lines) + "\n"

    def plan(self, repo_url: str, entrypoint: str = "python train.py") -> List[List[str]]:
        boot = self.bootstrap_script(repo_url, entrypoint)
        return [self.create_command(),
                self.run_on_all_workers_command(f"bash -c {shlex.quote(boot)}")]

    # --- execution ---
    def execute(self, commands: Sequence[List[str]]) -> int:
        if self.runner is None:
            raise RuntimeError("dry-run setup: inject runner= to execute")
        for cmd in commands:
            self.executed.append(list(cmd))
            rc = self.runner(list(cmd))
            if rc != 0:
                return rc
        return 0
