"""Cluster provisioning — deeplearning4j-aws equivalent (SURVEY.md §2.4:
``aws/ec2/provision/ClusterSetup.java``, ``Ec2BoxCreator.java``).

The reference shells out to the EC2 API to create boxes and rsync a
distributed run onto them. The TPU-native counterpart provisions TPU pod
slices: this module *generates* the gcloud commands / bootstrap scripts
(deterministic, reviewable, no cloud credentials or egress needed at build
time) and can execute them when a ``runner`` is injected.
"""

from __future__ import annotations

import shlex
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

# (cores_per_chip, chips_per_host) by TPU generation. NOTE the public
# naming convention: the pod-slice suffix counts TENSORCORES for v4/v5p
# ("v4-32" = 32 cores = 16 chips on 4 hosts) but CHIPS for v5e/v6e
# ("v5litepod-256" = 256 chips on 32 hosts).
_GEN = {"v4": (2, 4), "v5p": (2, 4),
        "v5litepod": (1, 8), "v5e": (1, 8), "v6e": (1, 8)}


def topology(accelerator_type: str, strict: bool = True) -> Optional[Dict[str, int]]:
    """Derive a slice's host/chip layout from its accelerator type.

    Returns {"chips": N, "hosts": H, "chips_per_host": C}. Malformed
    strings always raise; an UNKNOWN generation raises when ``strict``
    (catching typos before a gcloud round trip) and returns None otherwise
    (pure command generation still works for e.g. v2/v3 types this table
    doesn't model).
    """
    try:
        gen, count = accelerator_type.rsplit("-", 1)
        suffix = int(count)
    except ValueError:
        raise ValueError(f"malformed accelerator type '{accelerator_type}' "
                         f"(expected e.g. v4-32, v5litepod-256)")
    if gen not in _GEN:
        if strict:
            raise ValueError(f"unknown TPU generation '{gen}' "
                             f"(known: {sorted(_GEN)})")
        return None
    cores_per_chip, cph = _GEN[gen]
    if suffix % cores_per_chip:
        raise ValueError(f"{accelerator_type}: suffix {suffix} is not a "
                         f"multiple of {cores_per_chip} cores/chip for {gen}")
    chips = suffix // cores_per_chip
    if chips <= cph:  # sub-host or single-host slice: one host
        return {"chips": chips, "hosts": 1, "chips_per_host": chips}
    if chips % cph:
        raise ValueError(f"{accelerator_type}: {chips} chips is not a "
                         f"multiple of {cph} chips/host for {gen}")
    return {"chips": chips, "hosts": chips // cph, "chips_per_host": cph}


@dataclass
class TpuPodSpec:
    """ClusterSetup config equivalent for a TPU pod slice."""

    name: str = "dl4j-tpu-pod"
    accelerator_type: str = "v5litepod-16"   # e.g. v4-32, v5litepod-256
    zone: str = "us-central2-b"
    project: Optional[str] = None
    runtime_version: str = "tpu-ubuntu2204-base"
    preemptible: bool = False
    network: Optional[str] = None
    metadata: Dict[str, str] = field(default_factory=dict)

    def __post_init__(self):
        # non-strict: unknown generations (v2/v3, future gens) still allow
        # pure command generation; host math is simply unavailable for them
        self.topology = topology(self.accelerator_type, strict=False)

    @property
    def num_hosts(self) -> Optional[int]:
        return self.topology["hosts"] if self.topology else None

    @property
    def num_chips(self) -> Optional[int]:
        return self.topology["chips"] if self.topology else None


class TpuClusterSetup:
    """Generates (and optionally runs) the provisioning command sequence.

    ``runner`` is a ``fn(cmd: List[str]) -> int``; defaults to dry-run
    (collect only), mirroring how ClusterSetup separates plan from execute.
    """

    def __init__(self, spec: TpuPodSpec,
                 runner: Optional[Callable[[List[str]], int]] = None):
        self.spec = spec
        self.runner = runner
        self.executed: List[List[str]] = []

    # --- command generation (Ec2BoxCreator.create equivalent) ---
    def create_command(self) -> List[str]:
        s = self.spec
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "create", s.name,
               f"--zone={s.zone}", f"--accelerator-type={s.accelerator_type}",
               f"--version={s.runtime_version}"]
        if s.project:
            cmd.append(f"--project={s.project}")
        if s.preemptible:
            cmd.append("--preemptible")
        if s.network:
            cmd.append(f"--network={s.network}")
        for k, v in s.metadata.items():
            cmd.append(f"--metadata={k}={v}")
        return cmd

    def delete_command(self) -> List[str]:
        s = self.spec
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "delete", s.name,
               f"--zone={s.zone}", "--quiet"]
        if s.project:
            cmd.append(f"--project={s.project}")
        return cmd

    def run_on_all_workers_command(self, remote_cmd: str) -> List[str]:
        """Distributed launch: the same command on every pod worker — the
        moral equivalent of ClusterSetup's parallel SSH provisioning."""
        s = self.spec
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", s.name,
               f"--zone={s.zone}", "--worker=all",
               f"--command={remote_cmd}"]
        if s.project:
            cmd.append(f"--project={s.project}")
        return cmd

    def bootstrap_script(self, repo_url: str, entrypoint: str = "python train.py",
                         env: Optional[Dict[str, str]] = None) -> str:
        """Worker bootstrap shell script: deps + repo + `jax.distributed`-ready
        launch (coordinator resolution is automatic on TPU pods)."""
        lines = ["#!/usr/bin/env bash", "set -euo pipefail",
                 "pip install -q 'jax[tpu]' optax flax 2>/dev/null || true",
                 f"git clone {shlex.quote(repo_url)} app || (cd app && git pull)",
                 "cd app"]
        for k, v in (env or {}).items():
            lines.append(f"export {k}={shlex.quote(v)}")
        lines.append(entrypoint)
        return "\n".join(lines) + "\n"

    def describe_command(self) -> List[str]:
        s = self.spec
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "describe", s.name,
               f"--zone={s.zone}"]
        if s.project:
            cmd.append(f"--project={s.project}")
        return cmd

    def copy_command(self, local_path: str, remote_path: str = "~/") -> List[str]:
        """Ship code/data to every worker (ClusterSetup's rsync step)."""
        s = self.spec
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "scp", "--recurse",
               local_path, f"{s.name}:{remote_path}", f"--zone={s.zone}",
               "--worker=all"]
        if s.project:
            cmd.append(f"--project={s.project}")
        return cmd

    def plan(self, repo_url: str, entrypoint: str = "python train.py") -> List[List[str]]:
        boot = self.bootstrap_script(repo_url, entrypoint)
        return [self.create_command(),
                self.run_on_all_workers_command(f"bash -c {shlex.quote(boot)}")]

    def multihost_train_plan(self, repo_url: str, train_args: str = "") -> List[List[str]]:
        """Full distributed-training launch: provision the slice, then start
        the framework's multi-host path on every worker. On TPU pods
        ``jax.distributed.initialize()`` auto-discovers the coordinator, so
        every host runs the SAME command; ``DL4J_TPU_MULTIHOST=1`` makes the
        CLI bootstrap ``initialize_multihost`` + ``MultiHostTrainer`` with a
        per-process data shard (cli.py). The reference needed Spark
        master/worker asymmetry; a pod slice needs one command."""
        if self.spec.topology is None:
            raise ValueError(
                f"multi-host launch needs known host math for "
                f"'{self.spec.accelerator_type}' — known generations: "
                f"{sorted(_GEN)}")
        entry = ("python -m deeplearning4j_tpu.cli train "
                 + train_args).strip()
        boot = self.bootstrap_script(
            repo_url, entry,
            env={"DL4J_TPU_MULTIHOST": "1",
                 "DL4J_TPU_NUM_HOSTS": str(self.spec.num_hosts)})
        return [self.create_command(),
                self.run_on_all_workers_command(f"bash -c {shlex.quote(boot)}")]

    # --- execution ---
    def execute(self, commands: Sequence[List[str]]) -> int:
        if self.runner is None:
            raise RuntimeError("dry-run setup: inject runner= to execute")
        for cmd in commands:
            self.executed.append(list(cmd))
            rc = self.runner(list(cmd))
            if rc != 0:
                return rc
        return 0
