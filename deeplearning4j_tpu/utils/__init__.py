"""Utilities: gradient checking (the universal layer oracle, SURVEY.md §4)
and memory reports (nn/conf/memory parity)."""

from .gradient_check import check_model_gradients
from .memory import (LayerMemoryReport, NetworkMemoryReport,
                     compiled_memory_report, memory_report)

__all__ = ["LayerMemoryReport", "NetworkMemoryReport", "check_model_gradients",
           "compiled_memory_report", "memory_report"]
