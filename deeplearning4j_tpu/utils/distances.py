"""Shared pairwise-distance kernels (used by knn, kmeans, t-SNE).

One implementation of the MXU-friendly squared-euclidean identity
``||a-b||^2 = ||a||^2 - 2ab + ||b||^2`` so clamp/precision behavior stays
consistent across every consumer.
"""

from __future__ import annotations

import jax.numpy as jnp


def pairwise_sq_dists(a, b=None):
    """Squared euclidean distances (N,M) between rows of a (N,D) and b (M,D);
    b=None means b=a. Clamped at 0 (the identity can go slightly negative in
    float32)."""
    if b is None:
        b = a
    cross = a @ b.T
    d2 = (jnp.sum(a * a, -1, keepdims=True) - 2.0 * cross
          + jnp.sum(b * b, -1)[None, :])
    return jnp.maximum(d2, 0.0)
