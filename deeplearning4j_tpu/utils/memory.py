"""Memory reports — analytical per-layer memory estimation before running.

Reference parity: ``nn/conf/memory/LayerMemoryReport.java`` /
``NetworkMemoryReport.java`` / ``MemoryReport.java`` (SURVEY.md §2.1): DL4J
estimates params + activations + workspace bytes per layer analytically.

TPU redesign: the analytical path is the same arithmetic over our shape
inference; on top of it, ``compiled_memory_report`` asks XLA itself
(``jax.stages.Compiled.memory_analysis()``) for the *true* compiled footprint
— temp buffers, fused intermediates, and rematerialisation included, which
the reference could never see through its per-op dispatch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ..nn.model import Graph, Sequential, _layer_key

_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "float64": 8}


@dataclass
class LayerMemoryReport:
    """LayerMemoryReport.java equivalent — one layer's analytic footprint."""

    name: str
    layer_type: str
    input_shape: tuple
    output_shape: tuple
    param_count: int
    param_bytes: int
    activation_bytes_per_example: int

    def total_bytes(self, batch_size: int, training: bool = True) -> int:
        act = self.activation_bytes_per_example * batch_size
        # training keeps params + grads + activations for backward
        mult = 2 if training else 1
        return self.param_bytes * mult + act * mult


@dataclass
class NetworkMemoryReport:
    """NetworkMemoryReport.java equivalent."""

    layers: List[LayerMemoryReport]
    model_name: str = "network"
    dtype: str = "float32"

    @property
    def total_param_count(self) -> int:
        return sum(l.param_count for l in self.layers)

    @property
    def total_param_bytes(self) -> int:
        return sum(l.param_bytes for l in self.layers)

    def total_bytes(self, batch_size: int, training: bool = True,
                    optimizer_state_multiplier: int = 2) -> int:
        """Estimated bytes for one step. ``optimizer_state_multiplier``: Adam
        keeps 2 extra param-sized buffers, SGD+momentum 1, plain SGD 0."""
        layer_total = sum(l.total_bytes(batch_size, training) for l in self.layers)
        opt = self.total_param_bytes * optimizer_state_multiplier if training else 0
        return layer_total + opt

    def to_string(self, batch_size: int = 32) -> str:
        lines = [f"Memory report: {self.model_name} (dtype={self.dtype}, batch={batch_size})",
                 f"{'layer':<24}{'type':<24}{'params':>12}{'param MB':>10}{'act KB/ex':>11}"]
        for l in self.layers:
            lines.append(f"{l.name:<24}{l.layer_type:<24}{l.param_count:>12}"
                         f"{l.param_bytes / 1e6:>10.2f}{l.activation_bytes_per_example / 1e3:>11.1f}")
        lines.append(f"Total params: {self.total_param_count} "
                     f"({self.total_param_bytes / 1e6:.1f} MB); "
                     f"est. training step: {self.total_bytes(batch_size) / 1e6:.1f} MB")
        return "\n".join(lines)


def memory_report(model) -> NetworkMemoryReport:
    """Analytic report from config shape inference (getMemoryReport parity)."""
    bpe = _DTYPE_BYTES.get(model.config.dtype, 4)
    reports = []
    if isinstance(model, Sequential):
        for i, layer in enumerate(model.layers):
            in_s = model.layer_input_shape(i)
            out_s = layer.output_shape(in_s)
            n = layer.param_count(in_s) if layer.has_params() else 0
            reports.append(LayerMemoryReport(
                name=_layer_key(i, layer), layer_type=type(layer).__name__,
                input_shape=tuple(in_s), output_shape=tuple(out_s),
                param_count=n, param_bytes=n * bpe,
                activation_bytes_per_example=int(np.prod(out_s)) * bpe))
    elif isinstance(model, Graph):
        for name in model.topo_order:
            node = model.nodes[name]
            out_s = model._shapes[name]
            in_s = model._shapes[node.inputs[0]]
            n = node.spec.param_count(in_s) if node.is_layer() and node.spec.has_params() else 0
            reports.append(LayerMemoryReport(
                name=name, layer_type=type(node.spec).__name__,
                input_shape=tuple(in_s), output_shape=tuple(out_s),
                param_count=n, param_bytes=n * bpe,
                activation_bytes_per_example=int(np.prod(out_s)) * bpe))
    else:
        raise TypeError(f"unsupported model type {type(model)}")
    return NetworkMemoryReport(reports, model_name=type(model).__name__,
                               dtype=model.config.dtype)


def compiled_memory_report(fn, *example_args) -> Dict[str, Any]:
    """True XLA-compiled footprint of a jitted function — what the reference's
    analytic estimate approximates. Returns bytes by category."""
    lowered = jax.jit(fn).lower(*example_args)
    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    if ma is None:
        return {"available": False}
    return {
        "available": True,
        "generated_code_bytes": getattr(ma, "generated_code_size_in_bytes", 0),
        "argument_bytes": getattr(ma, "argument_size_in_bytes", 0),
        "output_bytes": getattr(ma, "output_size_in_bytes", 0),
        "temp_bytes": getattr(ma, "temp_size_in_bytes", 0),
        "alias_bytes": getattr(ma, "alias_size_in_bytes", 0),
    }
