"""Numerical gradient checking — parity with ``gradientcheck/GradientCheckUtil.java``
(626 LoC), the reference's universal layer-correctness oracle (16 suites).

In the TPU build, analytic gradients come from ``jax.grad`` through the whole
jitted network; this utility validates them against central finite differences
on the params pytree, mirroring GradientCheckUtil's per-parameter loop but
vectorized where possible.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def check_gradients(loss_fn: Callable, params, *, eps: float = 1e-4,
                    rtol: float = 1e-2, atol: float = 1e-4,
                    max_checks_per_param: int = 24, seed: int = 0,
                    verbose: bool = False) -> bool:
    """Compare jax.grad(loss_fn)(params) against central finite differences.

    loss_fn: pure scalar function of the params pytree (data closed over).
    Checks up to ``max_checks_per_param`` random coordinates of each leaf
    (GradientCheckUtil checks every coordinate; sampling keeps TPU/CPU test
    time bounded at equal confidence for smooth losses).
    """
    params = jax.tree.map(lambda a: jnp.asarray(a, jnp.float64)
                          if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a, params)
    loss_fn = jax.jit(loss_fn)  # one compile; FD evals below hit the cache
    analytic = jax.jit(jax.grad(loss_fn))(params)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    grad_leaves = jax.tree_util.tree_leaves(analytic)
    rng = np.random.default_rng(seed)
    ok = True
    for li, (leaf, g) in enumerate(zip(leaves, grad_leaves)):
        flat = np.asarray(leaf).ravel()
        n = flat.size
        idxs = rng.choice(n, size=min(n, max_checks_per_param), replace=False)
        g_flat = np.asarray(g).ravel()
        for idx in idxs:
            bumped_p = flat.copy()
            bumped_p[idx] += eps
            bumped_m = flat.copy()
            bumped_m[idx] -= eps

            def rebuild(new_flat):
                new_leaves = list(leaves)
                new_leaves[li] = jnp.asarray(new_flat.reshape(leaf.shape), leaf.dtype)
                return jax.tree_util.tree_unflatten(treedef, new_leaves)

            f_p = float(loss_fn(rebuild(bumped_p)))
            f_m = float(loss_fn(rebuild(bumped_m)))
            numeric = (f_p - f_m) / (2 * eps)
            a = float(g_flat[idx])
            denom = max(abs(a), abs(numeric), 1e-8)
            rel = abs(a - numeric) / denom
            if abs(a - numeric) > atol and rel > rtol:
                ok = False
                if verbose:
                    print(f"GRADIENT MISMATCH leaf={li} idx={idx} analytic={a:.6g} numeric={numeric:.6g} rel={rel:.3g}")
    return ok


def check_model_gradients(model, params, state, x, y, *, mask=None, **kw) -> bool:
    """Gradient-check a Sequential/Graph score function at (x, y)."""
    from ..nn.model import Sequential

    mask_kw = {}
    if mask is not None:
        mask_kw = {"mask": mask} if isinstance(model, Sequential) else {"masks": mask}

    def loss(p):
        l, _ = model.score(p, state, x, y, training=False, **mask_kw)
        return l

    return check_gradients(loss, params, **kw)
