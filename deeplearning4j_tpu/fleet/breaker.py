"""Per-model circuit breaker: stop queueing doomed work.

When a model's serving path is failing deterministically — its page-in
transfers die, its engine 500s every batch — admitting more traffic just
burns queue slots, device time, and client timeouts on requests that
cannot succeed. The classic three-state breaker cuts that off:

- **closed** (normal): requests flow; *server-side* failures count.
- **open**: after ``failure_threshold`` consecutive failures, requests
  are refused instantly with a typed :class:`CircuitOpenError` (HTTP 503
  + ``Retry-After`` = time until the next probe). No page-in, no queue.
- **half-open**: after ``reset_s``, exactly ONE probe request is let
  through. Success closes the breaker; failure re-opens it for another
  ``reset_s``.

Only failures that indicate the *model's serving path* is broken count
(internal errors, worker stalls, exhausted page-in retries): client
errors, quota sheds, and queue-full backpressure do not — tripping a
breaker on overload would amplify the overload into an outage (that
discipline lives in :meth:`~.registry.FleetRegistry._breaker_counts`).

The clock is injectable, so open→half-open→closed is testable on a
simulated timeline (a satellite requirement of this PR). State is
exported as ``fleet_breaker_state{model}`` (0 closed / 1 half-open /
2 open) and transitions as ``fleet_breaker_transitions_total{model,to}``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from ..obs import flight as _flight
from ..serve.errors import ShedError

log = logging.getLogger(__name__)

CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"

_STATE_N = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitOpenError(ShedError):
    """Request refused because the model's circuit breaker is open: recent
    requests failed consecutively and the serving path is presumed broken.
    ``retry_after_s`` says when the next half-open probe is due — retrying
    sooner is guaranteed to be refused again (HTTP 503)."""

    cause = "breaker_open"

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = max(float(retry_after_s), 0.0)


class CircuitBreaker:
    """Consecutive-failure breaker with a single half-open probe."""

    def __init__(self, *, failure_threshold: int = 5, reset_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic, metrics=None,
                 model: Optional[str] = None, health=None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if reset_s <= 0:
            raise ValueError("reset_s must be > 0")
        self.failure_threshold = int(failure_threshold)
        self.reset_s = float(reset_s)
        self._clock = clock
        self._metrics = metrics
        self.model = model
        self._health = health
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        self._gauge = None
        if metrics is not None:
            self._gauge = metrics.gauge(
                "fleet_breaker_state",
                {"model": model} if model is not None else None,
                help="circuit breaker state: 0=closed 1=half_open 2=open")
            self._gauge.set(0)

    # --------------------------------------------------------------- plumbing
    def _transition_locked(self, to: str) -> None:
        if to == self._state:
            return
        self._state = to
        if self._gauge is not None:
            self._gauge.set(_STATE_N[to])
        if self._metrics is not None:
            labels = {"to": to}
            if self.model is not None:
                labels["model"] = self.model
            self._metrics.counter(
                "fleet_breaker_transitions_total", labels,
                help="circuit breaker state transitions").inc()
        if _flight.ACTIVE is not None:
            _flight.ACTIVE.record_event("breaker", to,
                                        model=self.model or "<model>",
                                        failures=self._failures)
        cause = f"breaker_open:{self.model or 'model'}"
        if self._health is not None:
            # open AND half-open keep readiness off: the model is not
            # healthy until a probe has actually succeeded
            if to == CLOSED:
                self._health.clear(cause)
            else:
                self._health.degrade(cause)
        log.log(logging.WARNING if to != CLOSED else logging.INFO,
                "breaker %s -> %s", self.model or "<model>", to)

    def state(self) -> str:
        with self._lock:
            return self._state

    # ---------------------------------------------------------------- surface
    def allow(self) -> None:
        """Gate one request. Raises :class:`CircuitOpenError` when open (or
        when half-open with the probe slot already taken); lets exactly one
        probe through per half-open window."""
        with self._lock:
            if self._state == OPEN:
                remaining = self._opened_at + self.reset_s - self._clock()
                if remaining > 0:
                    raise CircuitOpenError(
                        f"model {self.model or '<model>'!s} breaker is open "
                        f"({self._failures} consecutive failures); next "
                        f"probe in {remaining:.1f}s",
                        retry_after_s=remaining)
                self._transition_locked(HALF_OPEN)
                self._probing = False
            if self._state == HALF_OPEN:
                if self._probing:
                    raise CircuitOpenError(
                        f"model {self.model or '<model>'!s} breaker is "
                        f"half-open with a probe in flight",
                        retry_after_s=self.reset_s)
                self._probing = True  # this caller is the probe

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != CLOSED:
                self._transition_locked(CLOSED)

    def record_ignored(self) -> None:
        """A gated request finished with a client-side outcome (quota,
        bad request, client deadline): release the half-open probe slot
        without counting for or against the breaker."""
        with self._lock:
            self._probing = False

    def record_failure(self) -> None:
        opened = False
        with self._lock:
            self._probing = False
            if self._state == HALF_OPEN:
                # failed probe: straight back to open, fresh window
                self._opened_at = self._clock()
                self._transition_locked(OPEN)
                opened = True
            else:
                self._failures += 1
                if self._state == CLOSED \
                        and self._failures >= self.failure_threshold:
                    self._opened_at = self._clock()
                    self._transition_locked(OPEN)
                    opened = True
        if opened and _flight.ACTIVE is not None:
            # the dump (file I/O) happens outside the gating lock
            _flight.ACTIVE.dump("breaker_open")

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state, "failures": self._failures,
                    "threshold": self.failure_threshold,
                    "reset_s": self.reset_s}
