"""Multi-model, multi-tenant fleet serving (PAPER.md north star: many
heterogeneous workloads multiplexed over fixed accelerator memory).

Layering over :mod:`~..serve`:

- :mod:`~.tenants` — per-tenant token-bucket quotas + SLO deadline
  classes; typed :class:`QuotaError` sheds (HTTP 429)
- :mod:`~.pager`  — LRU paging of model weights host↔HBM under a byte
  budget, with the hot-swap lease-drain discipline on eviction
- :mod:`~.registry` — :class:`FleetRegistry` of named models, each its
  own ModelRegistry/ServeEngine/ContinuousBatcher when resident
- :mod:`~.http` — the routed front door
  (``/v1/models/{name}/predict|generate``, ``X-Tenant``, ``/v1/fleet``)

Attach a shared ``aot_store`` so a page-in warms executables from disk
instead of recompiling — activation in seconds, zero traces.
"""

from .http import FleetServer
from .pager import WeightPager
from .registry import FleetEntry, FleetRegistry, FleetResult, \
    UnknownModelError
from .tenants import (DEFAULT_SLO_CLASSES, QuotaError, SLOClass, TenantTable,
                      TokenBucket)

__all__ = ["DEFAULT_SLO_CLASSES", "FleetEntry", "FleetRegistry",
           "FleetResult", "FleetServer", "QuotaError", "SLOClass",
           "TenantTable", "TokenBucket", "UnknownModelError", "WeightPager"]
