"""Multi-model, multi-tenant fleet serving (PAPER.md north star: many
heterogeneous workloads multiplexed over fixed accelerator memory).

Layering over :mod:`~..serve`:

- :mod:`~.tenants` — per-tenant token-bucket quotas + SLO deadline
  classes; typed :class:`QuotaError` sheds (HTTP 429)
- :mod:`~.pager`  — LRU paging of model weights host↔HBM under a byte
  budget, with the hot-swap lease-drain discipline on eviction and
  bounded-retry page-in transfers (typed :class:`PageInError` on
  exhaustion)
- :mod:`~.breaker` — per-model circuit breakers: consecutive server-side
  failures open the circuit and requests shed instantly with
  :class:`CircuitOpenError` (503 + ``Retry-After``) until a half-open
  probe succeeds
- :mod:`~.registry` — :class:`FleetRegistry` of named models, each its
  own ModelRegistry/ServeEngine/ContinuousBatcher when resident; owns the
  fleet's :class:`~..serve.health.Health` state machine and (optional)
  :class:`~..serve.watchdog.Watchdog`
- :mod:`~.http` — the routed front door
  (``/v1/models/{name}/predict|generate``, ``X-Tenant``, ``/v1/fleet``)

Attach a shared ``aot_store`` so a page-in warms executables from disk
instead of recompiling — activation in seconds, zero traces. Fault
injection for all of the above lives in :mod:`~..chaos`.
"""

from .breaker import CircuitBreaker, CircuitOpenError
from .http import FleetServer
from .pager import PageInError, WeightPager
from .registry import FleetEntry, FleetRegistry, FleetResult, \
    UnknownModelError
from .tenants import (DEFAULT_SLO_CLASSES, QuotaError, SLOClass, TenantTable,
                      TokenBucket)

__all__ = ["CircuitBreaker", "CircuitOpenError", "DEFAULT_SLO_CLASSES",
           "FleetEntry", "FleetRegistry", "FleetResult", "FleetServer",
           "PageInError", "QuotaError", "SLOClass", "TenantTable",
           "TokenBucket", "UnknownModelError", "WeightPager"]
