"""Per-tenant admission: token-bucket quotas and SLO deadline classes.

The fleet front door multiplexes many tenants over one process, so
admission has to answer two questions *before* any device work is
scheduled: "is this tenant within its rate?" (token bucket) and "how long
is this request allowed to take?" (SLO class). Both answers are cheap —
a float refill and a dict lookup — because an over-quota tenant must be
shed in microseconds, not after a page-in.

- :class:`TokenBucket` — the classic leaky-bucket dual: capacity ``burst``
  tokens, refilled at ``rate_per_s``. ``take`` either debits and admits or
  refuses without blocking. The clock is injectable (``now=``) so the
  no-tenant-exceeds-its-rate property is testable with a simulated clock.
- :class:`SLOClass` — a named deadline tier. The deadline feeds straight
  into the existing engine/batcher deadline machinery
  (``timeout_ms`` -> EDF prefill ordering, dispatch-time expiry), so
  "gold traffic preempts batch traffic" is the *same* mechanism that
  already orders chunked prefills — tenants just pick the tier.
- :class:`TenantTable` — registration + admission. Unknown tenants get a
  default policy (so the front door never 500s on a new ``X-Tenant``),
  and every refusal is a typed :class:`QuotaError` counted on
  ``serve_shed_total{cause="quota",tenant=...}``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, NamedTuple, Optional, Sequence

from ..serve.errors import ShedError


class QuotaError(ShedError):
    """Tenant exceeded its token-bucket rate.

    A quota shed is the tenant's fault, not the server's — HTTP 429, not
    503 — and it carries ``retry_after_s``, the bucket's own estimate of
    when the next token lands, which the front door surfaces as a
    ``Retry-After`` header."""

    cause = "quota"
    http_status = 429

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class SLOClass(NamedTuple):
    """One deadline tier. ``deadline_ms=None`` means no deadline (bulk
    work that should never expire, only yield to deadline-carrying
    traffic under the EDF prefill scheduler)."""

    name: str
    deadline_ms: Optional[float]


DEFAULT_SLO_CLASSES = (
    SLOClass("gold", 1000.0),
    SLOClass("standard", 5000.0),
    SLOClass("batch", None),
)


class TokenBucket:
    """Thread-safe token bucket.

    ``burst`` tokens max, refilled continuously at ``rate_per_s``. The
    timestamp of the first ``take`` anchors the clock, so buckets created
    long before traffic don't start with a phantom backlog of refills
    beyond the burst cap (the cap bounds that anyway; this just keeps the
    math exact for injected clocks that start at 0).
    """

    def __init__(self, rate_per_s: float, burst: float):
        if rate_per_s <= 0 or burst <= 0:
            raise ValueError("rate_per_s and burst must be > 0")
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._t: Optional[float] = None
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        if self._t is None:
            self._t = now
        elapsed = max(now - self._t, 0.0)
        self._tokens = min(self.burst, self._tokens + elapsed * self.rate)
        self._t = now

    def take(self, n: float = 1.0, now: Optional[float] = None) -> bool:
        """Debit ``n`` tokens if available; never blocks."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            self._refill(now)
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def wait_s(self, n: float = 1.0, now: Optional[float] = None) -> float:
        """Seconds until ``n`` tokens will be available (0 if they already
        are) — the honest Retry-After for a quota shed."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            self._refill(now)
            deficit = n - self._tokens
            return max(deficit, 0.0) / self.rate

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


class TenantPolicy(NamedTuple):
    rate_per_s: float
    burst: float
    slo: SLOClass


class TenantTable:
    """Tenant registration + per-request admission for the fleet.

    ``admit`` is the single choke point: it debits the tenant's bucket,
    counts the request, and returns the tenant's :class:`SLOClass` (whose
    deadline the caller forwards as ``timeout_ms``). Refusal raises
    :class:`QuotaError` and bumps
    ``serve_shed_total{cause="quota",tenant=...}`` (plus ``model=`` when
    the caller names one), so one scrape shows exactly who is being
    throttled and on what.
    """

    def __init__(self, metrics=None, *,
                 slo_classes: Sequence[SLOClass] = DEFAULT_SLO_CLASSES,
                 default_rate_per_s: float = 100.0,
                 default_burst: float = 50.0,
                 default_slo: str = "standard"):
        self._classes: Dict[str, SLOClass] = {c.name: c for c in slo_classes}
        if default_slo not in self._classes:
            raise ValueError(f"default_slo {default_slo!r} is not one of "
                             f"{sorted(self._classes)}")
        self._default = TenantPolicy(float(default_rate_per_s),
                                     float(default_burst),
                                     self._classes[default_slo])
        self._metrics = metrics
        self._lock = threading.Lock()
        self._policies: Dict[str, TenantPolicy] = {}
        self._buckets: Dict[str, TokenBucket] = {}
        self._admitted: Dict[str, int] = {}
        self._shed: Dict[str, int] = {}

    def slo_class(self, name: str) -> SLOClass:
        return self._classes[name]

    def register(self, tenant: str, *, rate_per_s: float,
                 burst: Optional[float] = None,
                 slo: str = "standard") -> None:
        """(Re-)register a tenant's policy. ``burst`` defaults to one
        second's worth of rate (min 1 token)."""
        if slo not in self._classes:
            raise ValueError(f"unknown SLO class {slo!r}; have "
                             f"{sorted(self._classes)}")
        if burst is None:
            burst = max(rate_per_s, 1.0)
        with self._lock:
            self._policies[tenant] = TenantPolicy(
                float(rate_per_s), float(burst), self._classes[slo])
            self._buckets[tenant] = TokenBucket(rate_per_s, burst)

    def _bucket_for(self, tenant: str) -> tuple:
        with self._lock:
            pol = self._policies.get(tenant, self._default)
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = TokenBucket(
                    pol.rate_per_s, pol.burst)
            return pol, bucket

    def admit(self, tenant: str, model: Optional[str] = None,
              cost: float = 1.0, now: Optional[float] = None) -> SLOClass:
        """Debit ``cost`` tokens for one request; return the tenant's SLO
        class, or raise :class:`QuotaError` with the bucket's refill time
        as ``retry_after_s``."""
        pol, bucket = self._bucket_for(tenant)
        if not bucket.take(cost, now=now):
            with self._lock:
                self._shed[tenant] = self._shed.get(tenant, 0) + 1
            if self._metrics is not None:
                labels = {"cause": "quota", "tenant": tenant}
                if model is not None:
                    labels["model"] = model
                self._metrics.counter(
                    "serve_shed_total", labels,
                    help="requests refused at admission, by cause").inc()
            raise QuotaError(
                f"tenant {tenant!r} over quota "
                f"({pol.rate_per_s:g} req/s, burst {pol.burst:g})",
                retry_after_s=bucket.wait_s(cost, now=now))
        with self._lock:
            self._admitted[tenant] = self._admitted.get(tenant, 0) + 1
        if self._metrics is not None:
            self._metrics.counter(
                "fleet_tenant_requests_total", {"tenant": tenant},
                help="requests admitted past tenant quota, by tenant").inc()
        return pol.slo

    def stats(self) -> dict:
        """Per-tenant policy + admission counters (the /v1/fleet view)."""
        with self._lock:
            tenants = set(self._policies) | set(self._buckets) \
                | set(self._admitted) | set(self._shed)
            out = {}
            for t in sorted(tenants):
                pol = self._policies.get(t, self._default)
                out[t] = {"rate_per_s": pol.rate_per_s, "burst": pol.burst,
                          "slo": pol.slo.name,
                          "deadline_ms": pol.slo.deadline_ms,
                          "admitted": self._admitted.get(t, 0),
                          "shed": self._shed.get(t, 0)}
            return out
