"""LRU weight paging: host ↔ HBM under a byte budget.

A fleet holds N models whose summed weights exceed device memory; the
pager decides which subset is *resident*. It is deliberately dumb about
models — an entry is anything exposing ``name``, ``weight_bytes``,
``activate()`` (host copy -> device, engines up) and ``deactivate()``
(drain leases, pull weights to host, drop device refs) — which keeps the
eviction policy testable with stub entries and keeps all the JAX in
:mod:`~.registry`.

Correctness properties the locking enforces:

- **Lease-drain eviction.** A victim's ``deactivate()`` runs the same
  drain discipline as hot-swap (``ServeEngine.shutdown(drain=True)``):
  every in-flight batch leased against the victim's registry retires
  *before* its device params are dropped. No batch ever loses its params
  mid-forward; eviction blocks on live leases by construction.
- **Single page-in per model.** Concurrent requests for a cold model
  dedupe on a loading set: one thread pages in, the rest wait on the
  condition variable.
- **Traffic to resident models never stalls on a page-in.** Victim
  selection happens under the pager lock (fast), but the expensive part —
  drain + device transfer + AOT warm — runs *outside* it. Residents are
  reserved by moving victims out of the resident map first, so their
  budget bytes are committed to the incoming model before anything slow
  happens.
- **Impossible requests are typed.** A single model larger than the whole
  budget sheds with :class:`~..serve.errors.CapacityError` — queueing
  can't help.

Budget accounting covers model *weights* only; KV pools and activations
are owned by each model's batcher/engine and sized at activation.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import List, Optional

from ..chaos import faults as _faults
from ..chaos.retry import RetryPolicy
from ..obs import profile as _prof
from ..obs import reqtrace as _rt
from ..serve.errors import CapacityError, ServeError


class PageInError(ServeError):
    """Paging a model's weights onto the device failed even after bounded
    retries. The model is not resident; the reservation was rolled back, so
    a later request will retry the transfer from scratch (HTTP 503)."""

    cause = "page_in_failed"
    http_status = 503


class WeightPager:
    """LRU resident-set manager over duck-typed fleet entries."""

    def __init__(self, budget_bytes: Optional[int] = None, metrics=None,
                 retry: Optional[RetryPolicy] = None):
        if budget_bytes is not None and budget_bytes <= 0:
            raise ValueError("budget_bytes must be positive (or None for "
                             "unbounded)")
        self.budget_bytes = int(budget_bytes) if budget_bytes else None
        self._metrics = metrics
        # host->HBM transfers are retried with backoff: a transient DMA /
        # allocator hiccup shouldn't shed the request when the next attempt
        # would land (injectable for tests; chaos smoke relies on this)
        self._retry = retry if retry is not None else RetryPolicy(
            attempts=3, base_s=0.05, cap_s=1.0, metrics=metrics)
        self._cond = threading.Condition()
        self._resident: "OrderedDict[str, object]" = OrderedDict()
        self._used = 0
        # bytes actually reserved per resident model — charged at page-in
        # and released at page-out, so a publish that resizes weights while
        # resident can never skew the budget arithmetic
        self._charged: dict = {}
        self._loading: set = set()
        self._page_ins = 0
        self._page_outs = 0
        if metrics is not None:
            metrics.gauge("fleet_hbm_budget_bytes",
                          help="weight-paging HBM budget (0 = unbounded)"
                          ).set(self.budget_bytes or 0)
            self._g_resident = metrics.gauge(
                "fleet_resident_bytes",
                help="bytes of model weights currently resident")
            self._g_models = metrics.gauge(
                "fleet_models_resident", help="models currently resident")
            self._h_page_in = metrics.histogram(
                "fleet_page_in_seconds",
                help="wall time to page one model in (drain victims + "
                     "device transfer + executable warm)")
        else:
            self._g_resident = self._g_models = self._h_page_in = None

    # ------------------------------------------------------------- accounting
    def _gauges(self) -> None:
        if self._g_resident is not None:
            self._g_resident.set(self._used)
            self._g_models.set(len(self._resident))

    def _count(self, name: str, model: str, help_: str) -> None:
        if self._metrics is not None:
            self._metrics.counter(name, {"model": model}, help=help_).inc()

    # --------------------------------------------------------------- ensure
    def ensure(self, entry) -> None:
        """Make ``entry`` resident, evicting LRU entries as needed.

        Fast path (already resident): one lock, an LRU touch. Miss path:
        claim victims + bytes under the lock, then drain/deactivate the
        victims and activate the entry outside it.
        """
        need = int(entry.weight_bytes)
        if self.budget_bytes is not None and need > self.budget_bytes:
            raise CapacityError(
                f"model {entry.name!r} needs {need} bytes but the fleet "
                f"HBM budget is {self.budget_bytes} — it can never fit")
        victims: List[object] = []
        with self._cond:
            while True:
                if entry.name in self._resident:
                    self._resident.move_to_end(entry.name)  # LRU touch
                    return
                if entry.name in self._loading:
                    # another thread is paging this model in; wait for it
                    self._cond.wait()
                    continue
                if self.budget_bytes is not None:
                    while self._resident \
                            and self._used + need > self.budget_bytes:
                        name, v = self._resident.popitem(last=False)  # LRU
                        self._used -= self._charged.pop(name)
                        victims.append(v)
                    if self._used + need > self.budget_bytes:
                        # the remaining bytes are reservations held by other
                        # in-flight page-ins; put any victims back and wait
                        # for a load to land, then re-evaluate
                        for v in victims:
                            self._resident[v.name] = v
                            charge = int(v.weight_bytes)
                            self._charged[v.name] = charge
                            self._used += charge
                        victims.clear()
                        self._cond.wait(0.05)
                        continue
                self._loading.add(entry.name)
                self._charged[entry.name] = need
                self._used += need  # reserve before the slow work
                self._gauges()
                break
        ok = False
        try:
            t0 = time.perf_counter()
            with _rt.span("fleet.page_in", model=entry.name,
                          victims=len(victims)):
                for v in victims:
                    # lease-drain: completes every in-flight batch on the
                    # victim before its device params drop
                    v.deactivate()
                    self._page_outs += 1
                    self._count("fleet_page_out_total", v.name,
                                "model weight page-outs (HBM -> host)")
                def _transfer():
                    if _faults.ACTIVE is not None:
                        _faults.ACTIVE.hit("fleet.page_in_transfer")
                    entry.activate()

                try:
                    self._retry.call(_transfer, op="fleet.page_in_transfer",
                                     give_up=(CapacityError,))
                except CapacityError:
                    raise
                except Exception as e:  # jaxlint: disable=broad-except
                    raise PageInError(
                        f"paging {entry.name!r} in failed after retries: "
                        f"{e}") from e
            ok = True
            self._page_ins += 1
            self._count("fleet_page_in_total", entry.name,
                        "model weight page-ins (host -> HBM)")
            dt = time.perf_counter() - t0
            if self._h_page_in is not None:
                self._h_page_in.observe(dt)
            if _prof.ACTIVE is not None:
                # measured transfer cost feeds CostProfile.page_in_s
                _prof.ACTIVE.page_in(dt)
        finally:
            with self._cond:
                self._loading.discard(entry.name)
                if ok:
                    self._resident[entry.name] = entry
                else:
                    # activation failed: release the reservation
                    self._used -= self._charged.pop(entry.name, need)
                self._gauges()
                self._cond.notify_all()

    def drop(self, entry) -> None:
        """Deactivate and forget one entry (fleet removal)."""
        with self._cond:
            while entry.name in self._loading:
                self._cond.wait()
            was = self._resident.pop(entry.name, None)
            if was is not None:
                self._used -= self._charged.pop(entry.name)
                self._gauges()
        if was is not None:
            was.deactivate()
            self._page_outs += 1
            self._count("fleet_page_out_total", entry.name,
                        "model weight page-outs (HBM -> host)")
        with self._cond:
            self._cond.notify_all()

    # ---------------------------------------------------------------- stats
    def resident(self) -> List[str]:
        """Resident model names, LRU-first."""
        with self._cond:
            return list(self._resident)

    def stats(self) -> dict:
        with self._cond:
            return {"budget_bytes": self.budget_bytes,
                    "resident_bytes": self._used,
                    "resident": list(self._resident),
                    "loading": sorted(self._loading),
                    "page_ins": self._page_ins,
                    "page_outs": self._page_outs}
