"""FleetRegistry — N named models multiplexed through one process.

Each :class:`FleetEntry` owns the full single-model serving stack when
resident — a :class:`~..serve.registry.ModelRegistry` (generations +
leases + hot-swap), a :class:`~..serve.engine.ServeEngine` (predict), and
a lazily-built :class:`~..serve.continuous.ContinuousBatcher` (generate)
— and shrinks to a host-side numpy weight copy when paged out. The
ground truth for a cold model is host RAM; activation is
``device_put`` + executable warm from the shared ``aot/`` store, so a
page-in costs seconds of transfer, not a recompile.

Generation numbers survive paging: deactivation records
``last generation + 1`` and the next activation's ModelRegistry starts
there (``start_generation``), so "which params answered this request" is
a total order per model across any number of page-out/page-in cycles —
the same purity contract hot-swap gives within one residency.

Request flow (:meth:`FleetRegistry.predict` / :meth:`~.generate`):
tenant admission first (:class:`~.tenants.TenantTable` — an over-quota
tenant is shed before any paging work), then ``pager.ensure`` (resident:
one lock; cold: LRU eviction + activation), then the entry's engine.
A request that loses the race with a concurrent eviction gets the
engine's typed ``ServerClosingError`` and simply retries through the
pager — bounded, because each retry pages the model back in.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, NamedTuple, Optional

import numpy as np

from ..obs.slo import SloBurn
from ..serve.continuous import ContinuousBatcher
from ..serve.engine import ServeEngine
from ..serve.errors import ServeError, ServerClosingError
from ..serve.health import Health
from ..serve.registry import ModelRegistry
from ..serve.watchdog import Watchdog
from .breaker import CircuitBreaker
from .pager import WeightPager
from .tenants import TenantTable

_EVICTION_RETRIES = 4

# ServeError causes that count against a model's circuit breaker: server-side
# breakage only. Quota/capacity/queue-full sheds and client deadlines are
# load signals, not path failures — tripping a breaker on them would turn an
# overload into an outage.
_BREAKER_CAUSES = frozenset({"internal", "page_in_failed", "worker_stall",
                             "worker_dead", "drain_timeout"})

# ServeError causes that do not consume error budget: the *client* (or its
# quota) failed, not our serving path. Everything else after admission —
# deadline misses included — is a bad event for the tenant's SLO class.
# "client_gone" is the client dropping its own socket mid-stream.
_SLO_EXCLUDED = frozenset({"quota", "over_capacity", "bad_request",
                           "client_gone"})


class UnknownModelError(ServeError):
    """No model with that name in the fleet (HTTP 404)."""

    cause = "unknown_model"
    http_status = 404


class FleetResult(NamedTuple):
    """One predict answer: the output rows and (when the request rode a
    single engine batch) the params generation that produced them."""

    output: np.ndarray
    generation: Optional[int]


def _tree_bytes(*trees) -> int:
    import jax

    return sum(int(leaf.nbytes) for tree in trees
               for leaf in jax.tree.leaves(tree))


class FleetEntry:
    """One named model: host weight copy + (when resident) serving stack."""

    def __init__(self, name: str, model, params, state=None, *,
                 version: str = "v0", input_dtype=np.float32, metrics=None,
                 aot_store=None, strict_aot: bool = False,
                 engine_opts: Optional[dict] = None,
                 gen_opts: Optional[dict] = None):
        import jax

        self.name = name
        self.model = model
        self.input_dtype = input_dtype
        self.metrics = metrics
        self.aot_store = aot_store
        # strict page-ins: activation loads executables from the prebuilt
        # store or fails typed (AotTraceError) — a paged-in model must
        # never trace its way back into residency
        self.strict_aot = bool(strict_aot)
        if self.strict_aot and aot_store is None:
            raise ValueError(f"model {name!r}: strict_aot=True requires "
                             "a shared aot_store")
        self.engine_opts = dict(engine_opts or {})
        self.gen_opts = dict(gen_opts or {})
        self.version = version
        # RLock held across the WHOLE of activate()/deactivate(): the pager
        # may start re-activating a victim (new traffic arrived) while its
        # drain is still completing — the lock serializes the lifecycles so
        # the new stack always starts from the drained host copy
        self._lock = threading.RLock()
        self._host_params = jax.tree.map(np.asarray, params)
        self._host_state = jax.tree.map(
            np.asarray, state if state is not None else {})
        self.weight_bytes = _tree_bytes(self._host_params, self._host_state)
        self._next_generation = 1
        self._registry: Optional[ModelRegistry] = None
        self._engine: Optional[ServeEngine] = None
        self._batcher: Optional[ContinuousBatcher] = None
        self._had_batcher = False

    # ------------------------------------------------------------- lifecycle
    @property
    def resident(self) -> bool:
        with self._lock:
            return self._engine is not None

    def activate(self) -> None:
        """Host copy -> device, registry/engine up, executables warmed.
        Called by the pager with residency bytes already reserved."""
        import jax
        import jax.numpy as jnp

        with self._lock:
            if self._engine is not None:
                return
            params = jax.tree.map(jnp.asarray, self._host_params)
            state = jax.tree.map(jnp.asarray, self._host_state)
            self._registry = ModelRegistry(
                params, state, version=self.version, metrics=self.metrics,
                model=self.name, start_generation=self._next_generation)
            self._engine = ServeEngine(
                self.model, registry=self._registry, metrics=self.metrics,
                aot_store=self.aot_store, strict_aot=self.strict_aot,
                model_name=self.name, **self.engine_opts)
            if self.aot_store is not None:
                # store hit on every re-activation: page-in never re-traces
                # (strict: an uncovered signature fails the page-in typed)
                self._engine.warm(self.input_dtype)
            if self._had_batcher:
                # the model served generate traffic last residency; rebuild
                # eagerly so paged-in decode is warm before the next request
                self._build_batcher_locked()

    # Deliberate: the entry RLock is held across the whole drain (see the
    # __init__ comment) so a re-activation can never interleave with a
    # half-finished eviction. The join/wait inside shutdown(drain=True) is
    # the contract, not an accident — sanctioned, with eyes open.
    def deactivate(self) -> None:  # jaxlint: sanction=blocking-call-under-lock
        """Lease-drain, pull current weights to host, drop device refs.

        This is the hot-swap drain discipline applied to eviction:
        ``shutdown(drain=True)`` completes every admitted batch/generation
        against the old device params before they are released, so no
        in-flight work ever loses its params. The *current* registry
        snapshot (including any generations published while resident) is
        what survives as the host copy."""
        import jax

        with self._lock:
            if self._engine is None:
                return
            self._engine.shutdown(drain=True)
            if self._batcher is not None:
                self._batcher.shutdown(drain=True)
            snap = self._registry.current()
            self._host_params = jax.tree.map(np.asarray, snap.params)
            self._host_state = jax.tree.map(np.asarray, snap.state)
            self.weight_bytes = _tree_bytes(self._host_params,
                                            self._host_state)
            self.version = snap.version
            self._next_generation = snap.generation + 1
            self._registry = None
            self._engine = None
            self._batcher = None

    # --------------------------------------------------------------- serving
    # Sanctioned: "not resident" is an internal eviction-race signal — the
    # fleet facade's _EVICTION_RETRIES loop swallows it and pages the model
    # back in; only an exhausted retry escapes, and the HTTP boundary
    # counts that on fleet_http_errors_total{endpoint,code}. Counting at
    # the raise would overcount every won race.
    def engine(self) -> ServeEngine:  # jaxlint: sanction=uncounted-shed
        with self._lock:
            if self._engine is None:
                raise ServerClosingError(
                    f"model {self.name!r} is not resident")
            return self._engine

    def _build_batcher_locked(self) -> None:
        self._batcher = ContinuousBatcher(
            self.model, registry=self._registry, metrics=self.metrics,
            aot_store=self.aot_store, strict_aot=self.strict_aot,
            model_name=self.name, **self.gen_opts)
        self._had_batcher = True

    # Sanctioned: same eviction-race signal as engine() above.
    def batcher(self) -> ContinuousBatcher:  # jaxlint: sanction=uncounted-shed
        with self._lock:
            if self._engine is None:
                raise ServerClosingError(
                    f"model {self.name!r} is not resident")
            if self._batcher is None:
                self._build_batcher_locked()
            return self._batcher

    # Deliberate: publish-with-drain waits out in-flight leases while the
    # entry RLock serializes it against eviction/re-activation — same
    # lifecycle contract as deactivate(). Sanctioned, not overlooked.
    def publish(self, params, state=None, version: Optional[str] = None,  # jaxlint: sanction=blocking-call-under-lock
                drain: bool = True) -> int:
        """Hot-swap this model's weights; returns the new generation.
        Resident: the full registry publish (warmers precompile the
        candidate, atomic flip, lease drain). Cold: the host copy and
        generation counter advance so the next activation serves the new
        weights under the right generation number."""
        import jax
        import jax.numpy as jnp

        with self._lock:
            if self._registry is not None:
                snap = self._registry.publish(
                    jax.tree.map(jnp.asarray, params),
                    state=(jax.tree.map(jnp.asarray, state)
                           if state is not None else None),
                    version=version, drain=drain)
                self.version = snap.version
                return snap.generation
            self._host_params = jax.tree.map(np.asarray, params)
            if state is not None:
                self._host_state = jax.tree.map(np.asarray, state)
            self.weight_bytes = _tree_bytes(self._host_params,
                                            self._host_state)
            gen = self._next_generation
            self.version = version if version is not None else f"v{gen - 1}"
            self._next_generation = gen + 1
            return gen

    def queue_depth(self) -> int:
        """Requests waiting in this entry's resident stack (0 when cold)."""
        with self._lock:
            if self._engine is None:
                return 0
            depth = self._engine.queue_depth()
            if self._batcher is not None:
                depth += self._batcher.queue_depth()
            return depth

    def kv_utilization(self) -> float:
        """Fraction of this entry's KV blocks in use — 0.0 when cold,
        predict-only, or the batcher runs the dense (non-paged) path."""
        with self._lock:
            if self._batcher is None:
                return 0.0
            stats = self._batcher.kv_block_stats()
        total = int(stats.get("blocks_total") or 0)
        return (int(stats.get("blocks_used") or 0) / total) if total else 0.0

    def components(self) -> list:
        """Watchdog view: ``(name, worker-owning component)`` pairs for the
        currently-resident serving stack (empty when paged out)."""
        with self._lock:
            if self._engine is None:
                return []
            comps = [(f"{self.name}.engine", self._engine)]
            if self._batcher is not None:
                comps.append((f"{self.name}.batcher", self._batcher))
            return comps

    def info(self) -> dict:
        with self._lock:
            resident = self._engine is not None
            out = {
                "resident": resident,
                "version": self.version,
                "generation": (self._registry.generation if resident
                               else self._next_generation - 1),
                "weight_bytes": int(self.weight_bytes),
                "generate_ready": self._batcher is not None,
            }
            batcher = self._batcher
        if batcher is not None and batcher.kv == "paged":
            # sharing picture per tenant-facing model: block usage,
            # prefix-cache hit rates, CoW/fork counts (router placement
            # and dashboards read this off the heartbeat)
            out["kv"] = batcher.kv_block_stats()
        return out


class FleetRegistry:
    """Named models + tenant admission + weight paging, one front door.

    ``hbm_budget_bytes`` caps summed resident weights (None = unbounded);
    ``aot_store`` is shared across models (cache keys include the model's
    architecture fingerprint, so entries never collide). Per-model
    engine/batcher knobs ride in ``add(engine_opts=..., gen_opts=...)``.
    """

    def __init__(self, *, hbm_budget_bytes: Optional[int] = None,
                 metrics=None, aot_store=None, strict_aot: bool = False,
                 tenants: Optional[TenantTable] = None,
                 breaker_failures: Optional[int] = 5,
                 breaker_reset_s: float = 10.0, breaker_clock=None,
                 watchdog_s: Optional[float] = None,
                 tuned_for: Optional[str] = None):
        from ..obs.metrics import MetricsRegistry

        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.aot_store = aot_store
        # strict_aot applies fleet-wide: every entry's activation (and
        # every page-in after an eviction) must be served by the prebuilt
        # store or fail with a typed AotTraceError — never a trace
        self.strict_aot = bool(strict_aot)
        if self.strict_aot and aot_store is None:
            raise ValueError("strict_aot=True requires a shared aot_store")
        # tuned_for: a workload fingerprint (sim/workload.py). When set, the
        # boot resolves the autotuner's winning knob set for (this runtime,
        # that workload) from the AOT store — the same place the compiled
        # executables come from — and every add() starts from those knobs.
        # A miss (counted on sim_tuned_config_misses_total) means hand-picked
        # defaults, exactly as before.
        self.tuned_config: Optional[dict] = None
        if tuned_for is not None:
            from ..aot.tuned import get_tuned

            self.tuned_config = get_tuned(aot_store, tuned_for,
                                          metrics=self.metrics)
        self.tenants = tenants if tenants is not None \
            else TenantTable(metrics=self.metrics)
        self.pager = WeightPager(hbm_budget_bytes, metrics=self.metrics)
        self._lock = threading.Lock()
        self._entries: Dict[str, FleetEntry] = {}
        self._closing = False
        self.health = Health(metrics=self.metrics, component="fleet")
        # per (model, slo_class) error-budget burn; works with tracing off
        self.slo = SloBurn(metrics=self.metrics)
        # per-model circuit breakers; breaker_failures=None disables them
        self._breaker_failures = breaker_failures
        self._breaker_reset_s = float(breaker_reset_s)
        self._breaker_clock = breaker_clock
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._watchdog: Optional[Watchdog] = None
        if watchdog_s is not None:
            self._watchdog = Watchdog(
                self._watch_components, deadline_s=watchdog_s,
                metrics=self.metrics, health=self.health).start()

    def _watch_components(self) -> list:
        with self._lock:
            entries = list(self._entries.values())
        comps: list = []
        for entry in entries:
            comps.extend(entry.components())
        return comps

    def _breaker(self, name: str) -> Optional[CircuitBreaker]:
        with self._lock:
            return self._breakers.get(name)

    # ------------------------------------------------------------ membership
    def add(self, name: str, model, params=None, state=None, *,
            version: str = "v0", input_dtype=np.float32,
            engine_opts: Optional[dict] = None,
            gen_opts: Optional[dict] = None,
            eager: bool = False) -> FleetEntry:
        """Register a model under ``name``. Weights default to the model's
        own initialized params. ``eager=True`` pages it in immediately;
        otherwise the first request does. With a resolved tuned config
        (``tuned_for=``), its engine/gen groups become the per-model
        defaults — explicit ``engine_opts``/``gen_opts`` keys still win."""
        if self.tuned_config is not None:
            from ..aot.tuned import tuned_group
            from ..serve.continuous import gen_opts_from_config
            from ..serve.engine import ENGINE_KNOBS

            tuned_engine = {
                k: v
                for k, v in tuned_group(self.tuned_config, "engine").items()
                if k in ENGINE_KNOBS}
            engine_opts = {**tuned_engine, **(engine_opts or {})}
            gen_opts = {**gen_opts_from_config(self.tuned_config),
                        **(gen_opts or {})}
        entry = FleetEntry(
            name, model,
            params if params is not None else model.params,
            state if state is not None else model.state,
            version=version, input_dtype=input_dtype, metrics=self.metrics,
            aot_store=self.aot_store, strict_aot=self.strict_aot,
            engine_opts=engine_opts, gen_opts=gen_opts)
        with self._lock:
            if name in self._entries:
                raise ValueError(f"model {name!r} already registered — "
                                 f"publish() hot-swaps weights in place")
            self._entries[name] = entry
            if self._breaker_failures is not None:
                kwargs = {}
                if self._breaker_clock is not None:
                    kwargs["clock"] = self._breaker_clock
                self._breakers[name] = CircuitBreaker(
                    failure_threshold=self._breaker_failures,
                    reset_s=self._breaker_reset_s, metrics=self.metrics,
                    model=name, health=self.health, **kwargs)
        if eager:
            self.pager.ensure(entry)
        return entry

    def remove(self, name: str) -> None:
        with self._lock:
            entry = self._entries.pop(name, None)
            self._breakers.pop(name, None)
        if entry is None:
            raise UnknownModelError(f"no model named {name!r}")
        # a removed model's open breaker must not keep readiness off
        self.health.clear(f"breaker_open:{name}")
        self.pager.drop(entry)

    def get(self, name: str) -> FleetEntry:
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise UnknownModelError(f"no model named {name!r}")
        return entry

    def names(self) -> list:
        with self._lock:
            return sorted(self._entries)

    def queue_depth(self) -> int:
        """Fleet-wide queued work (sum over resident models) — the load
        signal a replica self-reports on each cluster heartbeat."""
        with self._lock:
            entries = list(self._entries.values())
        return sum(e.queue_depth() for e in entries)

    def kv_pressure(self) -> float:
        """Worst KV-block utilization across resident models — the memory
        half of the load signal a replica self-reports on each cluster
        heartbeat (the autoscaler's KV-pressure input)."""
        with self._lock:
            entries = list(self._entries.values())
        return max((e.kv_utilization() for e in entries), default=0.0)

    def ensure(self, name: str) -> FleetEntry:
        """Page a model in without serving a request (prewarm)."""
        entry = self.get(name)
        self.pager.ensure(entry)
        return entry

    # --------------------------------------------------------------- serving
    def _admit(self, tenant: str, name: str,
               timeout_ms: Optional[float]) -> tuple:
        """Tenant admission; returns ``(deadline_ms, slo_class_name)``."""
        slo = self.tenants.admit(tenant, model=name)
        return (timeout_ms if timeout_ms is not None else slo.deadline_ms,
                slo.name)

    def _slo_record(self, name: str, slo_class: Optional[str],
                    exc: Optional[BaseException]) -> None:
        """One admitted request's outcome into the burn accounting.
        ``slo_class`` is None when admission itself refused (quota) —
        nothing to account."""
        if slo_class is None:
            return
        if isinstance(exc, ServeError) and exc.cause in _SLO_EXCLUDED:
            return
        self.slo.record(name, slo_class, good=exc is None)

    @staticmethod
    def _breaker_counts(exc: BaseException) -> bool:
        """Does this failure count against the model's breaker? Server-side
        breakage only — see ``_BREAKER_CAUSES``."""
        if isinstance(exc, ServeError):
            return exc.cause in _BREAKER_CAUSES
        return True

    def _observed(self, br: Optional[CircuitBreaker], fn):
        """Run one gated serving attempt, feeding its outcome back into the
        model's breaker. ``br.allow()`` already passed for this request."""
        if br is None:
            return fn()
        try:
            out = fn()
        except BaseException as e:
            if self._breaker_counts(e):
                br.record_failure()
            else:
                br.record_ignored()
            raise
        br.record_success()
        return out

    def predict(self, name: str, x, *, tenant: str = "anonymous",
                timeout_ms: Optional[float] = None, ctx=None) -> FleetResult:
        """Breaker gate -> tenant admission -> page-in -> engine predict.
        ``timeout_ms`` defaults to the tenant's SLO deadline."""
        entry = self.get(name)
        br = self._breaker(name)
        if br is not None:
            br.allow()  # open breaker refuses before quota/paging work
        slo_cls: list = [None]

        def _serve() -> FleetResult:
            nonlocal timeout_ms
            if ctx is None:
                timeout_ms, slo_cls[0] = self._admit(tenant, name,
                                                     timeout_ms)
            else:
                with ctx.stage("admit", model=name):
                    timeout_ms, slo_cls[0] = self._admit(tenant, name,
                                                         timeout_ms)
                ctx.tenant = tenant
                ctx.slo_class = slo_cls[0]
            x_ = np.asarray(x, entry.input_dtype)
            last: Optional[ServeError] = None
            for _ in range(_EVICTION_RETRIES):
                if ctx is None:
                    self.pager.ensure(entry)
                else:
                    with ctx.stage("page_in_wait", model=name):
                        self.pager.ensure(entry)
                try:
                    eng = entry.engine()
                    if x_.ndim > len(entry.model.input_shape) \
                            and x_.shape[0] <= eng.batch_buckets[-1]:
                        handle = eng.submit(x_, timeout_ms=timeout_ms,
                                            ctx=ctx)
                        return FleetResult(handle.wait(), handle.generation)
                    return FleetResult(
                        eng.predict(x_, timeout_ms=timeout_ms, ctx=ctx),
                        None)
                except ServerClosingError as e:
                    last = e  # lost the race with an eviction: page back in
            raise last

        try:
            out = self._observed(br, _serve)
        except BaseException as e:
            self._slo_record(name, slo_cls[0], e)
            raise
        self._slo_record(name, slo_cls[0], None)
        return out

    def submit_generate(self, name: str, prompt, max_new_tokens: int, *,
                        tenant: str = "anonymous", temperature: float = 1.0,
                        top_k: Optional[int] = None,
                        eos_id: Optional[int] = None,
                        timeout_ms: Optional[float] = None, ctx=None):
        """Admit one generation; returns the batcher's streamable handle.
        The breaker observes the *submission* path (paging + admission into
        the batcher) — a handle that later times out does not count."""
        entry = self.get(name)
        br = self._breaker(name)
        if br is not None:
            br.allow()
        slo_cls: list = [None]

        def _serve():
            nonlocal timeout_ms
            if ctx is None:
                timeout_ms, slo_cls[0] = self._admit(tenant, name,
                                                     timeout_ms)
            else:
                with ctx.stage("admit", model=name):
                    timeout_ms, slo_cls[0] = self._admit(tenant, name,
                                                         timeout_ms)
                ctx.tenant = tenant
                ctx.slo_class = slo_cls[0]
            prompt_ = np.asarray(prompt, np.int32)
            last: Optional[ServeError] = None
            for _ in range(_EVICTION_RETRIES):
                if ctx is None:
                    self.pager.ensure(entry)
                else:
                    with ctx.stage("page_in_wait", model=name):
                        self.pager.ensure(entry)
                try:
                    return entry.batcher().submit(
                        prompt_, max_new_tokens, temperature=temperature,
                        top_k=top_k, eos_id=eos_id, timeout_ms=timeout_ms,
                        ctx=ctx)
                except ServerClosingError as e:
                    last = e
            raise last

        try:
            handle = self._observed(br, _serve)
        except BaseException as e:
            # the submission path itself failed after admission: account it
            self._slo_record(name, slo_cls[0], e)
            raise
        # SLO outcome is decided when the batcher finishes the request —
        # possibly much later, on the decode/watchdog thread
        cls = slo_cls[0]
        handle.set_on_done(lambda r: self._slo_record(name, cls, r.error))
        return handle

    def cancel_generate(self, name: str, handle,
                        cause: str = "client_gone") -> bool:
        """Abandon one streamed generation whose consumer vanished — frees
        its decode slot and KV pages via the batcher's cancel path. Returns
        False when the request already finished (including via a racing
        page-out, which drains in-flight work)."""
        try:
            batcher = self.get(name).batcher()
        except ServeError:
            return False
        return batcher.cancel(handle, cause=cause)

    def generate(self, name: str, prompt, max_new_tokens: int, *,
                 tenant: str = "anonymous", temperature: float = 1.0,
                 top_k: Optional[int] = None, eos_id: Optional[int] = None,
                 timeout_ms: Optional[float] = None, ctx=None) -> np.ndarray:
        """Blocking generate; batch prompts fan out row-per-request like
        :meth:`ContinuousBatcher.generate`."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim == 1:
            return self.submit_generate(
                name, prompt, max_new_tokens, tenant=tenant,
                temperature=temperature, top_k=top_k, eos_id=eos_id,
                timeout_ms=timeout_ms, ctx=ctx).wait()
        handles = [self.submit_generate(
            name, p, max_new_tokens, tenant=tenant, temperature=temperature,
            top_k=top_k, eos_id=eos_id, timeout_ms=timeout_ms)
            for p in prompt]
        outs = [h.wait() for h in handles]
        width = max(o.shape[0] for o in outs)
        pad = eos_id if eos_id is not None else 0
        full = np.full((len(outs), width), pad, np.int32)
        for i, o in enumerate(outs):
            full[i, :o.shape[0]] = o
        return full

    # ----------------------------------------------------------------- admin
    def publish(self, name: str, params, state=None,
                version: Optional[str] = None, drain: bool = True) -> int:
        return self.get(name).publish(params, state=state, version=version,
                                      drain=drain)

    def status(self) -> dict:
        with self._lock:
            entries = dict(self._entries)
            breakers = dict(self._breakers)
        body: Dict[str, Any] = {
            "models": {n: e.info() for n, e in sorted(entries.items())},
            "pager": self.pager.stats(),
            "tenants": self.tenants.stats(),
            "health": self.health.snapshot(),
            "breakers": {n: b.snapshot() for n, b in sorted(breakers.items())},
            "slo": self.slo.snapshot(),
        }
        if self.aot_store is not None:
            body["aot_store"] = self.aot_store.stats()
        return body

    def shutdown(self) -> None:
        """Drain and deactivate every resident model."""
        if self._watchdog is not None:
            # stop the watchdog FIRST: a drain must not be mistaken for a
            # stall and "restarted" mid-teardown
            self._watchdog.stop()
        with self._lock:
            self._closing = True
            entries = list(self._entries.values())
        for entry in entries:
            self.pager.drop(entry)
