"""Fleet HTTP front door — routed, tenant-aware serving over a
:class:`~.registry.FleetRegistry`.

One listener multiplexes every model in the fleet:

- ``POST /v1/models/{name}/predict``  — body as ``ModelServer /predict``;
  answers ``{"output": ..., "generation": N, "model": name}``.
- ``POST /v1/models/{name}/generate`` — body as ``ModelServer /generate``;
  **streams SSE by default** for 1-D prompts, ``?stream=false`` (or batch
  prompts) buffers.
- ``GET /v1/models`` (names + residency) · ``GET /v1/models/{name}``
  (one entry) · ``GET /v1/fleet`` (models + pager + tenants + AOT store)
  · ``GET /health`` · ``GET /ready`` · ``GET /metrics``.
- ``GET /v1/replica`` — the cluster heartbeat self-report (identity,
  residency, HBM budget, queue depth); ``POST /v1/admin/drain``
  ``{"model": name}`` pages a model out on router demotion.
- ``/v1/debug/chaos`` (GET echo / POST install-or-uninstall fault specs)
  when constructed with ``chaos_admin=True`` — 404 otherwise.

The tenant rides the ``X-Tenant`` header (default ``"anonymous"``, which
gets the table's default policy — the front door never 500s on a new
tenant). Typed failures map to their HTTP status; back-pressure answers
carry ``Retry-After``: a 429 quota shed uses the tenant bucket's own
refill estimate, a 503 queue shed scales with the target model's queue
depth (:func:`~..serve.http.retry_after_s`).

``/metrics`` label cardinality stays bounded: ``_metric_route`` collapses
``/v1/models/<anything>/predict`` to ``/v1/models/{name}/predict`` for
the shared per-endpoint latency histograms (model disaggregation lives on
the ``model=`` label of the serving metrics, not the endpoint label).
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from typing import Optional

import numpy as np

from ..chaos import faults as _faults
from ..obs import flight as _flight
from ..obs import profile as _profile
from ..obs import reqtrace as _rt
from ..serve.errors import ServeError
from ..serve.http import (chaos_apply, chaos_status, jitter_retry_after,
                          retry_after_s)
from ..utils.httpd import JsonHTTPServerMixin, JsonRequestHandler
from .registry import FleetRegistry
from .tenants import QuotaError

log = logging.getLogger(__name__)

_BAD_REQUEST = (KeyError, ValueError, TypeError, AttributeError,
                json.JSONDecodeError)
_MODEL_ROUTE = re.compile(r"^/v1/models/([^/]+)(?:/(predict|generate))?$")
_HTTP_ERRORS_HELP = "non-2xx HTTP answers by endpoint and status code"


class FleetServer(JsonHTTPServerMixin):
    """Serve a whole :class:`FleetRegistry` over one HTTP listener."""

    def __init__(self, fleet: FleetRegistry, *, host: str = "127.0.0.1",
                 port: int = 9020, replica_id: Optional[str] = None,
                 chaos_admin: bool = False, jitter_rng=None):
        self.fleet = fleet
        # injectable Retry-After jitter source (None = process-global RNG);
        # replays pass random.Random(seed) for bit-deterministic backoff
        self.jitter_rng = jitter_rng
        self.host = host
        self.port = port
        # cluster identity: who this process is in a replica set. The id
        # rides on every /v1/replica heartbeat answer so the router's
        # membership table and placement speak one namespace.
        self.replica_id = replica_id
        # debug-only surface: /v1/debug/chaos answers 404 unless opted in,
        # so a production front door never exposes fault injection
        self.chaos_admin = bool(chaos_admin)
        self.metrics = fleet.metrics  # httpd scaffolding serves /metrics
        self._lifecycle_lock = threading.Lock()
        self._accepting = True

    def accepting(self) -> bool:
        with self._lifecycle_lock:
            return self._accepting

    def ready(self) -> bool:
        # readiness (load-balancer rotation) flips on ANY degradation —
        # breaker open, watchdog restart in progress — but a degraded
        # server still ANSWERS requests: accepting() gates the handlers
        return self.accepting() and self.fleet.health.ok()

    def beat(self) -> dict:
        """One cluster-heartbeat self-report: identity, readiness, model
        residency, HBM budget, and queued load. The router's membership
        table polls this (``GET /v1/replica``) and feeds placement."""
        pager = self.fleet.pager.stats()
        return {
            "replica": self.replica_id,
            "accepting": self.accepting(),
            "ready": self.ready(),
            "models": {n: self.fleet.get(n).info()
                       for n in self.fleet.names()},
            "hbm_budget_bytes": pager.get("budget_bytes"),
            "resident_bytes": pager.get("resident_bytes"),
            "queue_depth": self.fleet.queue_depth(),
            "kv_utilization": self.fleet.kv_pressure(),
        }

    def _metric_route(self, path: str) -> str:
        m = _MODEL_ROUTE.match(path)
        if m:
            verb = f"/{m.group(2)}" if m.group(2) else ""
            return f"/v1/models/{{name}}{verb}"
        return path

    def _retry_after(self, name: Optional[str]) -> int:
        """503 back-off derived from the shedding model's queue depth; a
        non-resident or unknown model reads as an idle queue (1s)."""
        depth = limit = 0
        try:
            entry = self.fleet.get(name) if name else None
        except ServeError:
            entry = None
        if entry is not None:
            try:
                eng = entry.engine()
                depth, limit = eng.queue_depth(), eng.queue_limit
            except ServeError:
                pass
        return retry_after_s(depth, limit, self.jitter_rng)

    # ------------------------------------------------------------- handler
    def _handler(self):
        server = self

        class Handler(JsonRequestHandler):
            owner = server

            def _tenant(self) -> str:
                return self.headers.get("X-Tenant", "anonymous")

            def _err(self, code, body, headers=None):
                """Non-2xx answer, counted per (endpoint, code) with the
                model name collapsed out of the endpoint label."""
                endpoint = server._metric_route(self.path.split("?", 1)[0])
                server.metrics.counter(
                    "serve_http_errors_total",
                    {"endpoint": endpoint, "code": str(code)},
                    help=_HTTP_ERRORS_HELP).inc()
                self.reply(code, body, headers=headers)

            def reply(self, code, payload, ctype="application/json",
                      headers=None):
                # traced requests echo their identity on every answer and
                # time the buffered write-out as the "flush" stage
                ctx = getattr(self, "_obs_ctx", None)
                if ctx is None:
                    super().reply(code, payload, ctype, headers)
                    return
                headers = dict(headers or {})
                headers.setdefault("X-Request-Id", ctx.request_id)
                headers.setdefault("traceparent", ctx.traceparent())
                with ctx.stage("flush", code=code):
                    super().reply(code, payload, ctype, headers)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/health":
                    # liveness + worst-case state machine: failed (watchdog
                    # gave up restarting) answers 503 so an orchestrator
                    # replaces the process; degraded stays 200 — still alive
                    snap = server.fleet.health.snapshot()
                    snap["models"] = server.fleet.names()
                    code = 200 if snap["status"] != "failed" else 503
                    if code == 200:
                        self.reply(code, snap)
                    else:
                        self._err(code, snap)
                elif path == "/ready":
                    if server.ready():
                        self.reply(200, {"status": "ready"})
                    else:
                        self._err(503, {
                            "status": "not_ready",
                            "health": server.fleet.health.snapshot()})
                elif path == "/v1/replica":
                    self.reply(200, server.beat())
                elif path == "/v1/metrics":
                    # structured registry snapshot for the federated
                    # scraper — JSON keeps the histogram quantile tracks
                    # the Prometheus text exposition cannot carry
                    self.reply(200, server.metrics.snapshot())
                elif path == "/v1/debug/profile":
                    # executable-level cost attribution for THIS replica;
                    # {"enabled": false} when no profiler is installed
                    self.reply(200, _profile.debug_payload())
                elif path == "/v1/debug/chaos" and server.chaos_admin:
                    self.reply(200, chaos_status())
                elif path == "/v1/fleet":
                    self.reply(200, server.fleet.status())
                elif path == "/v1/models":
                    status = server.fleet.status()
                    self.reply(200, {"models": status["models"]})
                elif path == "/v1/debug/requests":
                    recs = (_flight.ACTIVE.requests()
                            if _flight.ACTIVE is not None else [])
                    self.reply(200, {"requests": recs})
                elif path == "/v1/debug/flight":
                    if _flight.ACTIVE is None:
                        self._err(404,
                                  {"error": "flight recorder not installed"})
                    else:
                        self.reply(200, _flight.ACTIVE.snapshot())
                else:
                    m = _MODEL_ROUTE.match(path)
                    if m and m.group(2) is None:
                        try:
                            entry = server.fleet.get(m.group(1))
                            self.reply(200, {"model": entry.name,
                                             **entry.info()})
                        except ServeError as e:
                            self._err(e.http_status,
                                      {"error": str(e), "cause": e.cause})
                    else:
                        self._err(404, {"error": "unknown endpoint"})

            def do_POST(self):
                path, _, query = self.path.partition("?")
                m = _MODEL_ROUTE.match(path)
                name = m.group(1) if m else None
                ctx = None
                if _rt.ACTIVE is not None:
                    # ingress: join the caller's W3C trace (or start one),
                    # echo X-Request-Id; a malformed traceparent yields a
                    # fresh trace, never a failed request
                    ctx = _rt.ACTIVE.begin(
                        m.group(2) if m and m.group(2) else "post",
                        traceparent=self.headers.get("traceparent"),
                        request_id=self.headers.get("X-Request-Id"),
                        model=name, tenant=self._tenant())
                    self._obs_ctx = ctx
                    self._obs_trace_id = ctx.trace_id
                try:
                    if path == "/v1/debug/chaos" and server.chaos_admin:
                        # admin surface stays usable even with a fault
                        # armed at http.handler — it is how you disarm one
                        self.reply(200, chaos_apply(self.read_json()))
                        return
                    if path == "/v1/admin/drain":
                        # demotion from the router: page the model out
                        # (lease-drained) so its weights stop holding HBM
                        # on a replica the placement no longer targets
                        req = self.read_json()
                        entry = server.fleet.get(req["model"])
                        server.fleet.pager.drop(entry)
                        self.reply(200, {"model": entry.name,
                                         "resident": entry.resident})
                        return
                    if _faults.ACTIVE is not None:
                        _faults.ACTIVE.hit("http.handler")
                    if not server.accepting():
                        raise ServeError("fleet is draining",
                                         cause="shutting_down")
                    if m is None or m.group(2) is None:
                        self._err(404, {"error": "unknown endpoint"})
                        if ctx is not None:
                            ctx.finish(error="bad_request")
                        return
                    req = self.read_json()
                    if m.group(2) == "predict":
                        self._predict(name, req)
                    else:
                        self._generate(name, req, query)
                except QuotaError as e:
                    self._err(e.http_status,
                              {"error": str(e), "cause": e.cause,
                               "tenant": self._tenant()},
                              headers={"Retry-After":
                                       jitter_retry_after(
                                           e.retry_after_s,
                                           server.jitter_rng)})
                    if ctx is not None:
                        ctx.finish(error=e.cause)
                except ServeError as e:
                    headers = None
                    if e.http_status == 503:
                        # breaker/page-in errors know their own back-off
                        # (jittered so refused clients don't re-arrive in
                        # one synchronized wave); queue sheds fall back to
                        # the depth-derived estimate
                        retry = getattr(e, "retry_after_s", None)
                        headers = {"Retry-After":
                                   jitter_retry_after(retry,
                                                      server.jitter_rng)
                                   if retry is not None
                                   else server._retry_after(name)}
                    self._err(e.http_status,
                              {"error": str(e), "cause": e.cause},
                              headers=headers)
                    if ctx is not None:
                        ctx.finish(error=e.cause)
                except _BAD_REQUEST as e:
                    self._err(400, {"error": str(e)})
                    if ctx is not None:
                        ctx.finish(error="bad_request")
                except (BrokenPipeError, ConnectionResetError):
                    # the client hung up while we were answering: nothing
                    # left to write to, and a vanished reader is shed load,
                    # not a server error
                    server.metrics.counter(
                        "serve_shed_total", {"cause": "client_gone"},
                        help="requests refused at admission, by cause").inc()
                    if ctx is not None:
                        ctx.finish(error="client_gone")
                except Exception as e:  # front door answers every request  # jaxlint: disable=broad-except
                    log.exception("unhandled error serving %s", self.path)
                    self._err(500, {"error": f"{type(e).__name__}: {e}"})
                    if ctx is not None:
                        ctx.finish(error="internal")
                finally:
                    if ctx is not None:
                        ctx.finish()  # idempotent: no-op after an error path

            def _predict(self, name, req):
                res = server.fleet.predict(
                    name, req["ndarray"], tenant=self._tenant(),
                    timeout_ms=req.get("timeout_ms"),
                    ctx=getattr(self, "_obs_ctx", None))
                body = {"output": np.asarray(res.output).tolist(),
                        "model": name}
                if res.generation is not None:
                    body["generation"] = res.generation
                self.reply(200, body)

            def _sse(self, payload):
                self.wfile.write(
                    b"data: " + json.dumps(payload).encode() + b"\n\n")
                self.wfile.flush()

            def _generate(self, name, req, query):
                ctx = getattr(self, "_obs_ctx", None)
                prompt = np.asarray(req["prompt"], np.int32)
                kwargs = dict(
                    tenant=self._tenant(),
                    temperature=float(req.get("temperature", 1.0)),
                    top_k=req.get("top_k"), eos_id=req.get("eos_id"),
                    timeout_ms=req.get("timeout_ms"))
                mnt = int(req.get("max_new_tokens", 16))
                stream = "stream=false" not in query \
                    and "stream=0" not in query \
                    and req.get("stream") is not False
                if prompt.ndim != 1:  # batch prompts are always buffered
                    stream = False
                if not stream:
                    toks = server.fleet.generate(name, prompt, mnt, ctx=ctx,
                                                 **kwargs)
                    self.reply(200, {"tokens": np.asarray(toks).tolist(),
                                     "model": name})
                    return
                # admission errors surface as typed statuses BEFORE the
                # stream opens; later failures are delivered in-band
                handle = server.fleet.submit_generate(name, prompt, mnt,
                                                      ctx=ctx, **kwargs)
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                if ctx is not None:
                    self.send_header("X-Request-Id", ctx.request_id)
                    self.send_header("traceparent", ctx.traceparent())
                self.end_headers()
                self.close_connection = True
                t0f = time.perf_counter_ns() if ctx is not None else 0
                out = []
                err_cause = None
                try:
                    for tok in handle.stream():
                        out.append(int(tok))
                        self._sse({"token": int(tok)})
                    self._sse({"done": True, "tokens": out, "model": name})
                except ServeError as e:
                    try:
                        self._sse({"error": str(e), "cause": e.cause,
                                   "tokens": out})
                    except (BrokenPipeError, ConnectionResetError):
                        pass  # nobody left to tell
                    err_cause = e.cause
                except (BrokenPipeError, ConnectionResetError):
                    # client dropped the socket mid-stream: free the decode
                    # slot and KV pages NOW (the cancel path counts the shed
                    # as cause="client_gone") instead of decoding to nobody
                    server.fleet.cancel_generate(name, handle)
                    err_cause = "client_gone"
                if ctx is not None:
                    # the streaming window: first header flush to last event
                    ctx.add_stage("flush", t0f, time.perf_counter_ns(),
                                  tokens=len(out))
                    if err_cause is not None:
                        ctx.finish(error=err_cause)

        return Handler

    # ----------------------------------------------------------- lifecycle
    def stop(self, drain: bool = True):
        """Flip readiness, drain every resident model, close the listener."""
        with self._lifecycle_lock:
            self._accepting = False
        if drain:
            self.fleet.shutdown()
        super().stop()
