"""Sequence zoo models: TextGenerationLSTM (zoo parity) + GravesLSTM char-RNN
(the reference baseline config, BASELINE.md #3)."""

from __future__ import annotations

from ..nn import layers as L
from ..nn.model import NetConfig, Sequential, SequentialBuilder
from .zoo import ZooModel, register_model


@register_model
class TextGenerationLSTM(ZooModel):
    """zoo/model/TextGenerationLSTM.java — 2x LSTM(256) char model."""

    input_shape = (64, 77)  # (T, vocab) one-hot input like the reference default
    num_classes = 77

    def build(self) -> Sequential:
        T, V = self.input_shape
        return (SequentialBuilder(NetConfig(seed=self.seed, tbptt_length=self.kwargs.get("tbptt", 0),
                                            updater={"type": "adam", "learning_rate": 1e-3}))
                .input_shape(T, V)
                .layer(L.LSTM(n_out=256))
                .layer(L.LSTM(n_out=256))
                .layer(L.RnnOutput(n_out=self.num_classes, activation="softmax", loss="mcxent"))
                .build())


@register_model
class GravesLSTMCharRNN(ZooModel):
    """BASELINE.md config #3: GravesLSTM char-RNN (dl4j-examples
    GravesLSTMCharModellingExample) — peephole LSTM path, the reference's
    CudnnLSTMHelper benchmark surface."""

    input_shape = (64, 98)
    num_classes = 98
    hidden = 200

    def build(self) -> Sequential:
        T, V = self.input_shape
        return (SequentialBuilder(NetConfig(seed=self.seed, tbptt_length=self.kwargs.get("tbptt", 50),
                                            updater={"type": "rmsprop", "learning_rate": 1e-1}))
                .input_shape(T, V)
                .layer(L.GravesLSTM(n_out=self.hidden,
                                    scan_unroll=self.kwargs.get("scan_unroll", 1)))
                .layer(L.GravesLSTM(n_out=self.hidden,
                                    scan_unroll=self.kwargs.get("scan_unroll", 1)))
                .layer(L.RnnOutput(n_out=self.num_classes, activation="softmax", loss="mcxent"))
                .build())
