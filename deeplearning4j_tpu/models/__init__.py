"""Model zoo (L7) — parity with deeplearning4j-zoo's 13 models (SURVEY.md §2.8)
plus the transformer family the TPU build adds."""

from .cnn import (VGG16, VGG19, YOLO2, AlexNet, Darknet19, FaceNetNN4Small2,
                  GoogLeNet, InceptionResNetV1, LeNet, ResNet50, SimpleCNN,
                  TinyYOLO)
from .rnn import GravesLSTMCharRNN, TextGenerationLSTM
from .transformer import BertBase, CausalLM, sharded_lm_step
from .zoo import ZOO_REGISTRY, ZooModel, model_by_name, register_model

__all__ = ["AlexNet", "BertBase", "CausalLM", "Darknet19", "FaceNetNN4Small2",
           "GoogLeNet", "GravesLSTMCharRNN", "InceptionResNetV1", "LeNet",
           "ResNet50", "SimpleCNN", "TextGenerationLSTM", "TinyYOLO", "VGG16",
           "VGG19", "YOLO2", "ZOO_REGISTRY", "ZooModel", "model_by_name",
           "register_model", "sharded_lm_step"]
