"""Class-label maps for zoo models — ``zoo/util/{ImageNetLabels, DarknetLabels,
VOCLabels, COCOLabels}.java`` parity.

COCO-80 and VOC-20 label sets are small enough to embed. ImageNet-1k and
Darknet-9k are shipped by the reference as vendored resource files; here they
load from ``$DL4J_TPU_DATA/labels/`` (standard one-label-per-line format, the
same files the reference bundles) with a clear error when absent — consistent
with the zero-egress dataset policy (data/datasets.py).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import List

COCO_LABELS: List[str] = [
    "person", "bicycle", "car", "motorcycle", "airplane", "bus", "train",
    "truck", "boat", "traffic light", "fire hydrant", "stop sign",
    "parking meter", "bench", "bird", "cat", "dog", "horse", "sheep", "cow",
    "elephant", "bear", "zebra", "giraffe", "backpack", "umbrella", "handbag",
    "tie", "suitcase", "frisbee", "skis", "snowboard", "sports ball", "kite",
    "baseball bat", "baseball glove", "skateboard", "surfboard",
    "tennis racket", "bottle", "wine glass", "cup", "fork", "knife", "spoon",
    "bowl", "banana", "apple", "sandwich", "orange", "broccoli", "carrot",
    "hot dog", "pizza", "donut", "cake", "chair", "couch", "potted plant",
    "bed", "dining table", "toilet", "tv", "laptop", "mouse", "remote",
    "keyboard", "cell phone", "microwave", "oven", "toaster", "sink",
    "refrigerator", "book", "clock", "vase", "scissors", "teddy bear",
    "hair drier", "toothbrush",
]

VOC_LABELS: List[str] = [
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
]

_LABELS_DIR = Path(os.environ.get(
    "DL4J_TPU_DATA", Path.home() / ".deeplearning4j_tpu" / "data")) / "labels"


def _load_label_file(name: str, expected: int) -> List[str]:
    p = _LABELS_DIR / name
    if not p.exists():
        raise FileNotFoundError(
            f"Label file {p} not found. The reference vendors this list as a "
            f"resource; zero-egress builds read the standard one-label-per-line "
            f"file — place it there (expected {expected} lines).")
    labels = [ln.strip() for ln in p.read_text().splitlines() if ln.strip()]
    if expected and len(labels) != expected:
        raise ValueError(f"{p} has {len(labels)} labels, expected {expected}")
    return labels


def imagenet_labels() -> List[str]:
    """ImageNetLabels.java — the 1000 ILSVRC2012 class names."""
    return _load_label_file("imagenet_labels.txt", 1000)


def darknet_labels() -> List[str]:
    """DarknetLabels.java — ImageNet-1k in darknet ordering."""
    return _load_label_file("darknet_labels.txt", 1000)


def coco_labels() -> List[str]:
    return list(COCO_LABELS)


def voc_labels() -> List[str]:
    return list(VOC_LABELS)


def decode_predictions(probs, labels: List[str], top: int = 5):
    """Top-k (label, probability) decode for zoo classifiers
    (TrainedModels.decodePredictions parity)."""
    import numpy as np

    probs = np.asarray(probs)
    if probs.ndim == 1:
        probs = probs[None]
    out = []
    for row in probs:
        idx = np.argsort(row)[::-1][:top]
        out.append([(labels[i] if i < len(labels) else str(i), float(row[i]))
                    for i in idx])
    return out
