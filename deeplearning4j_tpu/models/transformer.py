"""Transformer model family — the modern sequence stack.

BERT-base is the driver's stretch import target (BASELINE.md #5); long-context
causal LMs are where the framework's sequence parallelism earns its keep.
These models are plain Sequential stacks of TransformerEncoderBlock, so they
serialize/train/evaluate through the same machinery as every zoo CNN — plus
``sharded_lm`` builds the fully-sharded (dp x tp x sp) training step used by
``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import optax
from jax.sharding import Mesh

from ..nn import layers as L
from ..nn.model import NetConfig, Sequential, SequentialBuilder
from ..parallel.sharding import TRANSFORMER_RULES
from .zoo import ZooModel, register_model


@register_model
class BertBase(ZooModel):
    """BERT-base-uncased shape: 12 layers, d=768, h=12, vocab 30522.

    Built from the generic layer catalog; the Keras/HF import path
    (keras_import/) targets this architecture.
    """

    num_layers = 12
    d_model = 768
    num_heads = 12
    vocab = 30522
    max_len = 512
    input_shape = (128,)  # (T,) int token ids
    num_classes = 2  # default classification head

    def __init__(self, num_classes=None, seed=12345, input_shape=None, *, small=False,
                 flash=False, remat=False, ragged=True, **kw):
        super().__init__(num_classes, seed, input_shape, **kw)
        self.flash = flash
        self.remat = remat
        # ragged=True (default): (B, T) masks are treated as RIGHT-PADDED
        # (how BERT tokenizers pad) and ride the flash kernel's faster
        # per-example-lengths path. Pass ragged=False for gappy/packed
        # masks — they then take the exact key_mask path bit-for-bit.
        self.ragged = ragged
        if small:  # test-sized variant
            self.num_layers, self.d_model, self.num_heads, self.vocab, self.max_len = 2, 64, 4, 1000, 128

    def build(self) -> Sequential:
        T = self.input_shape[0]
        b = (SequentialBuilder(NetConfig(seed=self.seed,
                                         updater={"type": "adamw", "learning_rate": 1e-4}))
             .input_shape(T)
             .layer(L.EmbeddingSequence(n_in=self.vocab, n_out=self.d_model))
             .layer(L.PositionalEmbedding(max_len=self.max_len)))
        for _ in range(self.num_layers):
            b.layer(L.TransformerEncoderBlock(num_heads=self.num_heads, causal=False,
                                              flash=self.flash, remat=self.remat,
                                              ragged=self.ragged))
        return (b.layer(L.LayerNorm())
                .layer(L.GlobalPooling(mode="avg"))
                .layer(L.Output(n_out=self.num_classes, activation="softmax", loss="mcxent"))
                .build())


@register_model
class CausalLM(ZooModel):
    """GPT-style causal LM — the long-context flagship."""

    num_layers = 4
    d_model = 256
    num_heads = 8
    vocab = 512
    input_shape = (256,)

    def __init__(self, num_classes=None, seed=12345, input_shape=None, *,
                 num_layers=None, d_model=None, num_heads=None, vocab=None,
                 flash=False, remat=False, ring=False, pos="learned",
                 num_kv_heads=None, window=None, **kw):
        super().__init__(num_classes, seed, input_shape, **kw)
        self.num_layers = num_layers or self.num_layers
        self.d_model = d_model or self.d_model
        self.num_heads = num_heads or self.num_heads
        self.vocab = vocab or self.vocab
        self.num_classes = self.vocab
        self.flash = flash
        self.remat = remat
        self.ring = ring
        if pos not in ("learned", "rope"):
            raise ValueError(f"pos must be 'learned' or 'rope', got {pos!r}")
        self.pos = pos
        self.num_kv_heads = num_kv_heads  # GQA: shrink KV proj + decode cache
        self.window = window  # sliding-window attention (Mistral-style)

    def build(self) -> Sequential:
        T = self.input_shape[0]
        b = (SequentialBuilder(NetConfig(seed=self.seed,
                                         updater={"type": "adamw", "learning_rate": 3e-4}))
             .input_shape(T)
             .layer(L.EmbeddingSequence(n_in=self.vocab, n_out=self.d_model)))
        rope = self.pos == "rope"
        if not rope:
            # learned absolute table; at long context prefer pos="rope"
            # (a T=64k table is 100M params at d=1536 and cannot
            # extrapolate past max_len)
            b.layer(L.PositionalEmbedding(max_len=max(T, 512)))
        for _ in range(self.num_layers):
            b.layer(L.TransformerEncoderBlock(num_heads=self.num_heads, causal=True,
                                              flash=self.flash, remat=self.remat,
                                              ring=self.ring, rope=rope,
                                              num_kv_heads=self.num_kv_heads,
                                              window=self.window))
        b.layer(L.LayerNorm())
        b.layer(L.RnnOutput(n_out=self.vocab, activation="softmax", loss="mcxent"))
        return b.build()


# ---------------------------------------------------------------------------
# Fully-sharded training step: dp x tp x sp over one mesh.
# ---------------------------------------------------------------------------

def sharded_lm_step(model: Sequential, mesh: Mesh, tx: optax.GradientTransformation):
    """Build a jit-compiled train step with:

    - params sharded per TRANSFORMER_RULES over the ``model`` axis (TP),
    - batch sharded over ``data`` (DP),
    - activations sequence-sharded over ``seq`` (SP) via sharding constraints —
      GSPMD decomposes the attention einsums into collective-permuted blocks.

    A thin functional wrapper over the one sharding API
    (``parallel.sharding``: place_params / batch_sharding /
    activation_sharding — the same machinery behind
    ``Trainer(mesh=, rules=)``). Returns (step_fn, placed_params,
    opt_state, placement helper).
    """
    assert model.params is not None, "init() the model first"
    from ..parallel.sharding import (activation_sharding, batch_sharding,
                                     place_params)

    params = place_params(model.params, mesh, TRANSFORMER_RULES)
    # eager init: moments inherit the params' shardings (a jitted init
    # would give constants fresh single-device layouts)
    opt_state = tx.init(params)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens, targets, rng):
        def loss_fn(p):
            with activation_sharding(mesh):
                loss, _ = model.score(p, {}, tokens, targets, training=True,
                                      rng=rng)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def place_batch(tokens, targets):
        return (jax.device_put(tokens, batch_sharding(mesh, tokens)),
                jax.device_put(targets, batch_sharding(mesh, targets)))

    return step, params, opt_state, place_batch
