"""Zoo CNN models — parity with deeplearning4j-zoo's 13 models (SURVEY.md §2.8):
LeNet, SimpleCNN, AlexNet, VGG16, VGG19, Darknet19, TinyYOLO, YOLO2, ResNet50,
GoogLeNet, InceptionResNetV1, FaceNetNN4Small2 (TextGenerationLSTM in rnn.py).

All NHWC, BatchNorm-after-conv, built on the Sequential/Graph containers so
every zoo model is jit-compiled end-to-end; ResNet-50 (ResNet50.java:80) is
the benchmark flagship (BASELINE.md: images/sec/chip).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from ..nn import layers as L
from ..nn import vertices as V
from ..nn.model import (Graph, GraphBuilder, NetConfig, Sequential,
                        SequentialBuilder)
from .zoo import ZooModel, register_model


def _net_config(seed, updater=None, **kw):
    return NetConfig(seed=seed, updater=updater or {"type": "adam", "learning_rate": 1e-3}, **kw)


@register_model
class LeNet(ZooModel):
    """zoo/model/LeNet.java — the minimum end-to-end slice (SURVEY.md §7.2)."""

    input_shape = (28, 28, 1)
    num_classes = 10

    def build(self) -> Sequential:
        return (SequentialBuilder(_net_config(self.seed))
                .input_shape(*self.input_shape)
                .layer(L.Conv2D(n_out=20, kernel=(5, 5), stride=(1, 1), padding="same", activation="relu"))
                .layer(L.Subsampling2D(kernel=(2, 2), stride=(2, 2)))
                .layer(L.Conv2D(n_out=50, kernel=(5, 5), stride=(1, 1), padding="same", activation="relu"))
                .layer(L.Subsampling2D(kernel=(2, 2), stride=(2, 2)))
                .layer(L.Flatten())
                .layer(L.Dense(n_out=500, activation="relu"))
                .layer(L.Output(n_out=self.num_classes, activation="softmax", loss="mcxent"))
                .build())


@register_model
class SimpleCNN(ZooModel):
    """zoo/model/SimpleCNN.java."""

    input_shape = (48, 48, 3)
    num_classes = 10

    def build(self) -> Sequential:
        b = (SequentialBuilder(_net_config(self.seed)).input_shape(*self.input_shape))
        for n_out, pool in [(16, False), (16, True), (32, False), (32, True), (64, False), (64, True)]:
            b.layer(L.Conv2D(n_out=n_out, kernel=(3, 3), padding="same", activation="identity"))
            b.layer(L.BatchNorm(activation="relu"))
            if pool:
                b.layer(L.Subsampling2D(kernel=(2, 2), stride=(2, 2)))
        return (b.layer(L.GlobalPooling(mode="avg"))
                .layer(L.DropoutLayer(rate=0.5))
                .layer(L.Output(n_out=self.num_classes, activation="softmax", loss="mcxent"))
                .build())


@register_model
class AlexNet(ZooModel):
    """zoo/model/AlexNet.java — incl. the LRN layers of the original."""

    input_shape = (224, 224, 3)
    num_classes = 1000

    def build(self) -> Sequential:
        return (SequentialBuilder(_net_config(self.seed))
                .input_shape(*self.input_shape)
                .layer(L.Conv2D(n_out=96, kernel=(11, 11), stride=(4, 4), padding="valid", activation="relu"))
                .layer(L.LRN())
                .layer(L.Subsampling2D(kernel=(3, 3), stride=(2, 2)))
                .layer(L.Conv2D(n_out=256, kernel=(5, 5), padding="same", activation="relu"))
                .layer(L.LRN())
                .layer(L.Subsampling2D(kernel=(3, 3), stride=(2, 2)))
                .layer(L.Conv2D(n_out=384, kernel=(3, 3), padding="same", activation="relu"))
                .layer(L.Conv2D(n_out=384, kernel=(3, 3), padding="same", activation="relu"))
                .layer(L.Conv2D(n_out=256, kernel=(3, 3), padding="same", activation="relu"))
                .layer(L.Subsampling2D(kernel=(3, 3), stride=(2, 2)))
                .layer(L.Flatten())
                .layer(L.Dense(n_out=4096, activation="relu", dropout=0.5))
                .layer(L.Dense(n_out=4096, activation="relu", dropout=0.5))
                .layer(L.Output(n_out=self.num_classes, activation="softmax", loss="mcxent"))
                .build())


def _vgg(seed, input_shape, num_classes, cfg: Sequence) -> Sequential:
    b = SequentialBuilder(_net_config(seed)).input_shape(*input_shape)
    for item in cfg:
        if item == "M":
            b.layer(L.Subsampling2D(kernel=(2, 2), stride=(2, 2)))
        else:
            b.layer(L.Conv2D(n_out=item, kernel=(3, 3), padding="same", activation="relu"))
    return (b.layer(L.Flatten())
            .layer(L.Dense(n_out=4096, activation="relu", dropout=0.5))
            .layer(L.Dense(n_out=4096, activation="relu", dropout=0.5))
            .layer(L.Output(n_out=num_classes, activation="softmax", loss="mcxent"))
            .build())


@register_model
class VGG16(ZooModel):
    """zoo/model/VGG16.java."""

    input_shape = (224, 224, 3)

    def build(self):
        return _vgg(self.seed, self.input_shape, self.num_classes,
                    [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
                     512, 512, 512, "M", 512, 512, 512, "M"])


@register_model
class VGG19(ZooModel):
    """zoo/model/VGG19.java."""

    input_shape = (224, 224, 3)

    def build(self):
        return _vgg(self.seed, self.input_shape, self.num_classes,
                    [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
                     512, 512, 512, 512, "M", 512, 512, 512, 512, "M"])


def _darknet_conv(b: SequentialBuilder, n_out: int, kernel: int):
    """DarknetHelper.addLayers parity: conv (no bias) + BN + leaky relu."""
    b.layer(L.Conv2D(n_out=n_out, kernel=(kernel, kernel), padding="same",
                     use_bias=False, activation="identity"))
    b.layer(L.BatchNorm(activation="leakyrelu"))


@register_model
class Darknet19(ZooModel):
    """zoo/model/Darknet19.java."""

    input_shape = (224, 224, 3)

    def build(self) -> Sequential:
        b = SequentialBuilder(_net_config(self.seed)).input_shape(*self.input_shape)
        plan = [(32, 3, True), (64, 3, True),
                (128, 3, False), (64, 1, False), (128, 3, True),
                (256, 3, False), (128, 1, False), (256, 3, True),
                (512, 3, False), (256, 1, False), (512, 3, False), (256, 1, False), (512, 3, True),
                (1024, 3, False), (512, 1, False), (1024, 3, False), (512, 1, False), (1024, 3, False)]
        for n_out, k, pool in plan:
            _darknet_conv(b, n_out, k)
            if pool:
                b.layer(L.Subsampling2D(kernel=(2, 2), stride=(2, 2)))
        b.layer(L.Conv2D(n_out=self.num_classes, kernel=(1, 1), padding="same", activation="identity"))
        b.layer(L.GlobalPooling(mode="avg"))
        b.layer(L.LossLayer(activation="softmax", loss="mcxent"))
        return b.build()


@register_model
class TinyYOLO(ZooModel):
    """zoo/model/TinyYOLO.java — darknet-tiny backbone + Yolo2 output."""

    input_shape = (416, 416, 3)
    num_classes = 20
    anchors = ((1.08, 1.19), (3.42, 4.41), (6.63, 11.38), (9.42, 5.11), (16.62, 10.52))

    def build(self) -> Sequential:
        b = SequentialBuilder(_net_config(self.seed)).input_shape(*self.input_shape)
        for i, n_out in enumerate([16, 32, 64, 128, 256]):
            _darknet_conv(b, n_out, 3)
            b.layer(L.Subsampling2D(kernel=(2, 2), stride=(2, 2)))
        _darknet_conv(b, 512, 3)
        b.layer(L.Subsampling2D(kernel=(2, 2), stride=(1, 1), padding="same"))
        _darknet_conv(b, 1024, 3)
        _darknet_conv(b, 1024, 3)
        n_anchor = len(self.anchors)
        b.layer(L.Conv2D(n_out=n_anchor * (5 + self.num_classes), kernel=(1, 1),
                         padding="same", activation="identity"))
        b.layer(L.Yolo2Output(anchors=self.anchors))
        return b.build()


@register_model
class YOLO2(ZooModel):
    """zoo/model/YOLO2.java — Darknet19 backbone + passthrough + Yolo2 output."""

    input_shape = (416, 416, 3)
    num_classes = 80
    anchors = ((0.57273, 0.677385), (1.87446, 2.06253), (3.33843, 5.47434),
               (7.88282, 3.52778), (9.77052, 9.16828))

    def build(self) -> Graph:
        g = GraphBuilder(_net_config(self.seed)).add_input("in", self.input_shape)

        def conv_bn(name, inp, n_out, k, act="leakyrelu"):
            g.add_layer(f"{name}_conv", L.Conv2D(n_out=n_out, kernel=(k, k), padding="same",
                                                 use_bias=False, activation="identity"), inp)
            g.add_layer(name, L.BatchNorm(activation=act), f"{name}_conv")
            return name

        x = conv_bn("c1", "in", 32, 3)
        g.add_layer("p1", L.Subsampling2D(kernel=(2, 2), stride=(2, 2)), x)
        x = conv_bn("c2", "p1", 64, 3)
        g.add_layer("p2", L.Subsampling2D(kernel=(2, 2), stride=(2, 2)), x)
        x = conv_bn("c3", "p2", 128, 3)
        x = conv_bn("c4", x, 64, 1)
        x = conv_bn("c5", x, 128, 3)
        g.add_layer("p3", L.Subsampling2D(kernel=(2, 2), stride=(2, 2)), x)
        x = conv_bn("c6", "p3", 256, 3)
        x = conv_bn("c7", x, 128, 1)
        x = conv_bn("c8", x, 256, 3)
        g.add_layer("p4", L.Subsampling2D(kernel=(2, 2), stride=(2, 2)), x)
        x = conv_bn("c9", "p4", 512, 3)
        x = conv_bn("c10", x, 256, 1)
        x = conv_bn("c11", x, 512, 3)
        x = conv_bn("c12", x, 256, 1)
        passthrough = conv_bn("c13", x, 512, 3)  # 26x26x512
        g.add_layer("p5", L.Subsampling2D(kernel=(2, 2), stride=(2, 2)), passthrough)
        x = conv_bn("c14", "p5", 1024, 3)
        x = conv_bn("c15", x, 512, 1)
        x = conv_bn("c16", x, 1024, 3)
        x = conv_bn("c17", x, 512, 1)
        x = conv_bn("c18", x, 1024, 3)
        x = conv_bn("c19", x, 1024, 3)
        x = conv_bn("c20", x, 1024, 3)
        # passthrough: space-to-depth 26x26x512 -> 13x13x2048, concat
        g.add_layer("s2d", L.SpaceToDepth(block_size=2), passthrough)
        g.add_vertex("concat", V.Merge(), "s2d", x)
        x = conv_bn("c21", "concat", 1024, 3)
        n_anchor = len(self.anchors)
        g.add_layer("det", L.Conv2D(n_out=n_anchor * (5 + self.num_classes), kernel=(1, 1),
                                    padding="same", activation="identity"), x)
        g.add_layer("out", L.Yolo2Output(anchors=self.anchors), "det")
        return g.set_outputs("out").build()


@register_model
class ResNet50(ZooModel):
    """zoo/model/ResNet50.java:80 — THE benchmark flagship (BASELINE.md).

    Bottleneck v1 graph: conv7x7/2 + maxpool, stages [3, 4, 6, 3] with
    (64/256, 128/512, 256/1024, 512/2048) widths, global pool + softmax.
    """

    input_shape = (224, 224, 3)

    def build(self) -> Graph:
        g = GraphBuilder(_net_config(self.seed)).add_input("in", self.input_shape)

        def conv_bn(name, inp, n_out, k, stride=1, act="relu"):
            g.add_layer(f"{name}_c", L.Conv2D(n_out=n_out, kernel=(k, k), stride=(stride, stride),
                                              padding="same", use_bias=False, activation="identity"), inp)
            g.add_layer(name, L.BatchNorm(activation=act), f"{name}_c")
            return name

        def bottleneck(name, inp, mid, out, stride=1, project=False):
            a = conv_bn(f"{name}_a", inp, mid, 1, stride)
            b = conv_bn(f"{name}_b", a, mid, 3)
            g.add_layer(f"{name}_cc", L.Conv2D(n_out=out, kernel=(1, 1), padding="same",
                                               use_bias=False, activation="identity"), b)
            g.add_layer(f"{name}_cbn", L.BatchNorm(activation="identity"), f"{name}_cc")
            if project:
                sc = conv_bn(f"{name}_proj", inp, out, 1, stride, act="identity")
            else:
                sc = inp
            g.add_vertex(f"{name}_add", V.ElementWise(op="add"), f"{name}_cbn", sc)
            g.add_layer(name, L.ActivationLayer(activation="relu"), f"{name}_add")
            return name

        x = conv_bn("stem", "in", 64, 7, stride=2)
        g.add_layer("pool1", L.Subsampling2D(kernel=(3, 3), stride=(2, 2), padding="same"), x)
        x = "pool1"
        stages = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2), (3, 512, 2048, 2)]
        for si, (blocks, mid, out, stride) in enumerate(stages):
            for bi in range(blocks):
                x = bottleneck(f"s{si}b{bi}", x, mid, out,
                               stride=stride if bi == 0 else 1, project=bi == 0)
        g.add_layer("gap", L.GlobalPooling(mode="avg"), x)
        g.add_layer("out", L.Output(n_out=self.num_classes, activation="softmax", loss="mcxent"), "gap")
        return g.set_outputs("out").build()


@register_model
class GoogLeNet(ZooModel):
    """zoo/model/GoogLeNet.java — inception-v1 modules via Merge vertices."""

    input_shape = (224, 224, 3)

    def build(self) -> Graph:
        g = GraphBuilder(_net_config(self.seed)).add_input("in", self.input_shape)

        def conv(name, inp, n_out, k, stride=1, pad="same"):
            g.add_layer(name, L.Conv2D(n_out=n_out, kernel=(k, k), stride=(stride, stride),
                                       padding=pad, activation="relu"), inp)
            return name

        def inception(name, inp, c1, c3r, c3, c5r, c5, pp):
            b1 = conv(f"{name}_1", inp, c1, 1)
            b3 = conv(f"{name}_3", conv(f"{name}_3r", inp, c3r, 1), c3, 3)
            b5 = conv(f"{name}_5", conv(f"{name}_5r", inp, c5r, 1), c5, 5)
            g.add_layer(f"{name}_p", L.Subsampling2D(kernel=(3, 3), stride=(1, 1),
                                                     padding="same", mode="max"), inp)
            bp = conv(f"{name}_pp", f"{name}_p", pp, 1)
            g.add_vertex(name, V.Merge(), b1, b3, b5, bp)
            return name

        x = conv("stem1", "in", 64, 7, stride=2)
        g.add_layer("pool1", L.Subsampling2D(kernel=(3, 3), stride=(2, 2), padding="same"), x)
        x = conv("stem3", conv("stem2", "pool1", 64, 1), 192, 3)
        g.add_layer("pool2", L.Subsampling2D(kernel=(3, 3), stride=(2, 2), padding="same"), x)
        x = inception("i3a", "pool2", 64, 96, 128, 16, 32, 32)
        x = inception("i3b", x, 128, 128, 192, 32, 96, 64)
        g.add_layer("pool3", L.Subsampling2D(kernel=(3, 3), stride=(2, 2), padding="same"), x)
        x = inception("i4a", "pool3", 192, 96, 208, 16, 48, 64)
        x = inception("i4b", x, 160, 112, 224, 24, 64, 64)
        x = inception("i4c", x, 128, 128, 256, 24, 64, 64)
        x = inception("i4d", x, 112, 144, 288, 32, 64, 64)
        x = inception("i4e", x, 256, 160, 320, 32, 128, 128)
        g.add_layer("pool4", L.Subsampling2D(kernel=(3, 3), stride=(2, 2), padding="same"), x)
        x = inception("i5a", "pool4", 256, 160, 320, 32, 128, 128)
        x = inception("i5b", x, 384, 192, 384, 48, 128, 128)
        g.add_layer("gap", L.GlobalPooling(mode="avg"), x)
        g.add_layer("drop", L.DropoutLayer(rate=0.4), "gap")
        g.add_layer("out", L.Output(n_out=self.num_classes, activation="softmax", loss="mcxent"), "drop")
        return g.set_outputs("out").build()


@register_model
class InceptionResNetV1(ZooModel):
    """zoo/model/InceptionResNetV1.java — residual inception for face embedding."""

    input_shape = (160, 160, 3)
    num_classes = 128  # embedding size by default

    def build(self) -> Graph:
        g = GraphBuilder(_net_config(self.seed)).add_input("in", self.input_shape)

        def conv_bn(name, inp, n_out, k, stride=1, act="relu", pad="same"):
            g.add_layer(f"{name}_c", L.Conv2D(n_out=n_out, kernel=(k, k) if isinstance(k, int) else k,
                                              stride=(stride, stride), padding=pad,
                                              use_bias=False, activation="identity"), inp)
            g.add_layer(name, L.BatchNorm(activation=act), f"{name}_c")
            return name

        def block35(name, inp, channels):
            """Inception-ResNet-A: three parallel towers + residual scale-add."""
            b0 = conv_bn(f"{name}_b0", inp, 32, 1)
            b1 = conv_bn(f"{name}_b1b", conv_bn(f"{name}_b1a", inp, 32, 1), 32, 3)
            b2 = conv_bn(f"{name}_b2c", conv_bn(f"{name}_b2b",
                         conv_bn(f"{name}_b2a", inp, 32, 1), 32, 3), 32, 3)
            g.add_vertex(f"{name}_cat", V.Merge(), b0, b1, b2)
            g.add_layer(f"{name}_up", L.Conv2D(n_out=channels, kernel=(1, 1), padding="same",
                                               activation="identity"), f"{name}_cat")
            g.add_vertex(f"{name}_scale", V.Scale(factor=0.17), f"{name}_up")
            g.add_vertex(f"{name}_add", V.ElementWise(op="add"), inp, f"{name}_scale")
            g.add_layer(name, L.ActivationLayer(activation="relu"), f"{name}_add")
            return name

        x = conv_bn("stem1", "in", 32, 3, stride=2)
        x = conv_bn("stem2", x, 32, 3)
        x = conv_bn("stem3", x, 64, 3)
        g.add_layer("pool1", L.Subsampling2D(kernel=(3, 3), stride=(2, 2), padding="same"), x)
        x = conv_bn("stem4", "pool1", 80, 1)
        x = conv_bn("stem5", x, 192, 3)
        x = conv_bn("stem6", x, 256, 3, stride=2)
        for i in range(5):
            x = block35(f"a{i}", x, 256)
        g.add_layer("gap", L.GlobalPooling(mode="avg"), x)
        g.add_layer("emb", L.Dense(n_out=self.num_classes, activation="identity"), "gap")
        g.add_vertex("out", V.L2Norm(), "emb")
        return g.set_outputs("out").build()


@register_model
class FaceNetNN4Small2(ZooModel):
    """zoo/model/FaceNetNN4Small2.java — nn4.small2 face-embedding net with
    L2-normalized embedding output (triplet-loss ready)."""

    input_shape = (96, 96, 3)
    num_classes = 128

    def build(self) -> Graph:
        g = GraphBuilder(_net_config(self.seed)).add_input("in", self.input_shape)

        def conv_bn(name, inp, n_out, k, stride=1):
            g.add_layer(f"{name}_c", L.Conv2D(n_out=n_out, kernel=(k, k), stride=(stride, stride),
                                              padding="same", use_bias=False, activation="identity"), inp)
            g.add_layer(name, L.BatchNorm(activation="relu"), f"{name}_c")
            return name

        def inception(name, inp, c1, c3r, c3, c5r, c5, pp):
            branches = []
            if c1:
                branches.append(conv_bn(f"{name}_1", inp, c1, 1))
            branches.append(conv_bn(f"{name}_3", conv_bn(f"{name}_3r", inp, c3r, 1), c3, 3))
            if c5:
                branches.append(conv_bn(f"{name}_5", conv_bn(f"{name}_5r", inp, c5r, 1), c5, 5))
            g.add_layer(f"{name}_p", L.Subsampling2D(kernel=(3, 3), stride=(1, 1),
                                                     padding="same", mode="max"), inp)
            branches.append(conv_bn(f"{name}_pp", f"{name}_p", pp, 1))
            g.add_vertex(name, V.Merge(), *branches)
            return name

        x = conv_bn("c1", "in", 64, 7, stride=2)
        g.add_layer("p1", L.Subsampling2D(kernel=(3, 3), stride=(2, 2), padding="same"), x)
        x = conv_bn("c2", "p1", 64, 1)
        x = conv_bn("c3", x, 192, 3)
        g.add_layer("p2", L.Subsampling2D(kernel=(3, 3), stride=(2, 2), padding="same"), x)
        x = inception("i3a", "p2", 64, 96, 128, 16, 32, 32)
        x = inception("i3b", x, 64, 96, 128, 32, 64, 64)
        g.add_layer("p3", L.Subsampling2D(kernel=(3, 3), stride=(2, 2), padding="same"), x)
        x = inception("i4a", "p3", 256, 96, 192, 32, 64, 128)
        x = inception("i4e", x, 0, 160, 256, 64, 128, 128)
        g.add_layer("p4", L.Subsampling2D(kernel=(3, 3), stride=(2, 2), padding="same"), x)
        x = inception("i5a", "p4", 256, 96, 384, 0, 0, 96)
        g.add_layer("gap", L.GlobalPooling(mode="avg"), x)
        g.add_layer("emb", L.Dense(n_out=self.num_classes, activation="identity"), "gap")
        g.add_vertex("out", V.L2Norm(), "emb")
        return g.set_outputs("out").build()
