"""Model zoo base — parity with ``zoo/ZooModel.java`` + ``zoo/ModelSelector.java``.

``ZooModel.init()`` builds the randomly-initialized network;
``init_pretrained()`` mirrors initPretrained(PretrainedType) with a local
weight cache (zero-egress: loads from $DL4J_TPU_CACHE/pretrained/<name>.zip
when present — the reference downloads+checksums from a CDN,
ZooModel.java:54-66).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Dict, Optional, Sequence, Tuple, Type

from ..nn.model import Graph, NetConfig, Sequential

CACHE_DIR = Path(os.environ.get("DL4J_TPU_CACHE", Path.home() / ".deeplearning4j_tpu")) / "pretrained"

ZOO_REGISTRY: Dict[str, Type["ZooModel"]] = {}


def register_model(cls):
    ZOO_REGISTRY[cls.__name__.lower()] = cls
    return cls


class ZooModel:
    """Base: subclasses define ``build() -> Sequential | Graph``."""

    name: str = "zoo"
    input_shape: Tuple[int, ...] = ()
    num_classes: int = 1000

    def __init__(self, num_classes: Optional[int] = None, seed: int = 12345,
                 input_shape: Optional[Tuple[int, ...]] = None, **kwargs):
        if num_classes is not None:
            self.num_classes = num_classes
        if input_shape is not None:
            self.input_shape = tuple(input_shape)
        self.seed = seed
        self.kwargs = kwargs

    def build(self):
        raise NotImplementedError

    def init(self):
        """ZooModel.init(): build + randomly initialize."""
        model = self.build()
        model.init()
        return model

    def pretrained_path(self, pretrained_type: str = "imagenet") -> Path:
        """THE pretrained checkpoint location: the standard model zip
        (config + params + updater + normalizer, train/serialization.py)
        under the cache dir — replacing the reference's CDN URL scheme
        (ZooModel.pretrainedUrl)."""
        return CACHE_DIR / f"{type(self).__name__.lower()}_{pretrained_type}.zip"

    def init_pretrained(self, pretrained_type: str = "imagenet",
                        auto_convert: bool = True):
        """initPretrained(PretrainedType) parity (ZooModel.java:51-81): load
        this entry's checkpoint from the cache, verifying the recorded
        sha256 (the reference md5-checks its CDN download and deletes on
        corruption). On a cache miss, ``auto_convert`` runs the
        keras.applications bridge (interop.pretrained) when this model has
        a mapping — that downloads the Keras weights where egress (or a
        warm ~/.keras cache) allows, converts through the golden-tested
        Keras importer, and publishes into the cache."""
        from ..interop.pretrained import ChecksumMismatch, verify_checksum

        path = self.pretrained_path(pretrained_type)
        verified = False
        if path.exists():
            try:
                verified = verify_checksum(path)
            except ChecksumMismatch:
                # reference parity (ZooModel.java:62-66): a corrupt cached
                # download is DELETED so the next step can re-fetch/convert.
                # Only on a genuine digest mismatch — a transient read error
                # (also an OSError) must not unlink a valid cache entry.
                path.unlink(missing_ok=True)
                Path(str(path) + ".sha256").unlink(missing_ok=True)
        # auto-convert only for weight sets Keras can actually supply —
        # other PretrainedTypes (mnist/cifar10/vggface) have no
        # keras.applications source and must come from save_pretrained
        if not path.exists() and auto_convert and pretrained_type == "imagenet":
            from ..interop.pretrained import (KERAS_APPLICATIONS,
                                              convert_keras_application)

            name = type(self).__name__.lower()
            if name in KERAS_APPLICATIONS:
                try:
                    convert_keras_application(name, weights=pretrained_type,
                                              pretrained_type=pretrained_type)
                except Exception as e:
                    raise FileNotFoundError(
                        f"No cached pretrained weights at {path}, and the "
                        f"keras.applications conversion failed "
                        f"({type(e).__name__}: {str(e)[:200]}). On an "
                        f"egress-less machine, warm ~/.keras/models first or "
                        f"copy a converted zip into the cache.") from e
        if not path.exists():
            raise FileNotFoundError(
                f"No cached pretrained weights at {path}. The reference downloads "
                f"from a CDN (ZooModel.java:54-66); this environment has no egress — "
                f"produce the zip with save_pretrained() or "
                f"interop.pretrained.convert_keras_application() to use "
                f"pretrained weights.")
        from ..train.serialization import load_model

        if not verified:  # fresh conversion above; head check already
            verify_checksum(path)  # hashed the warm-cache path once
        model, *_ = load_model(str(path))  # populates model.params/state
        return model

    def save_pretrained(self, model, pretrained_type: str = "imagenet") -> Path:
        """Publish `model`'s weights as this zoo entry's pretrained
        checkpoint (+ sha256 sidecar) — the producer side the reference
        lacks locally (its zips come only from the CDN). Round-trips with
        init_pretrained."""
        path = self.pretrained_path(pretrained_type)
        path.parent.mkdir(parents=True, exist_ok=True)
        from ..interop.pretrained import write_checksum
        from ..train.serialization import save_model

        save_model(str(path), model, params=model.params, state=model.state)
        write_checksum(path)
        return path


def model_by_name(name: str, **kwargs) -> ZooModel:
    """ModelSelector parity."""
    key = name.lower()
    if key not in ZOO_REGISTRY:
        raise ValueError(f"Unknown zoo model '{name}'. Known: {sorted(ZOO_REGISTRY)}")
    return ZOO_REGISTRY[key](**kwargs)
