"""Model zoo base — parity with ``zoo/ZooModel.java`` + ``zoo/ModelSelector.java``.

``ZooModel.init()`` builds the randomly-initialized network;
``init_pretrained()`` mirrors initPretrained(PretrainedType) with a local
weight cache (zero-egress: loads from $DL4J_TPU_CACHE/pretrained/<name>.zip
when present — the reference downloads+checksums from a CDN,
ZooModel.java:54-66).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Dict, Optional, Sequence, Tuple, Type

from ..nn.model import Graph, NetConfig, Sequential

CACHE_DIR = Path(os.environ.get("DL4J_TPU_CACHE", Path.home() / ".deeplearning4j_tpu")) / "pretrained"

ZOO_REGISTRY: Dict[str, Type["ZooModel"]] = {}


def register_model(cls):
    ZOO_REGISTRY[cls.__name__.lower()] = cls
    return cls


class ZooModel:
    """Base: subclasses define ``build() -> Sequential | Graph``."""

    name: str = "zoo"
    input_shape: Tuple[int, ...] = ()
    num_classes: int = 1000

    def __init__(self, num_classes: Optional[int] = None, seed: int = 12345,
                 input_shape: Optional[Tuple[int, ...]] = None, **kwargs):
        if num_classes is not None:
            self.num_classes = num_classes
        if input_shape is not None:
            self.input_shape = tuple(input_shape)
        self.seed = seed
        self.kwargs = kwargs

    def build(self):
        raise NotImplementedError

    def init(self):
        """ZooModel.init(): build + randomly initialize."""
        model = self.build()
        model.init()
        return model

    def init_pretrained(self, pretrained_type: str = "imagenet"):
        """initPretrained(PretrainedType) — local cache only (zero egress)."""
        path = CACHE_DIR / f"{type(self).__name__.lower()}_{pretrained_type}.zip"
        if not path.exists():
            raise FileNotFoundError(
                f"No cached pretrained weights at {path}. The reference downloads "
                f"from a CDN (ZooModel.java:54-66); this environment has no egress — "
                f"place a model zip there to use pretrained weights.")
        from ..train.serialization import load_model

        model, *_ = load_model(str(path))
        return model


def model_by_name(name: str, **kwargs) -> ZooModel:
    """ModelSelector parity."""
    key = name.lower()
    if key not in ZOO_REGISTRY:
        raise ValueError(f"Unknown zoo model '{name}'. Known: {sorted(ZOO_REGISTRY)}")
    return ZOO_REGISTRY[key](**kwargs)
