"""Flash attention — Pallas TPU kernel for the attention hot path.

The reference has no attention at all (DL4J 0.9 predates it; SURVEY.md §5);
this kernel serves the framework's transformer/long-context families, where
attention is the dominant non-matmul cost. Design per the Pallas TPU
playbook (/opt/skills/guides/pallas_guide.md):

- forward: ONE kernel, grid (B·H, T/bq, T/bk) with the key-block dimension
  innermost (sequential on TPU), streaming-softmax accumulators (m, l, acc)
  in VMEM scratch that persist across key blocks — O(T·block) memory, never
  a (T, T) score tensor in HBM
- scores accumulate in f32 regardless of input dtype (bf16-safe softmax,
  same contract as ``dot_product_attention``)
- backward: custom_vjp with the standard flash recomputation — the forward
  saves only (o, logsumexp); gradients are rebuilt q-block-by-q-block in a
  ``lax.scan`` (pure JAX: XLA already fuses the per-block matmul chain well,
  and the scan bounds memory the same way the kernel does)
- ``interpret=True`` automatically off-TPU, so the same code path is testable
  on the CPU mesh (pl.pallas_call interpreter mode)

Causal masking and right-padded sequences (T not a multiple of the block)
are handled with compile-time index masks.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, lens_ref, kmask_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale: float, causal: bool,
                bq: int, bk: int, t_actual: int, has_lens: bool,
                has_kmask: bool, window: int = 0):
    """Mosaic-friendly layout notes: the (m, l) running stats live in
    (bq, 128) lane-replicated VMEM scratch (TPU vectors are (8, 128) tiles —
    1-D per-row scalars don't lower); lse is written as a (bq, 1) column so
    the HBM output can be (BH, T, 1) with a legal (1, bq, 1) block.

    ``has_lens`` (static): per-example ragged lengths — keys at positions
    >= lens_ref's value are masked out (right-padded batches). The
    interior-block specialization stays: blocks fully inside the length
    run unmasked under a runtime predicate; blocks fully beyond it are
    skipped at runtime.

    ``has_kmask`` (static): exact arbitrary (B, T) key mask — every block
    takes the masked path (no contiguity to exploit), and p is masked
    directly (an all-masked block must contribute nothing, which the
    s=NEG_INF trick alone does not guarantee: exp(NEG_INF - NEG_INF)=1)."""
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)
    L = lens_ref[0, 0, 0] if has_lens else t_actual

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _accumulate(masked: bool):
        q = q_ref[0].astype(jnp.float32)         # (bq, D)
        k = k_ref[0].astype(jnp.float32)         # (bk, D)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale

        if masked:
            q_pos = iq * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = ik * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            valid = k_pos < t_actual             # right-padding mask
            if has_lens:
                valid = valid & (k_pos < L)      # ragged example length
            if has_kmask:
                valid = valid & (kmask_ref[0, 0] != 0)[None, :]
            if causal:
                valid = valid & (k_pos <= q_pos)
            if window:  # sliding window: q attends [q-window+1, q]
                valid = valid & (q_pos - k_pos < window)
            s = jnp.where(valid, s, NEG_INF)

        m_prev = m_scr[...]                      # (bq, 128) replicated
        l_prev = l_scr[...]
        row_max = jnp.max(s, axis=1, keepdims=True)          # (bq, 1)
        m_cur = jnp.maximum(m_prev, jnp.broadcast_to(row_max, m_prev.shape))
        alpha = jnp.exp(m_prev - m_cur)                      # (bq, 128)
        rep = m_cur.shape[1]  # scratch lane width (128 compiled; bq interp)
        if bk == rep:
            m_bk = m_cur
        elif bk > rep and bk % rep == 0:  # replicate per-row max across lanes
            m_bk = pltpu.repeat(m_cur, bk // rep, axis=1)
        else:  # interpret mode (tiny or odd blocks): plain broadcast works
            m_bk = jnp.broadcast_to(m_cur[:, :1], (m_cur.shape[0], bk))
        p = jnp.exp(s - m_bk)                                # (bq, bk)
        if masked:
            # a row whose every key so far is masked has m == NEG_INF, where
            # exp(s - m) = exp(0) = 1 for masked entries — zero p explicitly
            # (reachable with kmask, and with window x lengths on padding
            # rows whose window lies wholly beyond the example length)
            p = jnp.where(valid, p, 0.0)
        l_scr[...] = l_prev * alpha + jnp.broadcast_to(
            jnp.sum(p, axis=1, keepdims=True), l_prev.shape)
        # p is in [0, 1]: bf16 is plenty for the PV matmul operand (f32
        # accumulation via preferred_element_type) and halves MXU feed cost
        pv = lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        acc_scr[...] = (acc_scr[...]
                        * jnp.broadcast_to(alpha[:, :1], acc_scr.shape) + pv)
        m_scr[...] = m_cur

    # Block-level specialization: interior blocks (fully below the causal
    # diagonal, no right-padding, fully inside the ragged length) skip the
    # iota/compare/where mask entirely — the masked path only runs on
    # diagonal and tail blocks, saving ~1/3 of the VPU work that dominates
    # flash attention on TPU. With ragged lengths the interior test gains a
    # runtime predicate and blocks fully beyond the length are skipped.
    k_end = (ik + 1) * bk
    interior = (k_end <= t_actual) & (not has_kmask)  # kmask: no interior
    run = True
    if has_lens:
        interior = interior & (k_end <= L)
        run = ik * bk < L  # key block fully beyond this example: skip
    if causal:
        on_diag = k_end - 1 > iq * bq  # any k_pos could exceed some q_pos
        interior = interior & jnp.logical_not(on_diag)
        reachable = (ik * bk <= (iq + 1) * bq - 1) & run  # skip above-diagonal
        if window:
            # skip key blocks entirely behind every q row's window; a block
            # is interior only if its OLDEST (q, k) pair is still in-window
            reachable = reachable & (k_end - 1 >= iq * bq - (window - 1))
            interior = interior & ((iq + 1) * bq - 1 - ik * bk <= window - 1)
        pl.when(reachable & interior)(lambda: _accumulate(False))
        pl.when(reachable & jnp.logical_not(interior))(lambda: _accumulate(True))
    else:
        pl.when(run & interior)(lambda: _accumulate(False))
        pl.when(run & jnp.logical_not(interior))(lambda: _accumulate(True))

    @pl.when(ik == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[...][:, :1], 1e-30)            # (bq, 1)
        o_ref[0] = (acc_scr[...] / jnp.broadcast_to(l, acc_scr.shape)
                    ).astype(o_ref.dtype)
        lse_ref[0] = m_scr[...][:, :1] + jnp.log(l)


def _mask_operands(lens, kmask, BH, tp, pad):
    """(lens3, km3) pallas operands shared by the forward and backward
    calls — dummies when absent, so both directions keep ONE pallas_call
    signature and can never desynchronize their masking inputs."""
    if lens is None:
        lens = jnp.zeros((BH,), jnp.int32)
    lens3 = lens.reshape(BH, 1, 1)
    if kmask is None:
        km3 = jnp.zeros((BH, 1, tp), jnp.int8)
    else:
        km3 = jnp.pad(kmask.astype(jnp.int8), ((0, 0), (0, pad))
                      ).reshape(BH, 1, tp)
    return lens3, km3


def _flash_fwd(q, k, v, lens, kmask, scale: float, causal: bool, bq: int,
               bk: int, interpret: bool, has_lens: bool, has_kmask: bool,
               window: int = 0):
    import math

    BH, T, D = q.shape
    pad = (-T) % math.lcm(bq, bk)  # both grids must tile the padded length
    tp = T + pad
    if pad:
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0)))
    nq, nk = tp // bq, tp // bk
    lens3, km3 = _mask_operands(lens, kmask, BH, tp, pad)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, t_actual=T, has_lens=has_lens,
                               has_kmask=has_kmask, window=window)
    o, lse = pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, iq, ik: (bh, ik, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, iq, ik: (bh, ik, 0)),
            pl.BlockSpec((1, 1, 1), lambda bh, iq, ik: (bh, 0, 0)),   # lens
            pl.BlockSpec((1, 1, bk), lambda bh, iq, ik: (bh, 0, ik)),  # kmask
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, iq, ik: (bh, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, tp, D), q.dtype),
            jax.ShapeDtypeStruct((BH, tp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),  # running max m (lane-replicated)
            pltpu.VMEM((bq, 128), jnp.float32),  # running sum l (lane-replicated)
            pltpu.VMEM((bq, D), jnp.float32),    # unnormalized output acc
        ],
        # default scoped-VMEM budget is 16MB; large (512+) blocks with the
        # masked/unmasked branch specialization need a bit more headroom
        # (v5e has 128MB VMEM)
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=96 * 1024 * 1024),
        interpret=interpret,
    )(q, k, v, lens3, km3)
    return o[:, :T], lse[:, :T, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, lens, kmask, scale, causal, bq, bk, interpret, backward,
           window):
    o, _ = _flash_fwd(q, k, v, lens, kmask, scale, causal, bq, bk, interpret,
                      lens is not None, kmask is not None, window)
    return o


def _flash_vjp_fwd(q, k, v, lens, kmask, scale, causal, bq, bk, interpret,
                   backward, window):
    o, lse = _flash_fwd(q, k, v, lens, kmask, scale, causal, bq, bk,
                        interpret, lens is not None, kmask is not None,
                        window)
    return o, (q, k, v, lens, kmask, o, lse)


# Block cap for the Mosaic backward kernels (the backward keeps more live
# tiles than the forward, so its VMEM-optimal block is smaller; 512 measured
# best on v5e at T<=4096 — scripts/chip_flashbwd.py sweeps this).
BWD_BLOCK_CAP = 512


def _bwd_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *,
              scale, causal, masked, iq, ik, bq, bk, t_actual, L=None,
              kmask_row=None, window=0):
    """Shared FlashAttention-2 backward recomputation for both passes:
    returns (p, ds) with p = exp(s - lse) (masked) and
    ds = p * (do @ v^T - delta) * scale. ``L`` (traced scalar): ragged
    example length — keys >= L are masked like the forward. ``kmask_row``
    ((bk,) traced): exact key mask block, same forward parity."""
    q = q_ref[0].astype(jnp.float32)          # (bq, D)
    k = k_ref[0].astype(jnp.float32)          # (bk, D)
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    lse = lse_ref[0]                          # (bq, 1) f32
    p = jnp.exp(s - jnp.broadcast_to(lse, s.shape))
    if masked:
        q_pos = iq * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = ik * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        valid = k_pos < t_actual
        if L is not None:
            valid = valid & (k_pos < L)
        if kmask_row is not None:
            valid = valid & (kmask_row != 0)[None, :]
        if causal:
            valid = valid & (k_pos <= q_pos)
        if window:
            valid = valid & (q_pos - k_pos < window)
        p = jnp.where(valid, p, 0.0)
    do = do_ref[0].astype(jnp.float32)        # (bq, D)
    dp = lax.dot_general(do, v_ref[0].astype(jnp.float32),
                         (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)  # (bq, bk)
    ds = p * (dp - jnp.broadcast_to(delta_ref[0], dp.shape)) * scale
    return p, ds


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, lens_ref,
                   kmask_ref, dq_ref, dq_scr, *, scale: float, causal: bool,
                   bq: int, bk: int, t_actual: int, has_lens: bool,
                   has_kmask: bool, window: int = 0):
    """dQ pass: grid (BH, T/bq, T/bk), key blocks innermost sequential.
    Standard FlashAttention-2 recomputation: p = exp(s - lse);
    ds = p * (dp - delta) * scale; dq += ds @ k — accumulated in VMEM."""
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)
    L = lens_ref[0, 0, 0] if has_lens else None

    @pl.when(ik == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _accumulate(masked: bool):
        _, ds = _bwd_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          scale=scale, causal=causal, masked=masked,
                          iq=iq, ik=ik, bq=bq, bk=bk, t_actual=t_actual,
                          L=L if masked else None,
                          kmask_row=(kmask_ref[0, 0]
                                     if masked and has_kmask else None),
                          window=window if masked else 0)
        dq_scr[...] += lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    k_end = (ik + 1) * bk
    interior = (k_end <= t_actual) & (not has_kmask)
    run = True
    if has_lens:
        interior = interior & (k_end <= L)
        run = ik * bk < L  # key block fully beyond the length: dq += 0
    if causal:
        on_diag = k_end - 1 > iq * bq
        interior = interior & jnp.logical_not(on_diag)
        reachable = (ik * bk <= (iq + 1) * bq - 1) & run
        if window:
            reachable = reachable & (k_end - 1 >= iq * bq - (window - 1))
            interior = interior & ((iq + 1) * bq - 1 - ik * bk <= window - 1)
        pl.when(reachable & interior)(lambda: _accumulate(False))
        pl.when(reachable & jnp.logical_not(interior))(lambda: _accumulate(True))
    else:
        pl.when(run & interior)(lambda: _accumulate(False))
        pl.when(run & jnp.logical_not(interior))(lambda: _accumulate(True))

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, lens_ref,
                    kmask_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                    scale: float, causal: bool, bq: int, bk: int,
                    t_actual: int, has_lens: bool, has_kmask: bool,
                    window: int = 0):
    """dK/dV pass: grid (BH, T/bk, T/bq), query blocks innermost sequential.
    dv += p^T @ do; dk += ds^T @ q — both accumulated in VMEM. With ragged
    lengths, a key block fully beyond the length skips every accumulate, so
    its dk/dv finalize as the zeros _init wrote (padded keys get 0 grad —
    matching the dense key-masked oracle); a key block straddling the
    length forces the masked path regardless of the q block."""
    ik = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)
    L = lens_ref[0, 0, 0] if has_lens else None

    @pl.when(iq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _accumulate(masked: bool):
        p, ds = _bwd_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          scale=scale, causal=causal, masked=masked,
                          iq=iq, ik=ik, bq=bq, bk=bk, t_actual=t_actual,
                          L=L if masked else None,
                          kmask_row=(kmask_ref[0, 0]
                                     if masked and has_kmask else None),
                          window=window if masked else 0)
        # dv += p^T @ do ((bk, bq) @ (bq, D)); p in [0,1] — bf16 operand ok
        dv_scr[...] += lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[...] += lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    q_end = (iq + 1) * bq
    interior = (q_end <= t_actual) & (not has_kmask)
    run = True
    if has_lens:
        interior = interior & ((ik + 1) * bk <= L)  # key tail must mask
        run = ik * bk < L  # whole key block beyond length: keep zeros
    if causal:
        # diagonal touches this (ik, iq) pair unless the k block is fully
        # below every q row in the block
        on_diag = (ik + 1) * bk - 1 > iq * bq
        interior = interior & jnp.logical_not(on_diag)
        reachable = (q_end - 1 >= ik * bk) & run  # some q row sees this k
        if window:
            # some (q, k) pair still in-window for this block pair; interior
            # additionally needs the OLDEST pair in-window
            reachable = reachable & (iq * bq <= (ik + 1) * bk - 1 + window - 1)
            interior = interior & (q_end - 1 - ik * bk <= window - 1)
        pl.when(reachable & interior)(lambda: _accumulate(False))
        pl.when(reachable & jnp.logical_not(interior))(lambda: _accumulate(True))
    else:
        pl.when(run & interior)(lambda: _accumulate(False))
        pl.when(run & jnp.logical_not(interior))(lambda: _accumulate(True))

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, lens, kmask, o, lse, do, scale, causal, bq, bk,
                      interpret, window=0):
    """Kernel-based flash backward (FlashAttention-2 decomposition): one
    pallas_call for dq (k innermost), one for dk/dv (q innermost)."""
    import math

    BH, T, D = q.shape
    # more live tiles than the forward (q, k, v, do + p/ds): cap blocks to
    # stay inside VMEM (sweepable — see scripts/chip_flashbwd.py)
    bq, bk = min(bq, BWD_BLOCK_CAP), min(bk, BWD_BLOCK_CAP)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)       # (BH, T, 1)
    lse3 = lse[..., None]                          # (BH, T, 1)

    pad = (-T) % math.lcm(bq, bk)
    tp = T + pad
    if pad:
        zpad = ((0, 0), (0, pad), (0, 0))
        q, k, v, do = (jnp.pad(a, zpad) for a in (q, k, v, do))
        delta = jnp.pad(delta, zpad)
        lse3 = jnp.pad(lse3, zpad)
    nq, nk = tp // bq, tp // bk
    has_lens = lens is not None
    has_kmask = kmask is not None
    lens3, km3 = _mask_operands(lens, kmask, BH, tp, pad)

    common = dict(scale=scale, causal=causal, bq=bq, bk=bk, t_actual=T,
                  has_lens=has_lens, has_kmask=has_kmask, window=window)
    vmem = pltpu.CompilerParams(vmem_limit_bytes=96 * 1024 * 1024)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, iq, ik: (bh, iq, 0)),   # q
            pl.BlockSpec((1, bk, D), lambda bh, iq, ik: (bh, ik, 0)),   # k
            pl.BlockSpec((1, bk, D), lambda bh, iq, ik: (bh, ik, 0)),   # v
            pl.BlockSpec((1, bq, D), lambda bh, iq, ik: (bh, iq, 0)),   # do
            pl.BlockSpec((1, bq, 1), lambda bh, iq, ik: (bh, iq, 0)),   # lse
            pl.BlockSpec((1, bq, 1), lambda bh, iq, ik: (bh, iq, 0)),   # delta
            pl.BlockSpec((1, 1, 1), lambda bh, iq, ik: (bh, 0, 0)),     # lens
            pl.BlockSpec((1, 1, bk), lambda bh, iq, ik: (bh, 0, ik)),   # kmask
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda bh, iq, ik: (bh, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, tp, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=vmem,
        interpret=interpret,
    )(q, k, v, do, lse3, delta, lens3, km3)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, ik, iq: (bh, iq, 0)),   # q
            pl.BlockSpec((1, bk, D), lambda bh, ik, iq: (bh, ik, 0)),   # k
            pl.BlockSpec((1, bk, D), lambda bh, ik, iq: (bh, ik, 0)),   # v
            pl.BlockSpec((1, bq, D), lambda bh, ik, iq: (bh, iq, 0)),   # do
            pl.BlockSpec((1, bq, 1), lambda bh, ik, iq: (bh, iq, 0)),   # lse
            pl.BlockSpec((1, bq, 1), lambda bh, ik, iq: (bh, iq, 0)),   # delta
            pl.BlockSpec((1, 1, 1), lambda bh, ik, iq: (bh, 0, 0)),     # lens
            pl.BlockSpec((1, 1, bk), lambda bh, ik, iq: (bh, 0, ik)),   # kmask
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda bh, ik, iq: (bh, ik, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, ik, iq: (bh, ik, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, tp, D), k.dtype),
            jax.ShapeDtypeStruct((BH, tp, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=vmem,
        interpret=interpret,
    )(q, k, v, do, lse3, delta, lens3, km3)
    return dq[:, :T], dk[:, :T], dv[:, :T]


# Default backward implementation: "pallas" = the Mosaic kernels above,
# "xla" = the pure-JAX scan recomputation. The per-call ``backward=`` arg of
# ``flash_attention`` overrides this (and, being a nondiff static arg, keys
# the jit cache correctly — mutating the global alone cannot retrace an
# already-compiled function). Default stays "xla" until the Mosaic lowering
# of the backward kernels is validated on a real chip (interpret-mode tests
# prove numerics, not lowering) — flip after the on-chip A/B in PERF.md.
BACKWARD = "xla"


def _flash_vjp_bwd(scale, causal, bq, bk, interpret, backward, window, res,
                   do):
    if backward == "pallas":
        q, k, v, lens, kmask, o, lse = res
        dq, dk, dv = _flash_bwd_pallas(q, k, v, lens, kmask, o, lse, do,
                                       scale, causal, bq, bk, interpret,
                                       window)
        return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
                _lens_ct(lens), _lens_ct(kmask))
    return _flash_vjp_bwd_xla(scale, causal, bq, bk, interpret, window, res,
                              do)


def _flash_vjp_bwd_xla(scale, causal, bq, bk, interpret, window, res, do):
    """Flash backward: recompute probabilities per q block from (q, k, lse);
    scan over q blocks carrying (dk, dv) accumulators — peak memory
    O(bq·T), never (T, T)."""
    q, k, v, lens, kmask, o, lse = res
    BH, T, D = q.shape
    # Decoupled from the forward kernel's block width: the bwd is pure JAX
    # (XLA-fused, far less sensitive to block size than Mosaic) and its
    # per-step score tensor is O(BH·bq·T) — a 1024-wide fwd block would grow
    # bwd peak memory 8x over 128 and can OOM a backward whose forward fits.
    bq = min(bq, 256)
    qf, kf, vf = (a.astype(jnp.float32) for a in (q, k, v))
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)  # (BH, T)

    pad = (-T) % bq
    tp = T + pad
    nq = tp // bq
    qp = jnp.pad(qf, ((0, 0), (0, pad), (0, 0))).reshape(BH, nq, bq, D)
    dop = jnp.pad(dof, ((0, 0), (0, pad), (0, 0))).reshape(BH, nq, bq, D)
    lsep = jnp.pad(lse, ((0, 0), (0, pad)), constant_values=1.0).reshape(BH, nq, bq)
    deltap = jnp.pad(delta, ((0, 0), (0, pad))).reshape(BH, nq, bq)

    k_pos = jnp.arange(T)[None, :]                       # (1, T)

    def per_block(carry, xs):
        dk_acc, dv_acc = carry
        qb, dob, lseb, deltab, iq = xs                    # (BH, bq, D) ...
        s = jnp.einsum("bqd,bkd->bqk", qb, kf) * scale    # (BH, bq, T)
        q_pos = iq * bq + jnp.arange(bq)[:, None]         # (bq, 1)
        valid = jnp.broadcast_to(k_pos <= q_pos if causal
                                 else jnp.ones((bq, T), bool), (bq, T))[None]
        if lens is not None:  # ragged: keys >= example length masked out
            valid = valid & (k_pos[None] < lens[:, None, None])
        if kmask is not None:  # exact (BH, T) key mask
            valid = valid & (kmask != 0)[:, None, :]
        if window:  # sliding window: q attends [q-window+1, q]
            valid = valid & (q_pos - k_pos < window)[None]
        # padded q rows (q_pos >= T) contribute nothing: their do is 0-padded
        p = jnp.where(valid, jnp.exp(s - lseb[..., None]), 0.0)
        dv_acc = dv_acc + jnp.einsum("bqk,bqd->bkd", p, dob)
        dp = jnp.einsum("bqd,bkd->bqk", dob, vf)
        ds = p * (dp - deltab[..., None]) * scale
        dq_b = jnp.einsum("bqk,bkd->bqd", ds, kf)
        dk_acc = dk_acc + jnp.einsum("bqk,bqd->bkd", ds, qb)
        return (dk_acc, dv_acc), dq_b

    xs = (qp.transpose(1, 0, 2, 3), dop.transpose(1, 0, 2, 3),
          lsep.transpose(1, 0, 2), deltap.transpose(1, 0, 2),
          jnp.arange(nq))
    (dk, dv), dq_blocks = lax.scan(
        per_block, (jnp.zeros_like(kf), jnp.zeros_like(vf)), xs)
    dq = dq_blocks.transpose(1, 0, 2, 3).reshape(BH, tp, D)[:, :T]
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            _lens_ct(lens), _lens_ct(kmask))


def _lens_ct(a):
    """Cotangent for an integer input (lengths / key mask): float0 zeros
    (ints have no tangent space), or None when the input was absent."""
    return None if a is None else np.zeros(a.shape, jax.dtypes.float0)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None, block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    backward: Optional[str] = None,
                    lengths=None, key_mask=None,
                    window: Optional[int] = None):
    """Memory-efficient exact attention. q, k, v: (B, T, H, D) (the layout of
    ``dot_product_attention``); returns (B, T, H, D).

    Differentiable (custom flash VJP). Off-TPU the kernel runs in Pallas
    interpreter mode automatically, so CPU tests exercise the same code.

    ``lengths`` ((B,) int32, optional): ragged example lengths for
    RIGHT-PADDED batches — keys at positions >= lengths[b] are masked out
    for every query (the key-padding mask), forward and backward, without
    materializing a mask or falling back to dense attention. Equivalent to
    the dense path's 2-D key mask ``arange(T) < lengths[:, None]``. The
    fast ragged variant: blocks fully inside the length keep the unmasked
    specialization, blocks beyond it are skipped. ``lengths[b] == 0``
    (fully padded example) returns 0 for that row with zero gradients —
    the dense oracle's mean(v) for an all-masked softmax is equally
    meaningless there; mask the loss either way.

    ``key_mask`` ((B, T) bool/int, optional): EXACT arbitrary key mask —
    no contiguity assumption (left padding, mid-sequence holes). Every
    block takes the masked path, so prefer ``lengths`` when the batch is
    right-padded. Mutually exclusive with ``lengths``. Rows whose keys are
    ALL masked return 0 (the dense path returns mean(v) there — both are
    degenerate; mask the loss). Padded ROWS still emit (ignored) outputs.

    ``window`` (int, optional, causal only): sliding-window attention —
    query t attends keys [t-window+1, t]. Key blocks wholly behind the
    window are SKIPPED, so attention cost scales O(T·window) instead of
    O(T²/2): at T=64k with window=4k that is ~16x less attention work.
    Windowed calls default to ``backward="pallas"`` — the Mosaic backward
    skips out-of-window blocks too, while the XLA scan backward computes
    full-width scores and only masks (pass ``backward="xla"`` to override;
    correct, but no backward FLOPs saving). window >= T degrades to plain
    causal. Composes with lengths/key_mask.

    Default block sizes adapt to T, capped at 1024 — the measured optimum on
    v5e (T=4096 causal: ~21 TF/s at 1024x1024 or 2048x2048, 5x faster than
    dense attention and 4.5x faster than this kernel at its previous 128x128
    defaults; 4096-wide blocks spill VMEM and regress ~2x — see PERF.md).
    """
    B, T, H, D = q.shape
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(f"q/k/v shapes must match, got {q.shape} {k.shape} {v.shape}")
    if lengths is not None and key_mask is not None:
        raise ValueError("pass lengths OR key_mask, not both")
    if window is not None:
        if not causal:
            raise ValueError("window= requires causal=True (sliding-window "
                             "attention is a causal-LM construct)")
        window = int(window)  # host-side hyperparameter  # jaxlint: disable=host-sync
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if window >= T:
            window = None  # full causal attention; keep the fast path
    if lengths is not None:
        if lengths.shape != (B,):
            raise ValueError(f"lengths must be ({B},), got {lengths.shape}")
        # length 0 = fully padded example: every block is skipped, the row
        # outputs 0 and contributes zero gradients (same contract as an
        # all-masked key_mask row) — do NOT clamp to 1, which would
        # silently attend key 0 and diverge from the dense oracle
        lengths = jnp.clip(lengths.astype(jnp.int32), 0, T)
    if key_mask is not None:
        if key_mask.shape != (B, T):
            raise ValueError(f"key_mask must be ({B}, {T}), got {key_mask.shape}")
        key_mask = key_mask.astype(jnp.int8)
    if backward is not None:
        bw = backward
    elif window:
        # the O(T·window) claim needs block SKIPPING in the backward too;
        # the XLA scan backward computes full (bq, T) scores per q block
        # and only masks, so windowed calls default to the Mosaic backward
        # (chip-validated numerics; scripts/chip_flashbwd.py covers the
        # windowed case)
        bw = "pallas"
    else:
        bw = BACKWARD
    if bw not in ("pallas", "xla"):
        raise ValueError(f"backward must be 'pallas' or 'xla', got {bw!r}")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    # Python-float scale: embedded as an f32 scalar constant in the kernel —
    # an np.float64 here would silently promote the whole QK^T tree.
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(D)  # jaxlint: disable=host-sync
    if interpret:
        # interpreter mode has no tiling constraints: shrink blocks toward T
        # so CPU tests stay fast
        bq = min(block_q or 128, max(16, T))
        bk = min(block_k or 128, max(16, T))
    else:
        # compiled TPU path: 128-multiple block sizes; the lcm padding
        # absorbs odd T — Mosaic requires hardware-aligned (sublane x
        # 128-lane) block shapes, so never clamp to raw T
        t128 = -(-T // 128) * 128
        bq = block_q if block_q is not None else min(1024, t128)
        bk = block_k if block_k is not None else min(1024, t128)
        if bq % 128 or bk % 128:
            raise ValueError(f"block_q/block_k must be multiples of 128 on "
                             f"TPU, got {bq}/{bk}")

    def to_bh(a):
        return a.transpose(0, 2, 1, 3).reshape(B * H, T, D)

    lens_bh = None if lengths is None else jnp.repeat(lengths, H)
    km_bh = None if key_mask is None else jnp.repeat(key_mask, H, axis=0)
    o = _flash(to_bh(q), to_bh(k), to_bh(v), lens_bh, km_bh, scale, causal,
               bq, bk, interpret, bw, window or 0)
    return o.reshape(B, H, T, D).transpose(0, 2, 1, 3)
