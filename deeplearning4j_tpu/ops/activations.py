"""Activation functions — TPU-native equivalent of ND4J ``IActivation``.

Reference parity: DL4J exposes ~21 activations through the
``org.nd4j.linalg.activations.Activation`` enum (used 118x across
deeplearning4j-nn; see reference ``nn/conf/layers/*`` configs). Here each
activation is a pure ``jnp``-traced function registered by canonical name so
that configs serialize to JSON the same way DL4J's enum names do.

Unlike DL4J — where each activation is a separate JNI-dispatched kernel — all
of these fuse into surrounding matmuls/convs under XLA, so there is no
"activation layer kernel" cost on TPU.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp

Array = jax.Array

_REGISTRY: Dict[str, Callable[[Array], Array]] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name.lower()] = fn
        return fn

    return deco


def get(name_or_fn) -> Callable[[Array], Array]:
    """Resolve an activation by canonical name (case-insensitive) or pass through callables."""
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _REGISTRY:
        raise ValueError(
            f"Unknown activation '{name_or_fn}'. Known: {sorted(_REGISTRY)}"
        )
    return _REGISTRY[key]


def names():
    return sorted(_REGISTRY)


# --- the catalogue (parity with Activation enum) ---

register("identity")(lambda x: x)
register("linear")(lambda x: x)
register("relu")(jax.nn.relu)
register("relu6")(jax.nn.relu6)
register("sigmoid")(jax.nn.sigmoid)
register("tanh")(jnp.tanh)
register("softmax")(lambda x: jax.nn.softmax(x, axis=-1))
register("softplus")(jax.nn.softplus)
register("softsign")(jax.nn.soft_sign)
register("elu")(jax.nn.elu)
register("selu")(jax.nn.selu)
register("gelu")(jax.nn.gelu)
register("swish")(jax.nn.swish)
register("silu")(jax.nn.silu)
register("mish")(lambda x: x * jnp.tanh(jax.nn.softplus(x)))
register("hardsigmoid")(jax.nn.hard_sigmoid)
register("hardtanh")(lambda x: jnp.clip(x, -1.0, 1.0))
register("cube")(lambda x: x * x * x)
register("rational_tanh")(
    # DL4J RationalTanh: 1.7159 * tanh(2x/3) approximated rationally; we use the
    # exact scaled tanh, which is the function it approximates.
    lambda x: 1.7159 * jnp.tanh(2.0 / 3.0 * x)
)
register("rectified_tanh")(lambda x: jnp.maximum(0.0, jnp.tanh(x)))
register("sin")(jnp.sin)
register("exp")(jnp.exp)


@register("leakyrelu")
def leaky_relu(x: Array, alpha: float = 0.01) -> Array:
    return jax.nn.leaky_relu(x, negative_slope=alpha)


@register("rrelu")
def rrelu(x: Array, lower: float = 1.0 / 8, upper: float = 1.0 / 3) -> Array:
    # Deterministic (inference-mode) RReLU: slope = mean of the range.
    return jax.nn.leaky_relu(x, negative_slope=(lower + upper) / 2.0)


@register("thresholdedrelu")
def thresholded_relu(x: Array, theta: float = 1.0) -> Array:
    return jnp.where(x > theta, x, 0.0)


def softmax_stable(x: Array, axis: int = -1) -> Array:
    """Numerically-stable softmax used by loss layers (log-sum-exp shifted)."""
    return jax.nn.softmax(x, axis=axis)
