"""Dropout variants, weight noise, and weight constraints.

Reference parity:
- ``nn/conf/dropout/`` (5): Dropout, AlphaDropout, GaussianDropout,
  GaussianNoise, SpatialDropout.
- ``nn/conf/weightnoise/`` (3): WeightNoise (additive/multiplicative),
  DropConnect.
- ``nn/conf/constraint/`` (5): MaxNormConstraint, MinMaxNormConstraint,
  NonNegativeConstraint, UnitNormConstraint (applied post-update).

All dropout ops are pure functions of an explicit PRNG key (JAX functional
randomness replaces ND4J's stateful RNG); constraints are pytree maps applied
after the optax update, matching DL4J's ``applyConstraints`` at
``StochasticGradientDescent.java:96``.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

Array = jax.Array


# --- dropout (activation noise) ---

def dropout(key, x: Array, rate: float, training: bool = True) -> Array:
    """Inverted dropout. DL4J configs give *retain* prob; callers convert (rate = 1-p)."""
    if not training or rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


def spatial_dropout(key, x: Array, rate: float, training: bool = True) -> Array:
    """Drop whole feature maps (NHWC: mask over channel axis only)."""
    if not training or rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask_shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
    mask = jax.random.bernoulli(key, keep, mask_shape)
    return jnp.where(mask, x / keep, 0.0)


def alpha_dropout(key, x: Array, rate: float, training: bool = True) -> Array:
    """SELU-compatible dropout (Klambauer et al.) — keeps self-normalizing stats."""
    if not training or rate <= 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha_p * (1 - keep)
    return a * jnp.where(mask, x, alpha_p) + b


def gaussian_dropout(key, x: Array, rate: float, training: bool = True) -> Array:
    """Multiplicative N(1, rate/(1-rate)) noise."""
    if not training or rate <= 0.0:
        return x
    std = math.sqrt(rate / (1.0 - rate))
    return x * (1.0 + std * jax.random.normal(key, x.shape, x.dtype))


def gaussian_noise(key, x: Array, stddev: float, training: bool = True) -> Array:
    if not training or stddev <= 0.0:
        return x
    return x + stddev * jax.random.normal(key, x.shape, x.dtype)


DROPOUTS: Dict[str, Callable] = {
    "dropout": dropout,
    "spatial": spatial_dropout,
    "alpha": alpha_dropout,
    "gaussian_dropout": gaussian_dropout,
    "gaussian_noise": gaussian_noise,
}


def apply_dropout_config(key, x: Array, cfg, training: bool) -> Array:
    """cfg: float (dropout rate) or {"type": name, ...kwargs}."""
    if cfg is None:
        return x
    if isinstance(cfg, (int, float)):  # guarded: cfg is a host-side number
        return dropout(key, x, float(cfg), training)  # jaxlint: disable=host-sync
    cfg = dict(cfg)
    kind = cfg.pop("type")
    return DROPOUTS[kind](key, x, training=training, **cfg)


# --- weight noise (applied to params before forward) ---

def weight_noise(key, params, stddev: float = 0.01, additive: bool = True, training: bool = True):
    """WeightNoise: perturb params for one forward pass (not persisted)."""
    if not training or stddev <= 0.0:
        return params
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(key, len(leaves))
    if additive:
        noised = [p + stddev * jax.random.normal(k, p.shape, p.dtype) for p, k in zip(leaves, keys)]
    else:
        noised = [p * (1.0 + stddev * jax.random.normal(k, p.shape, p.dtype)) for p, k in zip(leaves, keys)]
    return jax.tree_util.tree_unflatten(treedef, noised)


def drop_connect(key, params, rate: float = 0.5, training: bool = True):
    """DropConnect: bernoulli-mask weights for one forward pass."""
    if not training or rate <= 0.0:
        return params
    keep = 1.0 - rate
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(key, len(leaves))
    dropped = [jnp.where(jax.random.bernoulli(k, keep, p.shape), p / keep, 0.0)
               for p, k in zip(leaves, keys)]
    return jax.tree_util.tree_unflatten(treedef, dropped)


# --- weight constraints (post-update projections) ---

def max_norm(w: Array, max_val: float = 2.0, axis=0) -> Array:
    norms = jnp.sqrt(jnp.sum(jnp.square(w), axis=axis, keepdims=True))
    return w * jnp.minimum(1.0, max_val / jnp.maximum(norms, 1e-8))


def min_max_norm(w: Array, min_val: float = 0.0, max_val: float = 1.0, rate: float = 1.0, axis=0) -> Array:
    norms = jnp.sqrt(jnp.sum(jnp.square(w), axis=axis, keepdims=True))
    clipped = jnp.clip(norms, min_val, max_val)
    target = rate * clipped + (1.0 - rate) * norms
    return w * (target / jnp.maximum(norms, 1e-8))


def unit_norm(w: Array, axis=0) -> Array:
    norms = jnp.sqrt(jnp.sum(jnp.square(w), axis=axis, keepdims=True))
    return w / jnp.maximum(norms, 1e-8)


def non_negative(w: Array) -> Array:
    return jnp.maximum(w, 0.0)


CONSTRAINTS: Dict[str, Callable] = {
    "max_norm": max_norm,
    "min_max_norm": min_max_norm,
    "unit_norm": unit_norm,
    "non_negative": non_negative,
}


def apply_constraint_config(w: Array, cfg) -> Array:
    if cfg is None:
        return w
    if isinstance(cfg, str):
        return CONSTRAINTS[cfg](w)
    cfg = dict(cfg)
    kind = cfg.pop("type")
    return CONSTRAINTS[kind](w, **cfg)
