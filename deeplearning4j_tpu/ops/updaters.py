"""Updaters — parity with the 10 ND4J ``IUpdater`` implementations, on optax.

Reference: ``org.nd4j.linalg.learning.config.*`` — Sgd (36 uses), Adam (13),
AMSGrad, Nesterovs, RmsProp, AdaGrad, AdaDelta, AdaMax, Nadam, NoOp — applied
block-wise by ``nn/updater/BaseMultiLayerUpdater.java`` over views of the
flattened gradient. The TPU design replaces the mutable flattened-view model
with optax GradientTransformations over the param pytree; XLA fuses the whole
update into a handful of kernels, and per-layer updater overrides become an
``optax.multi_transform`` over a label pytree (see build_multi).

Gradient normalization (GradientNormalization enum in layer configs:
RenormalizeL2PerLayer/PerParamType, ClipElementWiseAbsoluteValue,
ClipL2PerLayer, ClipL2PerParamType) maps to chained transforms here.

All hyperparameters accept either a float or a schedule (ops/schedules.py),
mirroring DL4J's ``ISchedule`` support on learning rate / momentum.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Union

import jax
import jax.numpy as jnp
import optax

from . import schedules as sched

ScalarOrSchedule = Union[float, Callable]

_REGISTRY: Dict[str, Callable[..., optax.GradientTransformation]] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name.lower()] = fn
        return fn

    return deco


def names():
    return sorted(_REGISTRY)


def _lr(learning_rate: ScalarOrSchedule):
    return sched.from_config(learning_rate) if not callable(learning_rate) else learning_rate


@register("sgd")
def sgd(learning_rate: ScalarOrSchedule = 1e-1, **_):
    return optax.sgd(_lr(learning_rate))


@register("nesterovs")
def nesterovs(learning_rate: ScalarOrSchedule = 1e-1, momentum: float = 0.9, **_):
    return optax.sgd(_lr(learning_rate), momentum=momentum, nesterov=True)


@register("adam")
def adam(learning_rate: ScalarOrSchedule = 1e-3, beta1: float = 0.9, beta2: float = 0.999,
         epsilon: float = 1e-8, **_):
    return optax.adam(_lr(learning_rate), b1=beta1, b2=beta2, eps=epsilon)


@register("adamw")
def adamw(learning_rate: ScalarOrSchedule = 1e-3, beta1: float = 0.9, beta2: float = 0.999,
          epsilon: float = 1e-8, weight_decay: float = 1e-2, **_):
    # Not in DL4J 0.9; standard for the transformer models this framework adds.
    return optax.adamw(_lr(learning_rate), b1=beta1, b2=beta2, eps=epsilon, weight_decay=weight_decay)


@register("amsgrad")
def amsgrad(learning_rate: ScalarOrSchedule = 1e-3, beta1: float = 0.9, beta2: float = 0.999,
            epsilon: float = 1e-8, **_):
    return optax.amsgrad(_lr(learning_rate), b1=beta1, b2=beta2, eps=epsilon)


@register("adamax")
def adamax(learning_rate: ScalarOrSchedule = 2e-3, beta1: float = 0.9, beta2: float = 0.999,
           epsilon: float = 1e-8, **_):
    return optax.adamax(_lr(learning_rate), b1=beta1, b2=beta2, eps=epsilon)


@register("nadam")
def nadam(learning_rate: ScalarOrSchedule = 1e-3, beta1: float = 0.9, beta2: float = 0.999,
          epsilon: float = 1e-8, **_):
    return optax.nadam(_lr(learning_rate), b1=beta1, b2=beta2, eps=epsilon)


@register("adagrad")
def adagrad(learning_rate: ScalarOrSchedule = 1e-1, epsilon: float = 1e-6, **_):
    return optax.adagrad(_lr(learning_rate), eps=epsilon)


@register("adadelta")
def adadelta(rho: float = 0.95, epsilon: float = 1e-6, **_):
    return optax.adadelta(learning_rate=1.0, rho=rho, eps=epsilon)


@register("rmsprop")
def rmsprop(learning_rate: ScalarOrSchedule = 1e-1, rms_decay: float = 0.95,
            epsilon: float = 1e-8, **_):
    return optax.rmsprop(_lr(learning_rate), decay=rms_decay, eps=epsilon)


@register("noop")
def noop(**_):
    return optax.set_to_zero()


# --- gradient normalization (GradientNormalization enum) ---

def renormalize_l2_per_layer() -> optax.GradientTransformation:
    """Divide each layer's gradients by the layer-wide L2 norm."""

    def update(updates, state, params=None):
        def norm_layer(layer):
            leaves = jax.tree_util.tree_leaves(layer)
            n = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
            return jax.tree.map(lambda g: g / jnp.maximum(n, 1e-8), layer)

        # "layer" = top-level entry of the params dict.
        if isinstance(updates, dict):
            return {k: norm_layer(v) for k, v in updates.items()}, state
        return norm_layer(updates), state

    return optax.GradientTransformation(lambda params: optax.EmptyState(), update)


def renormalize_l2_per_param() -> optax.GradientTransformation:
    def update(updates, state, params=None):
        return jax.tree.map(lambda g: g / jnp.maximum(jnp.linalg.norm(g.ravel()), 1e-8), updates), state

    return optax.GradientTransformation(lambda params: optax.EmptyState(), update)


def clip_elementwise(threshold: float) -> optax.GradientTransformation:
    return optax.clip(threshold)


def clip_l2_per_layer(threshold: float) -> optax.GradientTransformation:
    def update(updates, state, params=None):
        def clip_layer(layer):
            leaves = jax.tree_util.tree_leaves(layer)
            n = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
            scale = jnp.minimum(1.0, threshold / jnp.maximum(n, 1e-8))
            return jax.tree.map(lambda g: g * scale, layer)

        if isinstance(updates, dict):
            return {k: clip_layer(v) for k, v in updates.items()}, state
        return clip_layer(updates), state

    return optax.GradientTransformation(lambda params: optax.EmptyState(), update)


def clip_l2_per_param(threshold: float) -> optax.GradientTransformation:
    def update(updates, state, params=None):
        def clip(g):
            n = jnp.linalg.norm(g.ravel())
            return g * jnp.minimum(1.0, threshold / jnp.maximum(n, 1e-8))

        return jax.tree.map(clip, updates), state

    return optax.GradientTransformation(lambda params: optax.EmptyState(), update)


_GRADNORM = {
    "renormalizel2perlayer": lambda t: renormalize_l2_per_layer(),
    "renormalizel2perparamtype": lambda t: renormalize_l2_per_param(),
    "clipelementwiseabsolutevalue": clip_elementwise,
    "clipl2perlayer": clip_l2_per_layer,
    "clipl2perparamtype": clip_l2_per_param,
}


def build(config: Union[str, dict, optax.GradientTransformation],
          gradient_normalization: Optional[str] = None,
          gradient_normalization_threshold: float = 1.0,
          l1: float = 0.0, l2: float = 0.0) -> optax.GradientTransformation:
    """Build the full update pipeline from a JSON-able updater config.

    Order (parity with BaseMultiLayerUpdater.preApply + regularization):
    L1/L2 penalty gradients -> gradient normalization -> updater math.
    """
    chain = []
    if l2:
        chain.append(optax.add_decayed_weights(l2))
    if l1:
        def add_l1(updates, state, params=None):
            return jax.tree.map(lambda g, p: g + l1 * jnp.sign(p), updates, params), state

        chain.append(optax.GradientTransformation(lambda p: optax.EmptyState(), add_l1))
    if gradient_normalization and gradient_normalization.lower() != "none":
        key = gradient_normalization.lower().replace("_", "")
        if key not in _GRADNORM:
            raise ValueError(f"Unknown gradient normalization '{gradient_normalization}'")
        chain.append(_GRADNORM[key](gradient_normalization_threshold))

    if isinstance(config, optax.GradientTransformation):
        chain.append(config)
    elif isinstance(config, str):
        chain.append(_REGISTRY[config.lower()]())
    else:
        cfg = dict(config)
        kind = cfg.pop("type")
        if "lr" in cfg:  # common alias; was silently swallowed by **_ before
            cfg["learning_rate"] = cfg.pop("lr")
        factory = _REGISTRY[kind.lower()]
        import inspect

        known = set(inspect.signature(factory).parameters)
        unknown = set(cfg) - known
        if unknown:  # every factory takes **_, so unknown keys would be
            import logging  # silently dropped — a config typo must be loud

            logging.getLogger(__name__).warning(
                "updater '%s': ignoring unknown config keys %s (known: %s)",
                kind, sorted(unknown), sorted(known - {"_"}))
        chain.append(factory(**cfg))
    return optax.chain(*chain) if len(chain) > 1 else chain[0]


def build_multi(label_fn: Callable[[Any], Any], transforms: Dict[str, optax.GradientTransformation]):
    """Per-layer updater overrides (DL4J allows a different IUpdater per layer)."""
    return optax.multi_transform(transforms, label_fn)
