"""Weight initialization schemes — parity with DL4J ``WeightInit`` (21 schemes).

Reference: ``nn/weights/WeightInit.java:68-72`` lists ZERO, ONES, SIGMOID_UNIFORM,
NORMAL, LECUN_NORMAL, UNIFORM, XAVIER, XAVIER_UNIFORM, XAVIER_FAN_IN,
XAVIER_LEGACY, RELU, RELU_UNIFORM, IDENTITY, LECUN_UNIFORM, VAR_SCALING_*
(6 variants), DISTRIBUTION.

Each scheme is a function ``(key, shape, fan_in, fan_out, dtype) -> Array``.
fan_in/fan_out are passed explicitly because DL4J computes them from layer
semantics (e.g. convs use kernel receptive field), not raw shape.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

Array = jax.Array

_REGISTRY: Dict[str, Callable] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name.lower()] = fn
        return fn

    return deco


def get(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown weight init '{name_or_fn}'. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def names():
    return sorted(_REGISTRY)


def compute_fans(shape: Sequence[int], kind: str = "dense"):
    """fan_in/fan_out following DL4J conventions.

    dense:  (in, out) -> fan_in=in, fan_out=out
    conv:   (kh, kw, in, out) [HWIO] -> fan_in=kh*kw*in, fan_out=kh*kw*out
    """
    shape = tuple(int(s) for s in shape)  # static dims, host-side  # jaxlint: disable=host-sync
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = math.prod(shape[:-2])
    return receptive * shape[-2], receptive * shape[-1]


register("zero")(lambda key, shape, fan_in, fan_out, dtype=jnp.float32: jnp.zeros(shape, dtype))
register("zeros")(lambda key, shape, fan_in, fan_out, dtype=jnp.float32: jnp.zeros(shape, dtype))
register("ones")(lambda key, shape, fan_in, fan_out, dtype=jnp.float32: jnp.ones(shape, dtype))


@register("normal")
def normal(key, shape, fan_in, fan_out, dtype=jnp.float32):
    # DL4J NORMAL: N(0, 1/sqrt(fan_in)) — note std not variance.
    return jax.random.normal(key, shape, dtype) / jnp.sqrt(jnp.asarray(fan_in, dtype))


@register("uniform")
def uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    # DL4J UNIFORM: U(-a, a), a = sqrt(3/fan_in)
    a = math.sqrt(3.0 / fan_in)
    return jax.random.uniform(key, shape, dtype, -a, a)


@register("xavier")
def xavier(key, shape, fan_in, fan_out, dtype=jnp.float32):
    # Glorot normal: N(0, 2/(fan_in+fan_out)) variance.
    std = math.sqrt(2.0 / (fan_in + fan_out))
    return jax.random.normal(key, shape, dtype) * std


@register("xavier_uniform")
def xavier_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, -a, a)


@register("xavier_fan_in")
def xavier_fan_in(key, shape, fan_in, fan_out, dtype=jnp.float32):
    std = math.sqrt(1.0 / fan_in)
    return jax.random.normal(key, shape, dtype) * std


@register("xavier_legacy")
def xavier_legacy(key, shape, fan_in, fan_out, dtype=jnp.float32):
    # DL4J's historical variant: variance 1/(fan_in+fan_out).
    std = math.sqrt(1.0 / (fan_in + fan_out))
    return jax.random.normal(key, shape, dtype) * std


@register("relu")
def relu_init(key, shape, fan_in, fan_out, dtype=jnp.float32):
    # He normal: N(0, 2/fan_in) variance.
    std = math.sqrt(2.0 / fan_in)
    return jax.random.normal(key, shape, dtype) * std


@register("relu_uniform")
def relu_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = math.sqrt(6.0 / fan_in)
    return jax.random.uniform(key, shape, dtype, -a, a)


@register("lecun_normal")
def lecun_normal(key, shape, fan_in, fan_out, dtype=jnp.float32):
    std = math.sqrt(1.0 / fan_in)
    return jax.random.normal(key, shape, dtype) * std


@register("lecun_uniform")
def lecun_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = math.sqrt(3.0 / fan_in)
    return jax.random.uniform(key, shape, dtype, -a, a)


@register("sigmoid_uniform")
def sigmoid_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, -a, a)


@register("identity")
def identity_init(key, shape, fan_in, fan_out, dtype=jnp.float32):
    if len(shape) == 2 and shape[0] == shape[1]:
        return jnp.eye(shape[0], dtype=dtype)
    # Conv identity: delta kernel at spatial center.
    if len(shape) >= 3 and shape[-2] == shape[-1]:
        w = jnp.zeros(shape, dtype)
        center = tuple(s // 2 for s in shape[:-2])
        eye = jnp.eye(shape[-1], dtype=dtype)
        return w.at[center].set(eye)
    raise ValueError(f"IDENTITY init requires square weights, got {shape}")


def _var_scaling(key, shape, scale_mode, distribution, fan_in, fan_out, dtype):
    if scale_mode == "fan_in":
        n = fan_in
    elif scale_mode == "fan_out":
        n = fan_out
    else:
        n = (fan_in + fan_out) / 2.0
    if distribution == "normal":
        return jax.random.normal(key, shape, dtype) * math.sqrt(1.0 / n)
    a = math.sqrt(3.0 / n)
    return jax.random.uniform(key, shape, dtype, -a, a)


for _mode in ("fan_in", "fan_out", "fan_avg"):
    for _dist in ("normal", "uniform"):
        _name = f"var_scaling_{_mode}_{_dist}"

        def _make(mode=_mode, dist=_dist):
            def fn(key, shape, fan_in, fan_out, dtype=jnp.float32):
                return _var_scaling(key, shape, mode, dist, fan_in, fan_out, dtype)

            return fn

        register(_name)(_make())


def distribution(dist_name: str, **kwargs):
    """WeightInit.DISTRIBUTION — arbitrary parameterized distribution.

    Supported: normal(mean,std), uniform(lower,upper), truncated_normal(mean,std),
    constant(value), orthogonal(gain), binomial(p) — parity with nn/conf/distribution/.
    """
    dist_name = dist_name.lower()

    def fn(key, shape, fan_in, fan_out, dtype=jnp.float32):
        if dist_name == "normal" or dist_name == "gaussian":
            return kwargs.get("mean", 0.0) + jax.random.normal(key, shape, dtype) * kwargs.get("std", 1.0)
        if dist_name == "uniform":
            return jax.random.uniform(key, shape, dtype, kwargs.get("lower", -1.0), kwargs.get("upper", 1.0))
        if dist_name == "truncated_normal":
            return kwargs.get("mean", 0.0) + jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype) * kwargs.get("std", 1.0)
        if dist_name == "constant":
            return jnp.full(shape, kwargs.get("value", 0.0), dtype)
        if dist_name == "orthogonal":
            return jax.nn.initializers.orthogonal(scale=kwargs.get("gain", 1.0))(key, shape, dtype)
        if dist_name == "binomial":
            return jax.random.bernoulli(key, kwargs.get("p", 0.5), shape).astype(dtype)
        raise ValueError(f"Unknown distribution '{dist_name}'")

    return fn


def init_param(key, scheme, shape, kind: str = "dense", dtype=jnp.float32,
               fan_in: Optional[int] = None, fan_out: Optional[int] = None) -> Array:
    """Initialize one parameter tensor using a named scheme."""
    fi, fo = compute_fans(shape, kind)
    fn = get(scheme)
    return fn(key, tuple(shape), fan_in or fi, fan_out or fo, dtype)
