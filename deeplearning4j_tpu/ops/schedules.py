"""Learning-rate / value schedules — parity with ND4J ``ISchedule``.

Reference: ``org.nd4j.linalg.schedule.*`` (Exponential, Inverse, Map, Poly,
Sigmoid, Step schedules) consumed by layer configs via
``.learningRateSchedule(...)``. On TPU these are pure functions of the step
counter evaluated inside the jitted update (optax-compatible: ``f(count) ->
scalar``), so schedule changes never trigger recompilation.

DL4J schedules take a ``ScheduleType`` of ITERATION or EPOCH; we express
everything in iterations and provide ``per_epoch(steps_per_epoch)`` wrapping.
"""

from __future__ import annotations

from typing import Callable, Dict, Sequence, Tuple, Union

import jax.numpy as jnp

Schedule = Callable  # (count) -> value
ScalarOrSchedule = Union[float, Schedule]


def constant(value: float) -> Schedule:
    return lambda count: jnp.asarray(value, jnp.float32)


def exponential(initial: float, gamma: float) -> Schedule:
    """value = initial * gamma^iter (ExponentialSchedule)."""
    return lambda count: initial * jnp.power(gamma, jnp.asarray(count, jnp.float32))


def inverse(initial: float, gamma: float, power: float) -> Schedule:
    """value = initial / (1 + gamma*iter)^power (InverseSchedule)."""
    return lambda count: initial / jnp.power(1.0 + gamma * jnp.asarray(count, jnp.float32), power)


def poly(initial: float, power: float, max_iter: int) -> Schedule:
    """value = initial * (1 - iter/maxIter)^power (PolySchedule)."""

    def fn(count):
        frac = jnp.clip(jnp.asarray(count, jnp.float32) / max_iter, 0.0, 1.0)
        return initial * jnp.power(1.0 - frac, power)

    return fn


def sigmoid_schedule(initial: float, gamma: float, step_size: int) -> Schedule:
    """value = initial / (1 + exp(-gamma*(iter - stepSize))) (SigmoidSchedule)."""
    return lambda count: initial / (1.0 + jnp.exp(-gamma * (jnp.asarray(count, jnp.float32) - step_size)))


def step_schedule(initial: float, decay_rate: float, step_size: int) -> Schedule:
    """value = initial * decayRate^floor(iter/step) (StepSchedule)."""
    return lambda count: initial * jnp.power(decay_rate, jnp.floor(jnp.asarray(count, jnp.float32) / step_size))


def map_schedule(values: Dict[int, float]) -> Schedule:
    """Piecewise-constant from {iteration: value} (MapSchedule). Jit-safe."""
    boundaries = sorted(values)
    vals = [values[b] for b in boundaries]

    def fn(count):
        c = jnp.asarray(count, jnp.float32)
        out = jnp.asarray(vals[0], jnp.float32)
        for b, v in zip(boundaries, vals):
            out = jnp.where(c >= b, v, out)
        return out

    return fn


def warmup_cosine(peak: float, warmup_steps: int, total_steps: int, end_value: float = 0.0) -> Schedule:
    """TPU-native extra: linear warmup + cosine decay (not in DL4J 0.9 but the
    modern default for the transformer/long-context models we add)."""

    def fn(count):
        c = jnp.asarray(count, jnp.float32)
        warm = peak * c / jnp.maximum(warmup_steps, 1)
        frac = jnp.clip((c - warmup_steps) / jnp.maximum(total_steps - warmup_steps, 1), 0.0, 1.0)
        cos = end_value + 0.5 * (peak - end_value) * (1.0 + jnp.cos(jnp.pi * frac))
        return jnp.where(c < warmup_steps, warm, cos)

    return fn


def per_epoch(schedule: Schedule, steps_per_epoch: int) -> Schedule:
    """Evaluate an epoch-based schedule from the iteration counter (ScheduleType.EPOCH)."""
    return lambda count: schedule(jnp.asarray(count) // steps_per_epoch)


_BUILDERS = {
    "constant": constant,
    "exponential": exponential,
    "inverse": inverse,
    "poly": poly,
    "sigmoid": sigmoid_schedule,
    "step": step_schedule,
    "map": map_schedule,
    "warmup_cosine": warmup_cosine,
}


def from_config(cfg: Union[float, dict, Schedule]) -> Schedule:
    """Build a schedule from JSON-able config: {"type": "step", "initial": .1, ...}."""
    if callable(cfg):
        return cfg
    if isinstance(cfg, (int, float)):
        return constant(cfg)  # constant() casts to f32 on device
    cfg = dict(cfg)
    kind = cfg.pop("type")
    return _BUILDERS[kind](**cfg)
