"""Loss functions — parity with ND4J ``ILossFunction`` (~15 losses).

Reference: ``org.nd4j.linalg.lossfunctions.LossFunctions`` (86 imports across
deeplearning4j-nn): MCXENT, NEGATIVELOGLIKELIHOOD, XENT, MSE, L1, L2, MAE,
RMSE_XENT, HINGE, SQUARED_HINGE, KL_DIVERGENCE, MEAN_ABSOLUTE_PERCENTAGE_ERROR,
MEAN_SQUARED_LOGARITHMIC_ERROR, POISSON, COSINE_PROXIMITY + CenterLoss
(nn/conf/layers/CenterLossOutputLayer.java).

Each loss is ``fn(predictions, labels, mask=None, weights=None) -> scalar``
computing the *mean over examples* of the *sum over output units* — DL4J's
``computeScore(average=True)`` convention. ``predictions`` are
post-activation values (the Output layer applies its activation first), except
the ``*_logits`` variants which fuse activation+loss for numerical stability —
the preferred TPU path, fused by XLA into one kernel.

Masks broadcast against the per-example score: shape (B,) or (B, T) for time
series (DL4J per-timestep masking, see MaskedReductionUtil).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

Array = jax.Array
_EPS = 1e-7

_REGISTRY: Dict[str, Callable] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name.lower()] = fn
        return fn

    return deco


def get(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown loss '{name_or_fn}'. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def names():
    return sorted(_REGISTRY)


def _reduce(per_unit: Array, mask: Optional[Array], weights: Optional[Array]) -> Array:
    """Sum over the feature axis, mask per example/timestep, mean over the rest."""
    if weights is not None:
        per_unit = per_unit * weights
    per_example = jnp.sum(per_unit, axis=-1)
    if mask is None:
        return jnp.mean(per_example)
    mask = mask.astype(per_example.dtype)
    mask = jnp.broadcast_to(mask.reshape(mask.shape + (1,) * (per_example.ndim - mask.ndim)), per_example.shape)
    total = jnp.sum(mask)
    return jnp.sum(per_example * mask) / jnp.maximum(total, 1.0)


@register("mse")
@register("squared_loss")
def mse(p, y, mask=None, weights=None):
    return _reduce(jnp.square(p - y), mask, weights)


@register("l2")
def l2(p, y, mask=None, weights=None):
    # DL4J L2 = sum of squared diffs (no 1/n over outputs) — same as our MSE
    # reduction since we sum over features and mean over examples.
    return _reduce(jnp.square(p - y), mask, weights)


@register("l1")
def l1(p, y, mask=None, weights=None):
    return _reduce(jnp.abs(p - y), mask, weights)


@register("mae")
def mae(p, y, mask=None, weights=None):
    return _reduce(jnp.abs(p - y), mask, weights)


def reduction_mass(labels, mask=None):
    """Total denominator weight of one (micro)batch under :func:`_reduce`'s
    masked mean — used by ``grad_accum`` for EXACT recombination of
    microbatch masked means (weight each microbatch's loss/grads by its
    mass, divide by the total): ``sum(mask)`` broadcast to the per-example
    shape, or the per-example element count when unmasked. Integer labels
    take the sparse-index path (per-example shape == labels shape); dense
    labels lose the trailing feature axis."""
    labels = jnp.asarray(labels)
    sparse = jnp.issubdtype(labels.dtype, jnp.integer)
    pe_shape = tuple(labels.shape) if sparse else tuple(labels.shape[:-1])
    if not pe_shape:
        pe_shape = (1,)
    if mask is None:
        # static shape product — stays a Python int, no float()/int() host sync
        return jnp.asarray(math.prod(pe_shape), jnp.float32)
    m = jnp.asarray(mask).astype(jnp.float32)
    m = jnp.broadcast_to(
        m.reshape(m.shape + (1,) * (len(pe_shape) - m.ndim)), pe_shape)
    return jnp.sum(m)


def _is_sparse_labels(p, y):
    """Sparse class-index labels = integer dtype AND one fewer trailing dim
    than predictions. Integer labels at full rank (e.g. np.eye(...).astype(int)
    one-hots) are ambiguous — reject loudly instead of silently gathering."""
    y = jnp.asarray(y)
    if not jnp.issubdtype(y.dtype, jnp.integer):
        return False
    if y.ndim == jnp.asarray(p).ndim - 1:
        return True
    raise ValueError(
        f"integer labels with shape {y.shape} are ambiguous against "
        f"predictions {jnp.asarray(p).shape}: cast one-hot labels to float "
        f"for the dense loss, or drop the trailing class dim for sparse "
        f"class-index labels")


def _sparse_nll(logp, y, mask, weights):
    """Integer class-index labels: gather the target log-prob instead of a
    one-hot product — for large vocabularies (LM heads) this avoids ever
    materializing a (B, T, V) one-hot tensor."""
    nll = -jnp.take_along_axis(logp, y[..., None].astype(jnp.int32), axis=-1)[..., 0]
    if weights is not None:
        nll = nll * jnp.take_along_axis(
            jnp.broadcast_to(weights, logp.shape), y[..., None].astype(jnp.int32),
            axis=-1)[..., 0]
    return _reduce(nll[..., None], mask, None)


@register("mcxent")
@register("negativeloglikelihood")
def mcxent(p, y, mask=None, weights=None):
    """Multi-class cross-entropy on probabilities (post-softmax).
    Integer ``y`` of rank ``p.ndim - 1`` is treated as sparse class indices."""
    if _is_sparse_labels(p, y):
        return _sparse_nll(jnp.log(jnp.clip(p, _EPS, 1.0)), y, mask, weights)
    return _reduce(-y * jnp.log(jnp.clip(p, _EPS, 1.0)), mask, weights)


@register("mcxent_logits")
@register("softmax_cross_entropy_logits")
def mcxent_logits(logits, y, mask=None, weights=None):
    """Fused softmax+CE on raw logits — numerically stable, XLA-fused.
    Integer ``y`` of rank ``logits.ndim - 1`` is treated as sparse indices."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    if _is_sparse_labels(logits, y):
        return _sparse_nll(logp, y, mask, weights)
    return _reduce(-y * logp, mask, weights)


@register("xent")
@register("binary_crossentropy")
def xent(p, y, mask=None, weights=None):
    p = jnp.clip(p, _EPS, 1.0 - _EPS)
    return _reduce(-(y * jnp.log(p) + (1.0 - y) * jnp.log(1.0 - p)), mask, weights)


@register("xent_logits")
@register("sigmoid_cross_entropy_logits")
def xent_logits(logits, y, mask=None, weights=None):
    # log(1+exp(-|x|)) formulation.
    per = jnp.maximum(logits, 0.0) - logits * y + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return _reduce(per, mask, weights)


@register("rmse_xent")
def rmse_xent(p, y, mask=None, weights=None):
    # DL4J legacy: sqrt of squared diff per unit.
    return _reduce(jnp.sqrt(jnp.square(p - y) + _EPS), mask, weights)


@register("hinge")
def hinge(p, y, mask=None, weights=None):
    # labels in {-1, +1} or {0,1} mapped to +-1.
    y_pm = jnp.where(y > 0.5, 1.0, -1.0) if jnp.issubdtype(y.dtype, jnp.floating) else y
    return _reduce(jnp.maximum(0.0, 1.0 - y_pm * p), mask, weights)


@register("squared_hinge")
def squared_hinge(p, y, mask=None, weights=None):
    y_pm = jnp.where(y > 0.5, 1.0, -1.0) if jnp.issubdtype(y.dtype, jnp.floating) else y
    return _reduce(jnp.square(jnp.maximum(0.0, 1.0 - y_pm * p)), mask, weights)


@register("kl_divergence")
@register("reconstruction_crossentropy")
def kl_divergence(p, y, mask=None, weights=None):
    p = jnp.clip(p, _EPS, 1.0)
    y_c = jnp.clip(y, _EPS, 1.0)
    return _reduce(y_c * (jnp.log(y_c) - jnp.log(p)), mask, weights)


@register("mean_absolute_percentage_error")
@register("mape")
def mape(p, y, mask=None, weights=None):
    return _reduce(100.0 * jnp.abs((p - y) / jnp.where(jnp.abs(y) < _EPS, _EPS, y)), mask, weights)


@register("mean_squared_logarithmic_error")
@register("msle")
def msle(p, y, mask=None, weights=None):
    return _reduce(jnp.square(jnp.log1p(jnp.maximum(p, -1 + _EPS)) - jnp.log1p(jnp.maximum(y, -1 + _EPS))), mask, weights)


@register("poisson")
def poisson(p, y, mask=None, weights=None):
    return _reduce(p - y * jnp.log(jnp.clip(p, _EPS, None)), mask, weights)


@register("cosine_proximity")
def cosine_proximity(p, y, mask=None, weights=None):
    pn = p / jnp.maximum(jnp.linalg.norm(p, axis=-1, keepdims=True), _EPS)
    yn = y / jnp.maximum(jnp.linalg.norm(y, axis=-1, keepdims=True), _EPS)
    per_example = -jnp.sum(pn * yn, axis=-1)
    if mask is not None:
        m = mask.astype(per_example.dtype)
        m = jnp.broadcast_to(m.reshape(m.shape + (1,) * (per_example.ndim - m.ndim)), per_example.shape)
        return jnp.sum(per_example * m) / jnp.maximum(jnp.sum(m), 1.0)
    return jnp.mean(per_example)


@register("wasserstein")
def wasserstein(p, y, mask=None, weights=None):
    return _reduce(p * y, mask, weights)


def center_loss(features: Array, label_idx: Array, centers: Array, alpha: float = 0.05):
    """CenterLoss (CenterLossOutputLayer): pull features toward per-class centers.

    Returns (loss, updated_centers). Centers update is an EMA toward the class
    mean — done with segment ops (static shapes, TPU-friendly).
    """
    num_classes = centers.shape[0]
    picked = centers[label_idx]
    loss = 0.5 * jnp.mean(jnp.sum(jnp.square(features - picked), axis=-1))
    onehot = jax.nn.one_hot(label_idx, num_classes, dtype=features.dtype)
    counts = jnp.maximum(onehot.sum(axis=0), 1.0)
    class_mean = (onehot.T @ features) / counts[:, None]
    seen = (onehot.sum(axis=0) > 0)[:, None]
    new_centers = jnp.where(seen, centers + alpha * (class_mean - centers), centers)
    return loss, new_centers
