"""TPU-native op foundation: activations, losses, initializers, updaters,
schedules, regularization — the replacement for DL4J's external ND4J surface
(SURVEY.md §2.11)."""

from . import activations, initializers, losses, regularization, schedules, updaters

__all__ = ["activations", "initializers", "losses", "regularization", "schedules", "updaters"]
