"""TPU-native op foundation: activations, losses, initializers, updaters,
schedules, regularization — the replacement for DL4J's external ND4J surface
(SURVEY.md §2.11). The pallas flash-attention kernel lives in
``ops.flash_attention`` and is imported from there at use sites only, so
importing the package never pulls in pallas.
"""

from . import activations, initializers, losses, regularization, schedules, updaters

__all__ = ["activations", "initializers", "losses", "regularization",
           "schedules", "updaters"]
