"""Dataset iterators — parity with DL4J's DataSetIterator stack (SURVEY.md §2.1).

Reference: ``datasets/iterator/AsyncDataSetIterator.java`` (background prefetch
thread + device buffers), ``DataSetIteratorSplitter``, ``EarlyTermination*``,
``impl/BenchmarkDataSetIterator.java:20`` (synthetic perf fixture),
``MultipleEpochsIterator``, plus the ND4J ``DataSet``/``MultiDataSet`` records.

TPU design: a ``DataSet`` is a (features, labels, masks) record of numpy/JAX
arrays; iterators are plain Python iterables. ``AsyncIterator`` prefetches on
a background thread and moves batches to device with ``jax.device_put`` so
host->HBM transfer overlaps compute — the same double-buffering
AsyncDataSetIterator does with its ETL thread, without the JVM queue machinery.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence, Tuple

import jax
import numpy as np


@dataclass
class DataSet:
    """ND4J DataSet equivalent: features, labels, optional masks."""

    features: Any
    labels: Any
    features_mask: Optional[Any] = None
    labels_mask: Optional[Any] = None

    @property
    def num_examples(self) -> int:
        f = self.features  # .shape avoids a D2H copy for device arrays
        return int(f.shape[0] if hasattr(f, "shape") else np.asarray(f).shape[0])

    def to_device(self, device=None):
        put = (lambda a: jax.device_put(a, device)) if device else jax.device_put
        return DataSet(
            put(self.features), put(self.labels),
            put(self.features_mask) if self.features_mask is not None else None,
            put(self.labels_mask) if self.labels_mask is not None else None,
        )


@dataclass
class MultiDataSet:
    """ND4J MultiDataSet: multiple feature/label arrays (ComputationGraph fit)."""

    features: List[Any]
    labels: List[Any]
    features_masks: Optional[List[Any]] = None
    labels_masks: Optional[List[Any]] = None

    @property
    def num_examples(self) -> int:
        f = self.features[0]  # .shape avoids a D2H copy for device arrays
        return int(f.shape[0] if hasattr(f, "shape") else np.asarray(f).shape[0])

    def to_device(self, device=None):
        put = (lambda a: jax.device_put(a, device)) if device else jax.device_put
        puts = lambda seq: None if seq is None else [put(a) for a in seq]
        return MultiDataSet(puts(self.features), puts(self.labels),
                            puts(self.features_masks), puts(self.labels_masks))


class DataSetIterator:
    """Base protocol; DL4J DataSetIterator parity (reset/batch/totalExamples)."""

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def reset(self):
        pass

    #: minibatch size; subclasses set an instance attribute or override
    batch_size: int = -1


class ArrayIterator(DataSetIterator):
    """Iterate minibatches over in-memory arrays (ListDataSetIterator parity)."""

    def __init__(self, features, labels, batch_size: int = 32, shuffle: bool = False,
                 seed: int = 0, features_mask=None, labels_mask=None, drop_last: bool = False):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        self.features_mask = np.asarray(features_mask) if features_mask is not None else None
        self.labels_mask = np.asarray(labels_mask) if labels_mask is not None else None
        self._batch = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.default_rng(seed)

    @property
    def batch_size(self):
        return self._batch

    def __len__(self):
        n = self.features.shape[0]
        return n // self._batch if self.drop_last else -(-n // self._batch)

    def __iter__(self):
        n = self.features.shape[0]
        idx = np.arange(n)
        if self.shuffle:
            self._rng.shuffle(idx)
        end = n - n % self._batch if self.drop_last else n
        for i in range(0, end, self._batch):
            sl = idx[i : i + self._batch]
            yield DataSet(
                self.features[sl], self.labels[sl],
                self.features_mask[sl] if self.features_mask is not None else None,
                self.labels_mask[sl] if self.labels_mask is not None else None,
            )


class AsyncIterator(DataSetIterator):
    """AsyncDataSetIterator.java equivalent: background-thread prefetch with a
    bounded queue; batches are device_put on the worker so H2D transfer
    overlaps the training step."""

    _SENTINEL = object()

    def __init__(self, base: Iterable[DataSet], queue_size: int = 4, device=None,
                 to_device: bool = True):
        self.base = base
        self.queue_size = queue_size
        self.device = device
        self.to_device = to_device

    @property
    def batch_size(self):
        return getattr(self.base, "batch_size", -1)

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_size)
        err: List[BaseException] = []

        def worker():
            try:
                for ds in self.base:
                    q.put(ds.to_device(self.device) if self.to_device else ds)
            except BaseException as e:  # propagated: consumer re-raises below  # jaxlint: disable=broad-except
                err.append(e)
            finally:
                q.put(self._SENTINEL)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is self._SENTINEL:
                break
            yield item
        t.join()
        if err:
            raise err[0]


class BenchmarkIterator(DataSetIterator):
    """BenchmarkDataSetIterator.java:20 — serves the SAME random batch
    repeatedly, isolating compute from ETL for perf measurement."""

    def __init__(self, feature_shape: Sequence[int], num_classes: int, batch_size: int,
                 num_batches: int, seed: int = 0, dtype=np.float32):
        rng = np.random.default_rng(seed)
        self._features = rng.standard_normal((batch_size, *feature_shape)).astype(dtype)
        labels = np.zeros((batch_size, num_classes), dtype)
        labels[np.arange(batch_size), rng.integers(0, num_classes, batch_size)] = 1
        self._labels = labels
        self._batch = batch_size
        self.num_batches = num_batches

    @property
    def batch_size(self):
        return self._batch

    def __len__(self):
        return self.num_batches

    def __iter__(self):
        ds = DataSet(self._features, self._labels)
        for _ in range(self.num_batches):
            yield ds


class EarlyTerminationIterator(DataSetIterator):
    """EarlyTerminationDataSetIterator.java — cap the number of batches."""

    def __init__(self, base: DataSetIterator, max_batches: int):
        self.base = base
        self.max_batches = max_batches

    @property
    def batch_size(self):
        return self.base.batch_size

    def reset(self):
        self.base.reset()

    def __iter__(self):
        for i, ds in enumerate(self.base):
            if i >= self.max_batches:
                break
            yield ds


class MultipleEpochsIterator(DataSetIterator):
    """MultipleEpochsIterator.java — loop the base iterator N times."""

    def __init__(self, base: DataSetIterator, epochs: int):
        self.base = base
        self.epochs = epochs

    @property
    def batch_size(self):
        return self.base.batch_size

    def __iter__(self):
        for _ in range(self.epochs):
            if hasattr(self.base, "reset"):
                self.base.reset()
            yield from self.base


def export_batches(iterator: DataSetIterator, directory: str,
                   prefix: str = "dataset") -> int:
    """Export-based training path (BatchAndExportDataSetsFunction.java /
    SparkUtils exportDataSet parity): materialize an iterator's batches as
    numbered ``.npz`` files so later epochs (or other processes) stream from
    disk instead of recomputing the ETL. Returns the number of files written.

    With ``FileDataSetIterator(directory, shard=(rank, world))`` this is also
    the per-process data-shard story for multi-host training (the reference's
    exported-RDD + VirtualDataSetIterator pattern)."""
    import os

    os.makedirs(directory, exist_ok=True)
    for stale in _batch_files(directory, prefix):  # a shorter re-export must
        os.remove(stale)  # not leave higher-numbered files from the old run
    n = 0
    for ds in iterator:
        arrs = {"features": np.asarray(ds.features), "labels": np.asarray(ds.labels)}
        if ds.features_mask is not None:
            arrs["features_mask"] = np.asarray(ds.features_mask)
        if ds.labels_mask is not None:
            arrs["labels_mask"] = np.asarray(ds.labels_mask)
        np.savez(os.path.join(directory, f"{prefix}_{n:06d}.npz"), **arrs)
        n += 1
    if hasattr(iterator, "reset"):
        iterator.reset()
    return n


def _batch_files(directory: str, prefix: str) -> List[str]:
    """Exactly the files ``export_batches`` writes for this prefix
    (``{prefix}_NNNNNN.npz``) — a strict match, so prefixes that extend each
    other ("dataset" vs "dataset_val") never bleed into one another."""
    import os
    import re

    pat = re.compile(re.escape(prefix) + r"_\d{6}\.npz$")
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    return sorted(os.path.join(directory, f) for f in names if pat.fullmatch(f))


class FileDataSetIterator(DataSetIterator):
    """ExistingMiniBatchDataSetIterator.java — stream pre-exported ``.npz``
    batches from a directory; optional shuffle of file order per epoch and
    ``shard=(rank, world_size)`` striping for per-process data sharding."""

    def __init__(self, directory: str, prefix: str = "dataset",
                 shuffle: bool = False, seed: int = 0,
                 shard: Optional[Tuple[int, int]] = None):
        import os

        if not os.path.isdir(directory):
            raise FileNotFoundError(f"export directory does not exist: {directory}")
        self.files = _batch_files(directory, prefix)
        if not self.files:  # before shard striping — an empty *shard* is legal
            raise ValueError(
                f"no exported batches matching '{prefix}_NNNNNN.npz' in "
                f"{directory} — check the prefix or run export_batches first")
        if shard is not None:
            rank, world = shard
            self.files = self.files[rank::world]
        self.shuffle = shuffle
        self._rng = np.random.default_rng(seed)

    def __len__(self):
        return len(self.files)

    def __iter__(self):
        order = np.arange(len(self.files))
        if self.shuffle:
            self._rng.shuffle(order)
        for i in order:
            with np.load(self.files[i]) as z:
                yield DataSet(z["features"], z["labels"],
                              z["features_mask"] if "features_mask" in z else None,
                              z["labels_mask"] if "labels_mask" in z else None)


def split_iterator(features, labels, fraction_train: float, batch_size: int = 32,
                   seed: int = 0, shuffle: bool = True) -> Tuple[ArrayIterator, ArrayIterator]:
    """DataSetIteratorSplitter / SplitTestAndTrain parity."""
    n = np.asarray(features).shape[0]
    idx = np.arange(n)
    np.random.default_rng(seed).shuffle(idx)
    cut = int(n * fraction_train)
    tr, te = idx[:cut], idx[cut:]
    f, l = np.asarray(features), np.asarray(labels)
    return (ArrayIterator(f[tr], l[tr], batch_size, shuffle=shuffle, seed=seed),
            ArrayIterator(f[te], l[te], batch_size))
