"""Record readers + transform pipeline — the DataVec bridge.

Reference parity (SURVEY.md §2.2 "DataVec bridge", ~126 org.datavec imports):
- ``RecordReaderDataSetIterator.java`` — record stream -> DataSet batches
- ``SequenceRecordReaderDataSetIterator.java`` — per-file sequences
- DataVec ``CSVRecordReader`` / ``ImageRecordReader`` / ``TransformProcess``

TPU-native design: readers produce numpy rows on the host (ETL is host-side
by definition); the iterator assembles fixed-shape batches that feed the
device via the async prefetch path (``native/io.py`` C++ batcher or
``AsyncIterator``). Transforms are pure functions over column arrays, so a
pipeline is data (a list of op descriptors) — serializable like the
reference's JSON TransformProcess.
"""

from __future__ import annotations

import csv
import os
from pathlib import Path
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .iterators import DataSet, DataSetIterator, MultiDataSet


# ---------------------------------------------------------------------------
# Record readers
# ---------------------------------------------------------------------------


class RecordReader:
    """Record stream contract (DataVec RecordReader): iterate lists of
    values; ``reset`` restarts."""

    def __iter__(self):
        raise NotImplementedError

    def reset(self):
        pass


class CollectionRecordReader(RecordReader):
    """In-memory records (CollectionRecordReader parity)."""

    def __init__(self, records: Sequence[Sequence[Any]]):
        self.records = [list(r) for r in records]

    def __iter__(self):
        return iter(self.records)


class CSVRecordReader(RecordReader):
    """CSVRecordReader parity: skip lines, delimiter, string cells kept as
    strings (transforms handle categorical -> numeric)."""

    def __init__(self, path: str, skip_lines: int = 0, delimiter: str = ","):
        self.path = str(path)
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        with open(self.path, newline="") as f:
            r = csv.reader(f, delimiter=self.delimiter)
            for i, row in enumerate(r):
                if i < self.skip_lines or not row:
                    continue
                yield [self._coerce(c) for c in row]

    @staticmethod
    def _coerce(cell: str):
        try:
            return float(cell)
        except ValueError:
            return cell.strip()


class ImageRecordReader(RecordReader):
    """ImageRecordReader parity: walks ``root/<label>/*.{png,jpg,...}``,
    yields [flattened HWC float array, label_index]. Labels are the sorted
    subdirectory names (ParentPathLabelGenerator semantics)."""

    EXTS = {".png", ".jpg", ".jpeg", ".bmp", ".gif"}

    def __init__(self, root: str, height: int, width: int, channels: int = 3,
                 min_examples_per_label: int = 0):
        self.root = Path(root)
        self.h, self.w, self.c = height, width, channels
        labels = sorted(d.name for d in self.root.iterdir() if d.is_dir())
        by_label: Dict[str, List[Path]] = {}
        for lab in labels:
            files = [p for p in sorted((self.root / lab).rglob("*"))
                     if p.suffix.lower() in self.EXTS]
            if len(files) >= min_examples_per_label:
                by_label[lab] = files
        self.labels = sorted(by_label)  # indices stay consistent post-filter
        self._files: List[Tuple[Path, int]] = [
            (p, li) for li, lab in enumerate(self.labels) for p in by_label[lab]]

    def __len__(self):
        return len(self._files)

    def load_image(self, path: Path) -> np.ndarray:
        from PIL import Image

        img = Image.open(path)
        img = img.convert("RGB" if self.c == 3 else "L")
        img = img.resize((self.w, self.h))
        arr = np.asarray(img, np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr

    def __iter__(self):
        for p, li in self._files:
            yield [self.load_image(p), li]


class CSVSequenceRecordReader(RecordReader):
    """CSVSequenceRecordReader parity: each FILE is one sequence (rows =
    timesteps). ``paths`` may be a glob pattern or an explicit list."""

    def __init__(self, paths, skip_lines: int = 0, delimiter: str = ","):
        if isinstance(paths, (str, Path)):
            import glob as _g

            self.paths = sorted(_g.glob(str(paths)))
        else:
            self.paths = [str(p) for p in paths]
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        for p in self.paths:
            rows = list(CSVRecordReader(p, self.skip_lines, self.delimiter))
            yield rows  # one record == one sequence (list of timestep rows)


# ---------------------------------------------------------------------------
# Transform pipeline (DataVec TransformProcess equivalent)
# ---------------------------------------------------------------------------


class TransformProcess:
    """Composable per-record column transforms; a pipeline is data
    (list of op descriptors) like the reference's JSON TransformProcess.

    Ops operate on a record (list of cells) and return the new record.
    """

    def __init__(self):
        self.ops: List[Tuple[str, dict]] = []

    # --- builder API (TransformProcess.Builder parity) ---
    def remove_columns(self, *indices: int) -> "TransformProcess":
        self.ops.append(("remove_columns", {"indices": sorted(indices)}))
        return self

    def categorical_to_integer(self, index: int, categories: Sequence[str]) -> "TransformProcess":
        self.ops.append(("categorical_to_integer",
                         {"index": index, "categories": list(categories)}))
        return self

    def categorical_to_onehot(self, index: int, categories: Sequence[str]) -> "TransformProcess":
        self.ops.append(("categorical_to_onehot",
                         {"index": index, "categories": list(categories)}))
        return self

    def normalize_minmax(self, index: int, lo: float, hi: float) -> "TransformProcess":
        self.ops.append(("normalize_minmax", {"index": index, "lo": lo, "hi": hi}))
        return self

    def normalize_standardize(self, index: int, mean: float, std: float) -> "TransformProcess":
        self.ops.append(("normalize_standardize", {"index": index, "mean": mean, "std": std}))
        return self

    def map_column(self, index: int, fn: Callable[[Any], Any]) -> "TransformProcess":
        self.ops.append(("map_column", {"index": index, "fn": fn}))
        return self

    def filter_rows(self, predicate: Callable[[Sequence[Any]], bool]) -> "TransformProcess":
        """Keep rows where predicate(record) is True (FilterOp parity)."""
        self.ops.append(("filter_rows", {"predicate": predicate}))
        return self

    # --- execution ---
    def __call__(self, record: Sequence[Any]) -> Optional[List[Any]]:
        rec = list(record)
        for name, a in self.ops:
            if name == "remove_columns":
                rec = [c for i, c in enumerate(rec) if i not in a["indices"]]
            elif name == "categorical_to_integer":
                rec[a["index"]] = float(a["categories"].index(rec[a["index"]]))
            elif name == "categorical_to_onehot":
                i, cats = a["index"], a["categories"]
                one = [0.0] * len(cats)
                one[cats.index(rec[i])] = 1.0
                rec = rec[:i] + one + rec[i + 1:]
            elif name == "normalize_minmax":
                i = a["index"]
                rec[i] = (float(rec[i]) - a["lo"]) / max(a["hi"] - a["lo"], 1e-12)
            elif name == "normalize_standardize":
                i = a["index"]
                rec[i] = (float(rec[i]) - a["mean"]) / max(a["std"], 1e-12)
            elif name == "map_column":
                rec[a["index"]] = a["fn"](rec[a["index"]])
            elif name == "filter_rows":
                if not a["predicate"](rec):
                    return None
        return rec

    def to_list(self) -> List[Tuple[str, dict]]:
        """Descriptor form (serializable except map/filter callables)."""
        return list(self.ops)

    # --- JSON round-trip (TransformProcess.toJson/fromJson parity) ---
    _CALLABLE_OPS = {"map_column", "filter_rows"}

    def to_json(self) -> str:
        """Serialize the pipeline. Ops with python callables (map_column,
        filter_rows) cannot round-trip through JSON — same boundary as the
        reference, whose JSON covers only its declarative op vocabulary."""
        import json

        bad = [n for n, _ in self.ops if n in self._CALLABLE_OPS]
        if bad:
            raise ValueError(f"Ops {bad} hold python callables and are not "
                             f"JSON-serializable; keep pipelines declarative "
                             f"to round-trip them")
        return json.dumps({"ops": [{"op": n, **a} for n, a in self.ops]})

    _KNOWN_OPS = {"remove_columns", "categorical_to_integer",
                  "categorical_to_onehot", "normalize_minmax",
                  "normalize_standardize"}

    @classmethod
    def from_json(cls, s: str) -> "TransformProcess":
        import json

        tp = cls()
        for entry in json.loads(s)["ops"]:
            entry = dict(entry)
            name = entry.pop("op")
            if name in cls._CALLABLE_OPS:
                raise ValueError(f"Op '{name}' cannot be deserialized")
            if name not in cls._KNOWN_OPS:  # fail fast, don't silently skip
                raise ValueError(f"Unknown transform op '{name}' "
                                 f"(known: {sorted(cls._KNOWN_OPS)})")
            tp.ops.append((name, entry))
        return tp


# ---------------------------------------------------------------------------
# RecordReader -> DataSet iterators
# ---------------------------------------------------------------------------


class RecordReaderDataSetIterator(DataSetIterator):
    """RecordReaderDataSetIterator.java parity: record stream -> DataSet
    batches. ``label_index``: column holding the label (after transforms);
    int labels one-hot to ``num_classes`` unless ``regression``. Feature
    cells may be scalars or arrays (ImageRecordReader rows)."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None, num_classes: int = 0,
                 regression: bool = False,
                 transform: Optional[TransformProcess] = None):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.transform = transform

    def _split(self, rec: List[Any]) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        if self.label_index is None:
            feats = rec
            label = None
        else:
            li = self.label_index if self.label_index >= 0 else len(rec) + self.label_index
            label = rec[li]
            feats = rec[:li] + rec[li + 1:]
        parts = [np.asarray(c, np.float32).ravel() if not np.isscalar(c)
                 else np.asarray([c], np.float32) for c in feats]
        x = np.concatenate(parts) if parts else np.zeros(0, np.float32)
        if label is None:
            return x, None
        if self.regression:
            return x, np.asarray([label], np.float32)
        y = np.zeros(self.num_classes, np.float32)
        y[int(label)] = 1.0
        return x, y

    def __iter__(self):
        xb, yb = [], []
        for rec in self.reader:
            if self.transform is not None:
                rec = self.transform(rec)
                if rec is None:
                    continue
            x, y = self._split(list(rec))
            xb.append(x)
            if y is not None:
                yb.append(y)
            if len(xb) == self.batch_size:
                yield self._emit(xb, yb)
                xb, yb = [], []
        if xb:
            yield self._emit(xb, yb)

    def _emit(self, xb, yb):
        x = np.stack(xb)
        y = np.stack(yb) if yb else np.zeros((len(xb), 0), np.float32)
        return DataSet(x, y)

    def reset(self):
        self.reader.reset()


class ImageRecordDataSetIterator(RecordReaderDataSetIterator):
    """Image records keep their HWC shape (no flatten) — the CNN input path
    of RecordReaderDataSetIterator(ImageRecordReader, ...)."""

    def _split(self, rec):
        img, label = rec[0], rec[1]
        x = np.asarray(img, np.float32)
        y = np.zeros(self.num_classes, np.float32)
        y[int(label)] = 1.0
        return x, y


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """SequenceRecordReaderDataSetIterator.java parity (single-reader mode):
    each record is a sequence of timestep rows; the label column yields a
    per-timestep label. Sequences in a batch are padded to the longest with
    feature/label masks — the masking contract the reference builds for
    ragged time series."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: int = 0,
                 regression: bool = False):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression

    def __iter__(self):
        buf = []
        for seq in self.reader:
            if not seq:  # empty file / header-only sequence: skip loudly
                import logging

                logging.getLogger(__name__).warning(
                    "SequenceRecordReaderDataSetIterator: skipping empty sequence")
                continue
            buf.append(seq)
            if len(buf) == self.batch_size:
                yield self._emit(buf)
                buf = []
        if buf:
            yield self._emit(buf)

    def _emit(self, seqs):
        B = len(seqs)
        T = max(len(s) for s in seqs)
        n_feat = len(seqs[0][0]) - 1
        x = np.zeros((B, T, n_feat), np.float32)
        if self.regression:
            y = np.zeros((B, T, 1), np.float32)
        else:
            y = np.zeros((B, T, self.num_classes), np.float32)
        mask = np.zeros((B, T), np.float32)
        for b, seq in enumerate(seqs):
            for t, row in enumerate(seq):
                li = self.label_index if self.label_index >= 0 else len(row) + self.label_index
                feats = [float(v) for i, v in enumerate(row) if i != li]
                x[b, t] = feats
                if self.regression:
                    y[b, t, 0] = float(row[li])
                else:
                    y[b, t, int(row[li])] = 1.0
                mask[b, t] = 1.0
        return DataSet(x, y, features_mask=mask, labels_mask=mask)

    def reset(self):
        self.reader.reset()


class RecordReaderMultiDataSetIterator(DataSetIterator):
    """RecordReaderMultiDataSetIterator.java parity: compose MULTIPLE named
    record readers into MultiDataSet batches for ComputationGraph training —
    builder-style column mappings:

        it = (RecordReaderMultiDataSetIterator(batch_size=32)
              .add_reader("csv", reader)
              .add_input("csv", 0, 3)                 # cols [0, 3] -> input 0
              .add_output_one_hot("csv", 4, 10))      # col 4 -> one-hot output

    Readers iterate in lockstep (the reference aligns them record-by-record).
    """

    def __init__(self, batch_size: int):
        self.batch_size = batch_size
        self._readers: Dict[str, RecordReader] = {}
        self._inputs: List[Tuple[str, int, int]] = []
        self._outputs: List[Tuple[str, str, int, int, int]] = []

    # --- builder (Builder.addReader/addInput/addOutput/addOutputOneHot) ---
    def add_reader(self, name: str, reader: RecordReader):
        self._readers[name] = reader
        return self

    def add_input(self, reader_name: str, col_from: int, col_to: int):
        self._inputs.append((reader_name, col_from, col_to))
        return self

    def add_output(self, reader_name: str, col_from: int, col_to: int):
        self._outputs.append(("raw", reader_name, col_from, col_to, 0))
        return self

    def add_output_one_hot(self, reader_name: str, col: int, num_classes: int):
        self._outputs.append(("onehot", reader_name, col, col, num_classes))
        return self

    def _check(self):
        for name, *_ in self._inputs:
            if name not in self._readers:
                raise ValueError(f"input references unknown reader '{name}'")
        for _, name, *_ in self._outputs:
            if name not in self._readers:
                raise ValueError(f"output references unknown reader '{name}'")
        if not self._inputs or not self._outputs:
            raise ValueError("need at least one input and one output mapping")

    def __iter__(self):
        self._check()
        names = list(self._readers)
        streams = [iter(self._readers[n]) for n in names]
        by_name = dict(zip(names, streams))
        xb = [[] for _ in self._inputs]
        yb = [[] for _ in self._outputs]

        def emit():
            xs = [np.stack(b).astype(np.float32) for b in xb]
            ys = [np.stack(b).astype(np.float32) for b in yb]
            return MultiDataSet(xs, ys)

        while True:
            # advance every reader; partial exhaustion is a hard error (the
            # reference requires aligned readers — silent truncation trains
            # on a shortened dataset)
            rows = {}
            done = []
            for n in names:
                try:
                    rows[n] = next(by_name[n])
                except StopIteration:
                    done.append(n)
            if done:
                if len(done) != len(names):
                    raise ValueError(
                        f"readers exhausted out of lockstep: {done} ended "
                        f"before {sorted(set(names) - set(done))}")
                break
            recs = {n: [float(v) if not isinstance(v, str) else v
                        for v in row] for n, row in rows.items()}
            for i, (n, cf, ct) in enumerate(self._inputs):
                xb[i].append(np.asarray(recs[n][cf:ct + 1], np.float32))
            for i, (kind, n, cf, ct, k) in enumerate(self._outputs):
                if kind == "onehot":
                    lab = int(recs[n][cf])
                    if not 0 <= lab < k:
                        raise ValueError(
                            f"reader '{n}' column {cf}: label {lab} outside "
                            f"[0, {k}) for one-hot output")
                    one = np.zeros(k, np.float32)
                    one[lab] = 1.0
                    yb[i].append(one)
                else:
                    yb[i].append(np.asarray(recs[n][cf:ct + 1], np.float32))
            if len(xb[0]) == self.batch_size:
                yield emit()
                xb = [[] for _ in self._inputs]
                yb = [[] for _ in self._outputs]
        if xb[0]:
            yield emit()

    def reset(self):
        for r in self._readers.values():
            r.reset()
