"""Data normalizers — parity with ND4J's DataNormalization implementations
(NormalizerStandardize, NormalizerMinMaxScaler, ImagePreProcessingScaler,
VGG16ImagePreProcessor) used throughout deeplearning4j-core datasets and
saved into model zips as ``normalizer.bin`` (ModelSerializer.java:40)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np


class Normalizer:
    def fit(self, features: np.ndarray):
        return self

    def transform(self, features):
        raise NotImplementedError

    def revert(self, features):
        raise NotImplementedError

    def to_dict(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_dict(d: dict) -> "Normalizer":
        kind = d["type"]
        cls = {"standardize": Standardize, "minmax": MinMaxScaler,
               "image_scaler": ImageScaler, "vgg16": VGG16Preprocessor}[kind]
        return cls._from_dict(d)


@dataclass
class Standardize(Normalizer):
    """NormalizerStandardize: (x - mean) / std per feature."""

    mean: Optional[np.ndarray] = None
    std: Optional[np.ndarray] = None

    def fit(self, features):
        f = np.asarray(features, np.float64)
        axes = tuple(range(f.ndim - 1))
        self.mean = f.mean(axis=axes).astype(np.float32)
        self.std = np.maximum(f.std(axis=axes), 1e-6).astype(np.float32)
        return self

    def transform(self, features):
        return (np.asarray(features) - self.mean) / self.std

    def revert(self, features):
        return np.asarray(features) * self.std + self.mean

    def to_dict(self):
        return {"type": "standardize", "mean": self.mean.tolist(), "std": self.std.tolist()}

    @classmethod
    def _from_dict(cls, d):
        return cls(np.asarray(d["mean"], np.float32), np.asarray(d["std"], np.float32))


@dataclass
class MinMaxScaler(Normalizer):
    """NormalizerMinMaxScaler: scale to [lo, hi]."""

    lo: float = 0.0
    hi: float = 1.0
    data_min: Optional[np.ndarray] = None
    data_max: Optional[np.ndarray] = None

    def fit(self, features):
        f = np.asarray(features, np.float64)
        axes = tuple(range(f.ndim - 1))
        self.data_min = f.min(axis=axes).astype(np.float32)
        self.data_max = f.max(axis=axes).astype(np.float32)
        return self

    def transform(self, features):
        rng = np.maximum(self.data_max - self.data_min, 1e-8)
        return (np.asarray(features) - self.data_min) / rng * (self.hi - self.lo) + self.lo

    def revert(self, features):
        rng = np.maximum(self.data_max - self.data_min, 1e-8)
        return (np.asarray(features) - self.lo) / (self.hi - self.lo) * rng + self.data_min

    def to_dict(self):
        return {"type": "minmax", "lo": self.lo, "hi": self.hi,
                "data_min": self.data_min.tolist(), "data_max": self.data_max.tolist()}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["lo"], d["hi"], np.asarray(d["data_min"], np.float32),
                   np.asarray(d["data_max"], np.float32))


@dataclass
class ImageScaler(Normalizer):
    """ImagePreProcessingScaler: pixel [0, maxval] -> [lo, hi] (default [0,1])."""

    lo: float = 0.0
    hi: float = 1.0
    max_pixel: float = 255.0

    def transform(self, features):
        return np.asarray(features, np.float32) / self.max_pixel * (self.hi - self.lo) + self.lo

    def revert(self, features):
        return (np.asarray(features) - self.lo) / (self.hi - self.lo) * self.max_pixel

    def to_dict(self):
        return {"type": "image_scaler", "lo": self.lo, "hi": self.hi, "max_pixel": self.max_pixel}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["lo"], d["hi"], d["max_pixel"])


@dataclass
class VGG16Preprocessor(Normalizer):
    """VGG16ImagePreProcessor: subtract ImageNet BGR means (NHWC, RGB order here)."""

    means: tuple = (123.68, 116.779, 103.939)

    def transform(self, features):
        return np.asarray(features, np.float32) - np.asarray(self.means, np.float32)

    def revert(self, features):
        return np.asarray(features) + np.asarray(self.means, np.float32)

    def to_dict(self):
        return {"type": "vgg16", "means": list(self.means)}

    @classmethod
    def _from_dict(cls, d):
        return cls(tuple(d["means"]))
