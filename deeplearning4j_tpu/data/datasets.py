"""Canned datasets — parity with deeplearning4j-core fetchers (MNIST, EMNIST,
Iris, LFW, CIFAR, SVHN, TinyImageNet, UCI; ``datasets/fetchers/``,
SURVEY.md §2.2). Zero-egress environment: loaders read local files when
present (standard formats under ``$DL4J_TPU_DATA``) and otherwise fall back
to a deterministic synthetic replica with the same shapes/classes, so every
example and test runs hermetically (the reference's fetchers download+cache;
``MnistDataFetcher.java``).

The fallback is LOUD: every synthetic substitution logs a warning and is
recorded in ``synthetic_fallbacks`` (tests tag themselves with it); set
``DL4J_TPU_STRICT_DATA=1`` to raise instead of substituting."""

from __future__ import annotations

import gzip
import logging
import os
import struct
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from .iterators import ArrayIterator

DATA_DIR = Path(os.environ.get("DL4J_TPU_DATA", Path.home() / ".deeplearning4j_tpu" / "data"))

logger = logging.getLogger(__name__)

#: dataset names that fell back to synthetic data in this process
synthetic_fallbacks: set = set()


def _synthetic_fallback(name: str, expected_path) -> None:
    """Record + loudly announce a synthetic substitution (or raise under
    DL4J_TPU_STRICT_DATA=1)."""
    msg = (f"dataset '{name}': no local copy at {expected_path}; using a "
           f"deterministic SYNTHETIC replica (zero-egress environment). "
           f"Place the real files there or set DL4J_TPU_DATA.")
    if os.environ.get("DL4J_TPU_STRICT_DATA") == "1":
        raise FileNotFoundError(msg)
    logger.warning(msg)
    synthetic_fallbacks.add(name)


def _read_idx(path: Path) -> np.ndarray:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


def _synthetic_images(n: int, h: int, w: int, c: int, num_classes: int, seed: int):
    """Deterministic class-structured synthetic images: each class k gets a
    distinct frequency pattern + noise, so models can actually learn."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, n)
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    base = np.stack([np.sin(xx * (k + 1) * np.pi / w) * np.cos(yy * (k % 3 + 1) * np.pi / h)
                     for k in range(num_classes)])  # (K, h, w)
    imgs = base[labels][..., None] * 0.5 + rng.standard_normal((n, h, w, 1)).astype(np.float32) * 0.25
    if c > 1:
        imgs = np.repeat(imgs, c, axis=-1)
    onehot = np.eye(num_classes, dtype=np.float32)[labels]
    return imgs.astype(np.float32), onehot


def load_mnist(train: bool = True, num_examples: Optional[int] = None,
               ) -> Tuple[np.ndarray, np.ndarray]:
    """MNIST as (N, 28, 28, 1) float [0,1] + one-hot labels.

    Looks for IDX files under $DL4J_TPU_DATA/mnist/ (standard names);
    synthesizes a replica otherwise.
    """
    split = "train" if train else "t10k"
    d = DATA_DIR / "mnist"
    img_p = next((p for p in [d / f"{split}-images-idx3-ubyte", d / f"{split}-images-idx3-ubyte.gz"] if p.exists()), None)
    lab_p = next((p for p in [d / f"{split}-labels-idx1-ubyte", d / f"{split}-labels-idx1-ubyte.gz"] if p.exists()), None)
    if img_p and lab_p:
        imgs = _read_idx(img_p).astype(np.float32)[..., None] / 255.0
        labels = np.eye(10, dtype=np.float32)[_read_idx(lab_p)]
    else:
        _synthetic_fallback("mnist", d)
        n = 8192 if train else 1024
        imgs, labels = _synthetic_images(n, 28, 28, 1, 10, seed=0 if train else 1)
        imgs = (imgs - imgs.min()) / (imgs.max() - imgs.min())
    if num_examples:
        imgs, labels = imgs[:num_examples], labels[:num_examples]
    return imgs, labels


def mnist_iterator(batch_size: int = 128, train: bool = True,
                   num_examples: Optional[int] = None, seed: int = 0) -> ArrayIterator:
    """MnistDataSetIterator parity."""
    f, l = load_mnist(train, num_examples)
    return ArrayIterator(f, l, batch_size, shuffle=train, seed=seed)


def load_iris() -> Tuple[np.ndarray, np.ndarray]:
    """IrisDataSetIterator parity — the classic 150x4; generated from the
    published per-class statistics when no local copy exists."""
    p = DATA_DIR / "iris.npy"
    if p.exists():
        d = np.load(p, allow_pickle=True).item()
        return d["x"], d["y"]
    # statistical regeneration, not a class-blob fake — do not flag strict
    rng = np.random.default_rng(42)
    means = np.array([[5.01, 3.43, 1.46, 0.25], [5.94, 2.77, 4.26, 1.33], [6.59, 2.97, 5.55, 2.03]])
    stds = np.array([[0.35, 0.38, 0.17, 0.11], [0.52, 0.31, 0.47, 0.20], [0.64, 0.32, 0.55, 0.27]])
    xs, ys = [], []
    for k in range(3):
        xs.append(rng.standard_normal((50, 4)) * stds[k] + means[k])
        ys.append(np.full(50, k))
    x = np.concatenate(xs).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.concatenate(ys)]
    return x, y


def load_cifar10(train: bool = True, num_examples: Optional[int] = None):
    """CifarDataSetIterator parity — (N, 32, 32, 3) float [0,1] + one-hot.

    Reads the standard python-pickle batches under $DL4J_TPU_DATA/
    cifar-10-batches-py/; synthetic fallback otherwise."""
    d = DATA_DIR / "cifar-10-batches-py"
    names = ([f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"])
    if all((d / n).exists() for n in names):
        import pickle

        xs, ys = [], []
        for n in names:
            with open(d / n, "rb") as f:
                batch = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(batch[b"data"], np.uint8))
            ys.append(np.asarray(batch[b"labels"], np.int64))
        x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        imgs = x.astype(np.float32) / 255.0
        labels = np.eye(10, dtype=np.float32)[np.concatenate(ys)]
    else:
        _synthetic_fallback("cifar10", d)
        n = num_examples or (4096 if train else 512)
        return _synthetic_images(n, 32, 32, 3, 10, seed=2 if train else 3)
    if num_examples:
        imgs, labels = imgs[:num_examples], labels[:num_examples]
    return imgs, labels


# --- EMNIST (datasets/fetchers/EmnistDataFetcher.java) ---

EMNIST_CLASSES = {"byclass": 62, "bymerge": 47, "balanced": 47,
                  "letters": 26, "digits": 10, "mnist": 10}


def load_emnist(split: str = "balanced", train: bool = True,
                num_examples: Optional[int] = None):
    """EMNIST as (N, 28, 28, 1) float [0,1] + one-hot over the split's
    classes. Looks for the standard IDX names under $DL4J_TPU_DATA/emnist/."""
    if split not in EMNIST_CLASSES:
        raise ValueError(f"Unknown EMNIST split '{split}' "
                         f"(expected one of {sorted(EMNIST_CLASSES)})")
    k = EMNIST_CLASSES[split]
    part = "train" if train else "test"
    d = DATA_DIR / "emnist"
    img_p = next((p for p in [d / f"emnist-{split}-{part}-images-idx3-ubyte",
                              d / f"emnist-{split}-{part}-images-idx3-ubyte.gz"]
                  if p.exists()), None)
    lab_p = next((p for p in [d / f"emnist-{split}-{part}-labels-idx1-ubyte",
                              d / f"emnist-{split}-{part}-labels-idx1-ubyte.gz"]
                  if p.exists()), None)
    if img_p and lab_p:
        imgs = _read_idx(img_p).astype(np.float32)[..., None] / 255.0
        raw = _read_idx(lab_p).astype(np.int64)
        if split == "letters":  # letters labels are 1..26
            raw = raw - 1
        labels = np.eye(k, dtype=np.float32)[raw]
    else:
        _synthetic_fallback(f"emnist-{split}", d)
        n = 4096 if train else 512
        imgs, labels = _synthetic_images(n, 28, 28, 1, k, seed=4 if train else 5)
    if num_examples:
        imgs, labels = imgs[:num_examples], labels[:num_examples]
    return imgs, labels


# --- SVHN (datasets/fetchers/SvhnDataFetcher.java) ---


def load_svhn(train: bool = True, num_examples: Optional[int] = None):
    """SVHN cropped digits as (N, 32, 32, 3) float [0,1] + one-hot(10).
    Reads the standard {train,test}_32x32.mat under $DL4J_TPU_DATA/svhn/."""
    d = DATA_DIR / "svhn"
    p = d / (f"{'train' if train else 'test'}_32x32.mat")
    if p.exists():
        from scipy.io import loadmat

        m = loadmat(str(p))
        x = np.transpose(m["X"], (3, 0, 1, 2)).astype(np.float32) / 255.0
        raw = m["y"].ravel().astype(np.int64) % 10  # '10' encodes digit 0
        labels = np.eye(10, dtype=np.float32)[raw]
    else:
        _synthetic_fallback("svhn", p)
        n = 4096 if train else 512
        x, labels = _synthetic_images(n, 32, 32, 3, 10, seed=6 if train else 7)
    if num_examples:
        x, labels = x[:num_examples], labels[:num_examples]
    return x, labels


# --- TinyImageNet (datasets/fetchers/TinyImageNetFetcher.java) ---


def load_tiny_imagenet(train: bool = True, num_examples: Optional[int] = None,
                       image_size: int = 64):
    """TinyImageNet-200 as (N, 64, 64, 3). Reads the standard directory
    layout under $DL4J_TPU_DATA/tiny-imagenet-200/ via ImageRecordReader."""
    base = DATA_DIR / "tiny-imagenet-200"
    root = base / ("train" if train else "val")
    if root.exists():
        from .records import ImageRecordReader

        if train:
            rr = ImageRecordReader(str(root), image_size, image_size, 3)
            files = [(p, li) for p, li in rr._files]
            n_classes = len(rr.labels)
        else:
            # standard val layout: val/images/*.JPEG + val_annotations.txt
            # (no per-class subdirs); class order follows train/ (or wnids.txt)
            wnids_p = base / "wnids.txt"
            if wnids_p.exists():
                wnids = sorted(wnids_p.read_text().split())
            else:
                wnids = sorted(d.name for d in (base / "train").iterdir() if d.is_dir())
            idx = {w: i for i, w in enumerate(wnids)}
            n_classes = len(wnids)
            ann = root / "val_annotations.txt"
            rr = ImageRecordReader.__new__(ImageRecordReader)
            rr.h, rr.w, rr.c = image_size, image_size, 3
            files = []
            for line in ann.read_text().splitlines():
                parts = line.split("\t")
                if len(parts) >= 2 and parts[1] in idx:
                    files.append((root / "images" / parts[0], idx[parts[1]]))
        n = min(len(files), num_examples or len(files))
        xs = np.zeros((n, image_size, image_size, 3), np.float32)
        ys = np.zeros(n, np.int64)
        for i, (p, li) in enumerate(files[:n]):
            xs[i], ys[i] = rr.load_image(p), li
        labels = np.eye(n_classes, dtype=np.float32)[ys]
        return xs, labels
    _synthetic_fallback("tiny-imagenet", root)
    n = num_examples or (2048 if train else 256)
    return _synthetic_images(n, image_size, image_size, 3, 200,
                             seed=8 if train else 9)


# --- LFW (datasets/fetchers/LFWDataFetcher.java) ---


def load_lfw(num_examples: Optional[int] = None, image_size: int = 64,
             min_faces_per_person: int = 2):
    """Labeled Faces in the Wild as (N, H, W, 3) + one-hot person labels.
    Reads $DL4J_TPU_DATA/lfw/<person>/*.jpg; people with fewer than
    ``min_faces_per_person`` images are dropped (fetcher parity)."""
    root = DATA_DIR / "lfw"
    if root.exists():
        from .records import ImageRecordReader

        rr = ImageRecordReader(str(root), image_size, image_size, 3,
                               min_examples_per_label=min_faces_per_person)
        n = min(len(rr), num_examples or len(rr))
        xs = np.zeros((n, image_size, image_size, 3), np.float32)
        ys = np.zeros(n, np.int64)
        for i, rec in enumerate(rr):
            if i >= n:
                break
            xs[i], ys[i] = rec[0], rec[1]
        labels = np.eye(len(rr.labels), dtype=np.float32)[ys]
        return xs, labels
    _synthetic_fallback("lfw", root)
    n = num_examples or 1024
    return _synthetic_images(n, image_size, image_size, 3, 40, seed=10)


# --- UCI synthetic-control (datasets/fetchers/UciSequenceDataFetcher.java) ---


def load_uci_synthetic_control(train: bool = True):
    """UCI synthetic-control time series: 600 univariate series of length 60
    in 6 classes. Reads $DL4J_TPU_DATA/uci/synthetic_control.data; otherwise
    regenerates from the published generator equations (this dataset IS
    synthetic by definition, so the regeneration is faithful, not a fake).

    Returns (x (N, 60, 1), y one-hot (N, 6)) with the reference's 450/150
    train/test split.
    """
    p = DATA_DIR / "uci" / "synthetic_control.data"
    if p.exists():
        rows = np.loadtxt(str(p), dtype=np.float32)
        x = rows.reshape(600, 60, 1)
        y = np.repeat(np.arange(6), 100)
    else:
        rng = np.random.default_rng(11)
        t = np.arange(60, dtype=np.float32)
        series = []
        for k in range(6):
            for _ in range(100):
                base = 30 + rng.standard_normal(60) * 2
                if k == 1:   # cyclic
                    base += 15 * np.sin(2 * np.pi * t / rng.uniform(10, 15))
                elif k == 2:  # increasing trend
                    base += rng.uniform(0.2, 0.5) * t
                elif k == 3:  # decreasing trend
                    base -= rng.uniform(0.2, 0.5) * t
                elif k == 4:  # upward shift
                    base += np.where(t >= rng.integers(20, 40), rng.uniform(7.5, 20), 0)
                elif k == 5:  # downward shift
                    base -= np.where(t >= rng.integers(20, 40), rng.uniform(7.5, 20), 0)
                series.append(base)
        x = np.asarray(series, np.float32)[..., None]
        y = np.repeat(np.arange(6), 100)
    onehot = np.eye(6, dtype=np.float32)[y]
    # reference split: interleaved 75/25 per class
    idx = np.arange(600)
    mask = (idx % 4) != 3
    sel = mask if train else ~mask
    return x[sel], onehot[sel]


def char_rnn_corpus(length: int = 100_000, seed: int = 0) -> Tuple[np.ndarray, dict]:
    """Synthetic character corpus for the GravesLSTM char-RNN baseline config
    (BASELINE.md #3) — Markov-structured text so an LSTM has signal to learn."""
    rng = np.random.default_rng(seed)
    words = ["the", "quick", "brown", "fox", "jumps", "over", "lazy", "dog",
             "neural", "network", "tensor", "gradient", "descent", "learning"]
    text = " ".join(rng.choice(words, size=length // 6))[:length]
    vocab = sorted(set(text))
    ch2id = {c: i for i, c in enumerate(vocab)}
    ids = np.array([ch2id[c] for c in text], np.int32)
    return ids, ch2id
