"""Canned datasets — parity with deeplearning4j-core fetchers (MNIST, EMNIST,
Iris, CIFAR, ...; SURVEY.md §2.2). Zero-egress environment: loaders read
local files when present (IDX/NumPy formats) and otherwise fall back to a
deterministic synthetic replica with the same shapes/classes, so every example
and test runs hermetically (the reference's fetchers download+cache;
MnistDataFetcher.java)."""

from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from .iterators import ArrayIterator

DATA_DIR = Path(os.environ.get("DL4J_TPU_DATA", Path.home() / ".deeplearning4j_tpu" / "data"))


def _read_idx(path: Path) -> np.ndarray:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


def _synthetic_images(n: int, h: int, w: int, c: int, num_classes: int, seed: int):
    """Deterministic class-structured synthetic images: each class k gets a
    distinct frequency pattern + noise, so models can actually learn."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, n)
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    base = np.stack([np.sin(xx * (k + 1) * np.pi / w) * np.cos(yy * (k % 3 + 1) * np.pi / h)
                     for k in range(num_classes)])  # (K, h, w)
    imgs = base[labels][..., None] * 0.5 + rng.standard_normal((n, h, w, 1)).astype(np.float32) * 0.25
    if c > 1:
        imgs = np.repeat(imgs, c, axis=-1)
    onehot = np.eye(num_classes, dtype=np.float32)[labels]
    return imgs.astype(np.float32), onehot


def load_mnist(train: bool = True, num_examples: Optional[int] = None,
               ) -> Tuple[np.ndarray, np.ndarray]:
    """MNIST as (N, 28, 28, 1) float [0,1] + one-hot labels.

    Looks for IDX files under $DL4J_TPU_DATA/mnist/ (standard names);
    synthesizes a replica otherwise.
    """
    split = "train" if train else "t10k"
    d = DATA_DIR / "mnist"
    img_p = next((p for p in [d / f"{split}-images-idx3-ubyte", d / f"{split}-images-idx3-ubyte.gz"] if p.exists()), None)
    lab_p = next((p for p in [d / f"{split}-labels-idx1-ubyte", d / f"{split}-labels-idx1-ubyte.gz"] if p.exists()), None)
    if img_p and lab_p:
        imgs = _read_idx(img_p).astype(np.float32)[..., None] / 255.0
        labels = np.eye(10, dtype=np.float32)[_read_idx(lab_p)]
    else:
        n = 8192 if train else 1024
        imgs, labels = _synthetic_images(n, 28, 28, 1, 10, seed=0 if train else 1)
        imgs = (imgs - imgs.min()) / (imgs.max() - imgs.min())
    if num_examples:
        imgs, labels = imgs[:num_examples], labels[:num_examples]
    return imgs, labels


def mnist_iterator(batch_size: int = 128, train: bool = True,
                   num_examples: Optional[int] = None, seed: int = 0) -> ArrayIterator:
    """MnistDataSetIterator parity."""
    f, l = load_mnist(train, num_examples)
    return ArrayIterator(f, l, batch_size, shuffle=train, seed=seed)


def load_iris() -> Tuple[np.ndarray, np.ndarray]:
    """IrisDataSetIterator parity — the classic 150x4; generated from the
    published per-class statistics when no local copy exists."""
    p = DATA_DIR / "iris.npy"
    if p.exists():
        d = np.load(p, allow_pickle=True).item()
        return d["x"], d["y"]
    rng = np.random.default_rng(42)
    means = np.array([[5.01, 3.43, 1.46, 0.25], [5.94, 2.77, 4.26, 1.33], [6.59, 2.97, 5.55, 2.03]])
    stds = np.array([[0.35, 0.38, 0.17, 0.11], [0.52, 0.31, 0.47, 0.20], [0.64, 0.32, 0.55, 0.27]])
    xs, ys = [], []
    for k in range(3):
        xs.append(rng.standard_normal((50, 4)) * stds[k] + means[k])
        ys.append(np.full(50, k))
    x = np.concatenate(xs).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.concatenate(ys)]
    return x, y


def load_cifar10(train: bool = True, num_examples: Optional[int] = None):
    """CifarDataSetIterator parity — (N, 32, 32, 3); synthetic fallback."""
    n = num_examples or (4096 if train else 512)
    imgs, labels = _synthetic_images(n, 32, 32, 3, 10, seed=2 if train else 3)
    return imgs, labels


def char_rnn_corpus(length: int = 100_000, seed: int = 0) -> Tuple[np.ndarray, dict]:
    """Synthetic character corpus for the GravesLSTM char-RNN baseline config
    (BASELINE.md #3) — Markov-structured text so an LSTM has signal to learn."""
    rng = np.random.default_rng(seed)
    words = ["the", "quick", "brown", "fox", "jumps", "over", "lazy", "dog",
             "neural", "network", "tensor", "gradient", "descent", "learning"]
    text = " ".join(rng.choice(words, size=length // 6))[:length]
    vocab = sorted(set(text))
    ch2id = {c: i for i, c in enumerate(vocab)}
    ids = np.array([ch2id[c] for c in text], np.int32)
    return ids, ch2id
