"""Data plumbing (L0): iterators, normalizers, canned datasets — replaces
DataVec + DL4J dataset iterator stack with a pure-Python pipeline feeding
device-prefetched numpy batches."""

from .datasets import (char_rnn_corpus, load_cifar10, load_iris, load_mnist,
                       mnist_iterator)
from .iterators import (ArrayIterator, AsyncIterator, BenchmarkIterator,
                        DataSet, DataSetIterator, EarlyTerminationIterator,
                        FileDataSetIterator, export_batches,
                        MultiDataSet, MultipleEpochsIterator, split_iterator)
from .normalizers import (ImageScaler, MinMaxScaler, Normalizer, Standardize,
                          VGG16Preprocessor)

__all__ = ["ArrayIterator", "AsyncIterator", "BenchmarkIterator", "DataSet",
           "DataSetIterator", "EarlyTerminationIterator", "FileDataSetIterator",
           "ImageScaler",
           "MinMaxScaler", "MultiDataSet", "MultipleEpochsIterator", "export_batches",
           "Normalizer", "Standardize", "VGG16Preprocessor", "char_rnn_corpus",
           "load_cifar10", "load_iris", "load_mnist", "mnist_iterator",
           "split_iterator"]
