"""Evaluation suite — parity with deeplearning4j eval/ (SURVEY.md §2.1)."""

from .evaluation import (ROC, Evaluation, EvaluationBinary,
                         EvaluationCalibration, Prediction, ROCBinary,
                         ROCMultiClass, RegressionEvaluation)
from .tools import (export_evaluation_to_html, export_roc_charts_to_html)

__all__ = ["Evaluation", "EvaluationBinary", "EvaluationCalibration",
           "Prediction", "ROC", "ROCBinary", "ROCMultiClass",
           "RegressionEvaluation", "export_evaluation_to_html",
           "export_roc_charts_to_html"]
