"""Evaluation suite — parity with deeplearning4j eval/ (SURVEY.md §2.1)."""

from .evaluation import (ROC, Evaluation, EvaluationBinary,
                         EvaluationCalibration, ROCMultiClass,
                         RegressionEvaluation)

__all__ = ["Evaluation", "EvaluationBinary", "EvaluationCalibration", "ROC",
           "ROCMultiClass", "RegressionEvaluation"]
