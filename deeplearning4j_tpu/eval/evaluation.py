"""Evaluation — parity with DL4J's eval package (~6k LoC; SURVEY.md §2.1):
``eval/Evaluation.java`` (accuracy/precision/recall/F1/confusion matrix,
top-N), ``EvaluationBinary``, ``RegressionEvaluation`` (MSE/MAE/RMSE/R²),
``ROC``/``ROCBinary``/``ROCMultiClass`` (AUC + PR curves),
``EvaluationCalibration`` (reliability diagram), and the curve records in
``eval/curves/``.

Design: accumulators hold numpy state on host (evaluation is not the hot
path); batch statistics are computed with vectorized numpy. ``eval_step``
helpers exist for computing predictions on device inside a jit, then stats
accumulate on host — matching how the reference streams eval over an iterator.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def _to_np(a):
    return np.asarray(a)


class _Mergeable:
    """Distributed-evaluation protocol (IEvaluation.merge parity — the
    reference evaluates ANY IEvaluation across the cluster and reduces:
    dl4j-spark ``IEvaluateFlatMapFunction.java`` +
    ``IEvaluationReduceFunction.java``). Subclasses list their additive
    accumulator fields in ``_STATE_FIELDS``; everything needed for
    per-process accumulate -> allgather -> merge follows:

    - ``state()``: accumulators as a flat dict of numpy arrays (allgatherable)
    - ``load_state(d)``: overwrite accumulators from such a dict
    - ``merge(other)``: combine two accumulators (additive by default)
    - ``new_like()``: empty instance with the same configuration
    """

    _STATE_FIELDS: Tuple[str, ...] = ()

    def state(self) -> Dict[str, np.ndarray]:
        return {f: np.asarray(getattr(self, f)) for f in self._STATE_FIELDS}

    def load_state(self, d: Dict[str, np.ndarray]):
        for f in self._STATE_FIELDS:
            cur = getattr(self, f)
            v = d[f]
            setattr(self, f, type(cur)(v) if isinstance(cur, (int, float))
                    else np.asarray(v))
        return self

    def merge(self, other):
        for f in self._STATE_FIELDS:
            setattr(self, f, getattr(self, f) + getattr(other, f))
        return self

    def new_like(self):
        raise NotImplementedError


def _labels_to_idx(labels):
    labels = _to_np(labels)
    if labels.ndim >= 2 and labels.shape[-1] > 1:
        return labels.argmax(-1)
    return labels.astype(np.int64).reshape(labels.shape[0], *labels.shape[1:-1]) if labels.ndim >= 2 else labels.astype(np.int64)


@dataclass
class Prediction:
    """eval/meta/Prediction.java — which example landed in which confusion
    cell, for error inspection (``record_metadata`` on :class:`Evaluation`)."""

    actual: int
    predicted: int
    metadata: object = None


class _AutoId(int):
    """Marker for auto-generated running-index metadata ids: merge offsets
    ONLY these (position in the concatenated stream); user-supplied ids —
    even ints — are never rewritten."""


class Evaluation(_Mergeable):
    """eval/Evaluation.java — multiclass classification metrics.

    Accepts (B, K) batches or time-series (B, T, K) with optional (B, T) mask.

    ``record_metadata=True`` captures a :class:`Prediction` per example on
    EVERY eval call (auto-numbering batches without ids); passing
    ``eval(..., metadata=[...])`` captures that batch regardless — the
    reference's ``eval(labels, out, recordMetaData)`` overload records
    exactly the batches that supply ids. Inspect with
    :meth:`prediction_errors` /
    :meth:`predictions_by_actual_class` / :meth:`predictions_by_predicted_class`.
    Predictions merge by concatenation; they ride along ``merge()`` but are
    NOT part of the numpy ``state()`` dict (the distributed allgather path
    exchanges fixed-shape accumulators only — DL4J likewise excludes
    metadata from its Spark reduce)."""

    _STATE_FIELDS = ("confusion", "top_n_correct", "top_n_total")

    def new_like(self) -> "Evaluation":
        return Evaluation(self.num_classes, self.top_n,
                          record_metadata=self.record_metadata)

    def merge(self, other):
        super().merge(other)
        # auto ids are running indices local to OTHER's stream: offset them
        # past this instance's predictions so merged ids stay unique and
        # equal to the position in the concatenated stream (exactly what one
        # instance over the whole stream assigns); explicit user ids are
        # never rewritten
        base = len(self.predictions)
        self.predictions.extend(
            Prediction(pr.actual, pr.predicted, _AutoId(pr.metadata + base))
            if isinstance(pr.metadata, _AutoId) else pr
            for pr in getattr(other, "predictions", ()))
        return self

    def __init__(self, num_classes: int, top_n: int = 1,
                 record_metadata: bool = False):
        self.num_classes = num_classes
        self.top_n = top_n
        self.record_metadata = record_metadata
        self.predictions: List[Prediction] = []
        self.confusion = np.zeros((num_classes, num_classes), np.int64)
        self.top_n_correct = 0
        self.top_n_total = 0

    def eval(self, labels, predictions, mask=None, metadata=None):
        y = _to_np(labels)
        p = _to_np(predictions)
        meta = list(metadata) if metadata is not None else None
        if meta is not None and len(meta) != y.shape[0]:
            raise ValueError(
                f"metadata has {len(meta)} entries for a batch of "
                f"{y.shape[0]} examples — one id per example required")
        # explicit ids capture THIS batch (the reference's
        # eval(labels, out, recordMetaData) overload records exactly the
        # batches that supply ids); record_metadata=True captures every
        # batch, auto-numbering the ones without ids
        capture = self.record_metadata or meta is not None
        if y.ndim == 3:  # time series: flatten with mask
            if mask is not None:
                m = _to_np(mask).astype(bool).reshape(-1)
            else:
                m = np.ones(y.shape[0] * y.shape[1], bool)
            if meta is not None:  # one id per (example, timestep)
                T = y.shape[1]
                meta = [(mid, t) for mid in meta for t in range(T)]
                meta = [x for x, keep in zip(meta, m) if keep]
            y = y.reshape(-1, y.shape[-1])[m]
            p = p.reshape(-1, p.shape[-1])[m]
        yi = y.argmax(-1)
        pi = p.argmax(-1)
        np.add.at(self.confusion, (yi, pi), 1)
        if capture:
            base = len(self.predictions)
            if meta is None:
                meta = [_AutoId(i) for i in range(base, base + len(yi))]
            self.predictions.extend(
                Prediction(int(a), int(b), mid)
                for a, b, mid in zip(yi, pi, meta))
        if self.top_n > 1:
            topn = np.argsort(-p, axis=-1)[:, : self.top_n]
            self.top_n_correct += int((topn == yi[:, None]).any(-1).sum())
            self.top_n_total += len(yi)
        return self

    # --- prediction metadata (eval/meta/Prediction.java) ---
    def prediction_errors(self) -> List[Prediction]:
        """Misclassified examples (getPredictionErrors)."""
        return [pr for pr in self.predictions if pr.actual != pr.predicted]

    def predictions_by_actual_class(self, cls: int) -> List[Prediction]:
        return [pr for pr in self.predictions if pr.actual == cls]

    def predictions_by_predicted_class(self, cls: int) -> List[Prediction]:
        return [pr for pr in self.predictions if pr.predicted == cls]

    # --- metrics (Evaluation.java getters) ---
    @property
    def num_examples(self) -> int:
        return int(self.confusion.sum())

    def accuracy(self) -> float:
        n = self.confusion.sum()
        return float(np.trace(self.confusion) / n) if n else 0.0

    def top_n_accuracy(self) -> float:
        return self.top_n_correct / self.top_n_total if self.top_n_total else 0.0

    def true_positives(self) -> np.ndarray:
        return np.diag(self.confusion)

    def false_positives(self) -> np.ndarray:
        return self.confusion.sum(0) - np.diag(self.confusion)

    def false_negatives(self) -> np.ndarray:
        return self.confusion.sum(1) - np.diag(self.confusion)

    def precision(self, cls: Optional[int] = None) -> float:
        tp, fp = self.true_positives(), self.false_positives()
        if cls is not None:
            d = tp[cls] + fp[cls]
            return float(tp[cls] / d) if d else 0.0
        # macro-average over classes that appear (DL4J convention)
        seen = (self.confusion.sum(1) + self.confusion.sum(0)) > 0
        vals = [float(tp[k] / (tp[k] + fp[k])) if tp[k] + fp[k] else 0.0
                for k in range(self.num_classes) if seen[k]]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        tp, fn = self.true_positives(), self.false_negatives()
        if cls is not None:
            d = tp[cls] + fn[cls]
            return float(tp[cls] / d) if d else 0.0
        seen = (self.confusion.sum(1) + self.confusion.sum(0)) > 0
        vals = [float(tp[k] / (tp[k] + fn[k])) if tp[k] + fn[k] else 0.0
                for k in range(self.num_classes) if seen[k]]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def matthews_correlation(self) -> float:
        c = self.confusion.astype(np.float64)
        t = c.sum()
        s = np.trace(c)
        pk = c.sum(0)
        tk = c.sum(1)
        num = s * t - tk @ pk
        den = np.sqrt(t * t - pk @ pk) * np.sqrt(t * t - tk @ tk)
        return float(num / den) if den else 0.0

    def stats(self) -> str:
        """Evaluation.stats() textual report."""
        lines = [
            f"# examples: {self.num_examples}",
            f"Accuracy:  {self.accuracy():.4f}",
            f"Precision: {self.precision():.4f}",
            f"Recall:    {self.recall():.4f}",
            f"F1:        {self.f1():.4f}",
        ]
        if self.top_n > 1:
            lines.append(f"Top-{self.top_n} accuracy: {self.top_n_accuracy():.4f}")
        lines.append("Confusion matrix (rows=actual, cols=predicted):")
        lines.append(str(self.confusion))
        return "\n".join(lines)

class EvaluationBinary(_Mergeable):
    """EvaluationBinary.java — per-output independent binary metrics."""

    _STATE_FIELDS = ("tp", "fp", "tn", "fn")

    def new_like(self) -> "EvaluationBinary":
        return EvaluationBinary(self.n, self.threshold)

    def __init__(self, num_outputs: int, threshold: float = 0.5):
        self.n = num_outputs
        self.threshold = threshold
        self.tp = np.zeros(num_outputs, np.int64)
        self.fp = np.zeros(num_outputs, np.int64)
        self.tn = np.zeros(num_outputs, np.int64)
        self.fn = np.zeros(num_outputs, np.int64)

    def eval(self, labels, predictions, mask=None):
        y = _to_np(labels).reshape(-1, self.n) > 0.5
        p = _to_np(predictions).reshape(-1, self.n) >= self.threshold
        if mask is not None:
            m = _to_np(mask).astype(bool).reshape(-1)
            y, p = y[m], p[m]
        self.tp += (y & p).sum(0)
        self.fp += (~y & p).sum(0)
        self.tn += (~y & ~p).sum(0)
        self.fn += (y & ~p).sum(0)
        return self

    def accuracy(self, i: int) -> float:
        t = self.tp[i] + self.fp[i] + self.tn[i] + self.fn[i]
        return float((self.tp[i] + self.tn[i]) / t) if t else 0.0

    def precision(self, i: int) -> float:
        d = self.tp[i] + self.fp[i]
        return float(self.tp[i] / d) if d else 0.0

    def recall(self, i: int) -> float:
        d = self.tp[i] + self.fn[i]
        return float(self.tp[i] / d) if d else 0.0

    def f1(self, i: int) -> float:
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / (p + r) if (p + r) else 0.0


class RegressionEvaluation(_Mergeable):
    """RegressionEvaluation.java — per-column MSE/MAE/RMSE/R²/correlation."""

    _STATE_FIELDS = ("count", "sum_err2", "sum_abs_err", "sum_y", "sum_y2",
                     "sum_p", "sum_p2", "sum_yp")

    def new_like(self) -> "RegressionEvaluation":
        return RegressionEvaluation(self.n)

    def __init__(self, num_columns: int):
        self.n = num_columns
        self.count = 0
        self.sum_err2 = np.zeros(num_columns)
        self.sum_abs_err = np.zeros(num_columns)
        self.sum_y = np.zeros(num_columns)
        self.sum_y2 = np.zeros(num_columns)
        self.sum_p = np.zeros(num_columns)
        self.sum_p2 = np.zeros(num_columns)
        self.sum_yp = np.zeros(num_columns)

    def eval(self, labels, predictions, mask=None):
        y = _to_np(labels).reshape(-1, self.n).astype(np.float64)
        p = _to_np(predictions).reshape(-1, self.n).astype(np.float64)
        if mask is not None:
            m = _to_np(mask).astype(bool).reshape(-1)
            y, p = y[m], p[m]
        self.count += len(y)
        self.sum_err2 += ((p - y) ** 2).sum(0)
        self.sum_abs_err += np.abs(p - y).sum(0)
        self.sum_y += y.sum(0)
        self.sum_y2 += (y ** 2).sum(0)
        self.sum_p += p.sum(0)
        self.sum_p2 += (p ** 2).sum(0)
        self.sum_yp += (y * p).sum(0)
        return self

    def mse(self, i: int = 0) -> float:
        return float(self.sum_err2[i] / self.count) if self.count else 0.0

    def mae(self, i: int = 0) -> float:
        return float(self.sum_abs_err[i] / self.count) if self.count else 0.0

    def rmse(self, i: int = 0) -> float:
        return float(np.sqrt(self.mse(i)))

    def r2(self, i: int = 0) -> float:
        if not self.count:
            return 0.0
        ss_tot = self.sum_y2[i] - self.sum_y[i] ** 2 / self.count
        return float(1.0 - self.sum_err2[i] / ss_tot) if ss_tot else 0.0

    def pearson(self, i: int = 0) -> float:
        n = self.count
        num = n * self.sum_yp[i] - self.sum_y[i] * self.sum_p[i]
        den = np.sqrt(n * self.sum_y2[i] - self.sum_y[i] ** 2) * np.sqrt(n * self.sum_p2[i] - self.sum_p[i] ** 2)
        return float(num / den) if den else 0.0

    def stats(self) -> str:
        cols = [f"col {i}: MSE={self.mse(i):.5f} MAE={self.mae(i):.5f} RMSE={self.rmse(i):.5f} R2={self.r2(i):.5f}"
                for i in range(self.n)]
        return "\n".join(cols)


class ROC(_Mergeable):
    """ROC.java — binary ROC/AUC + precision-recall curve via threshold sweep.

    ``num_thresholds=0`` keeps exact scores (DL4J "exact" mode); otherwise a
    fixed-width histogram of scores is accumulated (streaming-friendly —
    and the mode to use for DISTRIBUTED evaluation: exact-mode state is
    variable-length and only merges when every process saw equal counts).
    """

    _STATE_FIELDS = ("pos_hist", "neg_hist")  # histogram mode

    def new_like(self) -> "ROC":
        return ROC(self.num_thresholds)

    def state(self):
        if self.num_thresholds:
            return super().state()
        return {"scores": (np.concatenate(self._scores) if self._scores
                           else np.zeros(0)),
                "labels": (np.concatenate(self._labels) if self._labels
                           else np.zeros(0))}

    def load_state(self, d):
        if self.num_thresholds:
            return super().load_state(d)
        self._scores = [np.asarray(d["scores"])]
        self._labels = [np.asarray(d["labels"])]
        return self

    def merge(self, other: "ROC") -> "ROC":
        if self.num_thresholds:
            return super().merge(other)
        self._scores.extend(other._scores)
        self._labels.extend(other._labels)
        return self

    def __init__(self, num_thresholds: int = 200):
        self.num_thresholds = num_thresholds
        if num_thresholds:
            self.pos_hist = np.zeros(num_thresholds + 1, np.int64)
            self.neg_hist = np.zeros(num_thresholds + 1, np.int64)
        else:
            self._scores: List[np.ndarray] = []
            self._labels: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None):
        y = _to_np(labels).reshape(-1)
        p = _to_np(predictions).reshape(-1)
        if y.size and _to_np(labels).ndim >= 2 and _to_np(labels).shape[-1] == 2:
            # two-column one-hot: positive class is column 1 (DL4J convention)
            y = _to_np(labels)[..., 1].reshape(-1)
            p = _to_np(predictions)[..., 1].reshape(-1)
        if mask is not None:
            m = _to_np(mask).astype(bool).reshape(-1)
            y, p = y[m], p[m]
        if self.num_thresholds:
            bins = np.clip((p * self.num_thresholds).astype(int), 0, self.num_thresholds)
            np.add.at(self.pos_hist, bins[y > 0.5], 1)
            np.add.at(self.neg_hist, bins[y <= 0.5], 1)
        else:
            self._scores.append(p)
            self._labels.append(y)
        return self

    def _curve_counts(self):
        """(tp, fp, P, N) in DESCENDING-threshold order: index 0 is the
        above-max threshold (tp=fp=0); the last index classifies everything
        positive. fpr/tpr derived from this are monotone non-decreasing, so
        integration needs no re-sorting (re-sorting ties at fpr=0 is exactly
        what mis-ordered saturated-score curves before)."""
        if self.num_thresholds:
            tp = np.concatenate([[0], np.cumsum(self.pos_hist[::-1])])
            fp = np.concatenate([[0], np.cumsum(self.neg_hist[::-1])])
            P, N = self.pos_hist.sum(), self.neg_hist.sum()
            return tp, fp, P, N
        p = np.concatenate(self._scores) if self._scores else np.zeros(0)
        y = np.concatenate(self._labels) if self._labels else np.zeros(0)
        order = np.argsort(-p, kind="stable")
        y_sorted = y[order] > 0.5
        tp = np.concatenate([[0], np.cumsum(y_sorted)])
        fp = np.concatenate([[0], np.cumsum(~y_sorted)])
        return tp, fp, y_sorted.sum(), (~y_sorted).sum()

    def roc_curve(self) -> Tuple[np.ndarray, np.ndarray]:
        """(fpr, tpr) from (0,0) to (1,1), descending threshold."""
        tp, fp, P, N = self._curve_counts()
        tpr = tp / max(P, 1)
        fpr = fp / max(N, 1)
        return fpr, tpr

    def auc(self) -> float:
        fpr, tpr = self.roc_curve()
        return float(np.trapezoid(tpr, fpr))

    def pr_curve(self) -> Tuple[np.ndarray, np.ndarray]:
        tp, fp, P, N = self._curve_counts()
        denom = np.maximum(tp + fp, 1)
        precision = np.where(tp + fp > 0, tp / denom, 1.0)
        recall = tp / max(P, 1)
        return recall, precision

    def auc_pr(self) -> float:
        r, p = self.pr_curve()
        return float(np.trapezoid(p, r))


class _ROCList(_Mergeable):
    """Shared plumbing for per-output / per-class ROC collections
    (:class:`ROCBinary`, :class:`ROCMultiClass`): a list of :class:`ROC`
    accumulators with prefixed flat state dicts, pairwise merge and AUC
    aggregation. Subclasses own the eval semantics."""

    _key = "o"  # state-dict prefix

    def state(self):
        return {f"{self._key}{k}_{f}": v for k, r in enumerate(self.rocs)
                for f, v in r.state().items()}

    def load_state(self, d):
        for k, r in enumerate(self.rocs):
            r.load_state({f: d[f"{self._key}{k}_{f}"] for f in r.state()})
        return self

    def merge(self, other):
        for r, o in zip(self.rocs, other.rocs):
            r.merge(o)
        return self

    def auc(self, i: int) -> float:
        return self.rocs[i].auc()

    def average_auc(self) -> float:
        return float(np.mean([r.auc() for r in self.rocs]))


class ROCBinary(_ROCList):
    """ROCBinary.java:28 — independent binary ROC/AUC per output column.

    For networks with ``n`` independent sigmoid outputs (multi-label):
    per-output ROC/AUC/PR, unlike :class:`EvaluationBinary`'s fixed-threshold
    counts. Accepts (B, n) or time-series (B, T, n); ``mask`` may be
    per-example (B,)/(B, 1)/(B, T) or PER-OUTPUT with the same shape as the
    labels (DL4J supports per-output masking for multi-label time series)."""

    def new_like(self) -> "ROCBinary":
        return ROCBinary(self.n, self.num_thresholds)

    def __init__(self, num_outputs: int, num_thresholds: int = 200):
        self.n = num_outputs
        self.num_thresholds = num_thresholds
        self.rocs = [ROC(num_thresholds) for _ in range(num_outputs)]

    def eval(self, labels, predictions, mask=None):
        y = _to_np(labels)
        p = _to_np(predictions)
        y2 = y.reshape(-1, self.n)
        p2 = p.reshape(-1, self.n)
        m2 = None
        if mask is not None:
            m = _to_np(mask)
            if m.shape == y.shape:  # per-output mask
                m2 = m.reshape(-1, self.n).astype(bool)
            else:  # per-example/timestep: keep or drop whole rows —
                # a (B,) mask against (B, T, n) labels broadcasts over T;
                # DL4J's column-vector (B, 1) / (B, T, 1) shapes squeeze
                m = m.astype(bool)
                while m.ndim > y.ndim - 1 and m.shape[-1] == 1:
                    m = m[..., 0]
                m = np.broadcast_to(
                    m.reshape(m.shape + (1,) * (y.ndim - 1 - m.ndim)),
                    y.shape[:-1])
                rows = m.reshape(-1)
                y2, p2 = y2[rows], p2[rows]
        for k, roc in enumerate(self.rocs):
            if m2 is not None:
                keep = m2[:, k]
                roc.eval(y2[keep, k], p2[keep, k])
            else:
                roc.eval(y2[:, k], p2[:, k])
        return self

    def auc_pr(self, output: int) -> float:
        return self.rocs[output].auc_pr()

    def roc_curve(self, output: int):
        return self.rocs[output].roc_curve()

    def pr_curve(self, output: int):
        return self.rocs[output].pr_curve()

    def stats(self) -> str:
        lines = [f"output {k}: AUC={self.auc(k):.4f} AUPRC={self.auc_pr(k):.4f}"
                 for k in range(self.n)]
        lines.append(f"average AUC: {self.average_auc():.4f}")
        return "\n".join(lines)


class ROCMultiClass(_ROCList):
    """ROCMultiClass.java — one-vs-all ROC per class."""

    _key = "c"

    def new_like(self) -> "ROCMultiClass":
        return ROCMultiClass(len(self.rocs), self.rocs[0].num_thresholds
                             if self.rocs else 200)

    def __init__(self, num_classes: int, num_thresholds: int = 200):
        self.rocs = [ROC(num_thresholds) for _ in range(num_classes)]

    def eval(self, labels, predictions, mask=None):
        y = _to_np(labels)
        p = _to_np(predictions)
        y2 = y.reshape(-1, y.shape[-1])
        p2 = p.reshape(-1, p.shape[-1])
        if mask is not None:
            m = _to_np(mask).astype(bool).reshape(-1)
            y2, p2 = y2[m], p2[m]
        for k, roc in enumerate(self.rocs):
            roc.eval(y2[:, k], p2[:, k])
        return self


class EvaluationCalibration(_Mergeable):
    """EvaluationCalibration.java — reliability diagram + residual histogram."""

    _STATE_FIELDS = ("bin_counts", "bin_pos", "bin_prob_sum")

    def new_like(self) -> "EvaluationCalibration":
        return EvaluationCalibration(self.num_bins)

    def __init__(self, num_bins: int = 10):
        self.num_bins = num_bins
        self.bin_counts = np.zeros(num_bins, np.int64)
        self.bin_pos = np.zeros(num_bins, np.int64)
        self.bin_prob_sum = np.zeros(num_bins)

    def eval(self, labels, predictions, mask=None):
        y = _to_np(labels).reshape(-1)
        p = _to_np(predictions).reshape(-1)
        if _to_np(labels).ndim >= 2 and _to_np(labels).shape[-1] > 1:
            yl = _to_np(labels).reshape(-1, _to_np(labels).shape[-1])
            pl = _to_np(predictions).reshape(-1, yl.shape[-1])
            y, p = yl.reshape(-1), pl.reshape(-1)
        bins = np.clip((p * self.num_bins).astype(int), 0, self.num_bins - 1)
        np.add.at(self.bin_counts, bins, 1)
        np.add.at(self.bin_pos, bins[y > 0.5], 1)
        np.add.at(self.bin_prob_sum, bins, p)
        return self

    def reliability(self) -> Tuple[np.ndarray, np.ndarray]:
        """(mean predicted prob, empirical frequency) per bin."""
        c = np.maximum(self.bin_counts, 1)
        return self.bin_prob_sum / c, self.bin_pos / c

    def expected_calibration_error(self) -> float:
        conf, freq = self.reliability()
        w = self.bin_counts / max(self.bin_counts.sum(), 1)
        return float(np.sum(w * np.abs(conf - freq)))
