"""EvaluationTools — HTML report generation for ROC / precision-recall /
calibration results.

Reference parity: ``deeplearning4j-core/.../evaluation/EvaluationTools.java``
(renders ROC + reliability charts to a standalone HTML page via the
ui-components DSL). Here the charts are inline SVG — no JS dependencies, one
self-contained file.
"""

from __future__ import annotations

import html as _html
from typing import Optional, Sequence, Tuple

import numpy as np

_PAGE = """<!DOCTYPE html>
<html><head><title>{title}</title>
<style>
body{{font-family:sans-serif;margin:24px;background:#fafafa}}
.card{{background:#fff;border:1px solid #ddd;display:inline-block;margin:8px;
padding:12px;vertical-align:top}}
h2,h3{{margin:6px}}
table{{border-collapse:collapse}} td,th{{padding:2px 10px;text-align:right}}
</style></head><body><h2>{title}</h2>{body}</body></html>"""


def _svg_curve(xs, ys, *, w=360, h=300, color="#d62728", diag=False,
               xlabel="", ylabel="") -> str:
    xs, ys = np.asarray(xs, float), np.asarray(ys, float)
    sx = lambda x: 40 + x * (w - 55)
    sy = lambda y: h - 30 - y * (h - 45)
    pts = " ".join(f"{sx(x):.1f},{sy(y):.1f}" for x, y in zip(xs, ys))
    parts = [f'<svg width="{w}" height="{h}">']
    parts.append(f'<rect x="40" y="15" width="{w-55}" height="{h-45}" '
                 f'fill="none" stroke="#ccc"/>')
    if diag:
        parts.append(f'<line x1="{sx(0):.1f}" y1="{sy(0):.1f}" x2="{sx(1):.1f}" '
                     f'y2="{sy(1):.1f}" stroke="#bbb" stroke-dasharray="4"/>')
    parts.append(f'<polyline fill="none" stroke="{color}" stroke-width="1.8" '
                 f'points="{pts}"/>')
    for t in (0.0, 0.5, 1.0):
        parts.append(f'<text x="{sx(t)-6:.0f}" y="{h-14}" font-size="10">{t:g}</text>')
        parts.append(f'<text x="14" y="{sy(t)+4:.0f}" font-size="10">{t:g}</text>')
    parts.append(f'<text x="{w//2-20}" y="{h-2}" font-size="11">{_html.escape(xlabel)}</text>')
    parts.append(f'<text x="2" y="12" font-size="11">{_html.escape(ylabel)}</text>')
    parts.append("</svg>")
    return "".join(parts)


def roc_chart_html(roc, title: str = "ROC") -> str:
    """One card: ROC curve + AUC (works for the binary ROC class)."""
    fpr, tpr = roc.roc_curve()
    auc = roc.auc()
    return (f'<div class="card"><h3>{_html.escape(title)} '
            f'(AUC={auc:.4f})</h3>'
            + _svg_curve(fpr, tpr, diag=True, xlabel="FPR", ylabel="TPR")
            + "</div>")


def pr_chart_html(roc, title: str = "Precision-Recall") -> str:
    rec, prec = roc.pr_curve()
    return (f'<div class="card"><h3>{_html.escape(title)}</h3>'
            + _svg_curve(rec, prec, color="#1f77b4", xlabel="recall",
                         ylabel="precision") + "</div>")


def calibration_chart_html(cal, title: str = "Reliability") -> str:
    conf, freq = cal.reliability()
    ok = np.isfinite(conf) & np.isfinite(freq)
    return (f'<div class="card"><h3>{_html.escape(title)}</h3>'
            + _svg_curve(conf[ok], freq[ok], color="#2ca02c", diag=True,
                         xlabel="confidence", ylabel="empirical frequency")
            + "</div>")


def export_roc_charts_to_html(roc, path: Optional[str] = None,
                              calibration=None,
                              title: str = "Evaluation report") -> str:
    """EvaluationTools.exportRocChartsToHtmlFile parity: ROC + PR (+ optional
    reliability) as one standalone HTML page; returns the HTML, writes it to
    ``path`` when given."""
    body = roc_chart_html(roc) + pr_chart_html(roc)
    if calibration is not None:
        body += calibration_chart_html(calibration)
    page = _PAGE.format(title=_html.escape(title), body=body)
    if path:
        with open(path, "w") as f:
            f.write(page)
    return page


def export_evaluation_to_html(evaluation, path: Optional[str] = None,
                              title: str = "Classification report") -> str:
    """Confusion-matrix + per-class P/R/F1 table as standalone HTML."""
    n = evaluation.num_classes
    cm = evaluation.confusion
    rows = ["<tr><th></th>" + "".join(f"<th>pred {j}</th>" for j in range(n))
            + "</tr>"]
    for i in range(n):
        rows.append(f"<tr><th>true {i}</th>"
                    + "".join(f"<td>{int(cm[i, j])}</td>" for j in range(n))
                    + "</tr>")
    stats = ["<tr><th>class</th><th>precision</th><th>recall</th><th>f1</th></tr>"]
    for c in range(n):
        stats.append(f"<tr><td>{c}</td><td>{evaluation.precision(c):.4f}</td>"
                     f"<td>{evaluation.recall(c):.4f}</td>"
                     f"<td>{evaluation.f1(c):.4f}</td></tr>")
    body = (f'<div class="card"><h3>accuracy {evaluation.accuracy():.4f}</h3>'
            f'<table>{"".join(rows)}</table></div>'
            f'<div class="card"><h3>per-class</h3><table>{"".join(stats)}</table></div>')
    page = _PAGE.format(title=_html.escape(title), body=body)
    if path:
        with open(path, "w") as f:
            f.write(page)
    return page
