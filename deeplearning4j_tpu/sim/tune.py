"""Serving-config autotuner — seeded random search + successive halving.

The serving pipeline is treated as a tunable program (TVM, arXiv
1802.04799; Relay, 1810.00952): a candidate is a full knob dict
(``sim/replay.py`` schema), its fitness is the deterministic
:func:`~.score.score` of a :class:`~.replay.VirtualReplayer` run, and the
search is classic **successive halving** — every candidate is scored on a
short prefix of the trace, the top ``1/eta`` survive to a prefix
``eta``× longer, until the final rung replays the full trace. Cheap early
rungs pay for wide exploration; the expensive full replay is spent on a
handful of finalists.

Two guarantees the smoke gate relies on:

- the **hand-picked default is candidate 0 and is never eliminated** — it
  rides every rung to the end, so the winner's full-trace score is ≥ the
  default's by construction (a config that only looked good on a prefix
  cannot beat the default by eliminating it early);
- everything is seeded and tie-broken by candidate index, so the same
  (trace, space, seed) always produces the same winner.

Winners persist into the AOT store via :func:`record_winner`, keyed by
(runtime/topology fingerprint, workload fingerprint) — see
``aot/tuned.py`` — so a booting replica resolves its tuned config the
same way it resolves its compiled executables.
"""

from __future__ import annotations

import copy
import json
import random
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

from .replay import (CostModel, DEFAULT_KNOBS, VirtualReplayer, merge_knobs,
                     set_flat)
from .score import score as score_report
from .workload import Trace

# Searched knobs and their candidate values. cluster.* knobs ride along in
# the recorded config but are NOT searched: the virtual cost model does not
# differentiate hedging/retry behavior (sim/README.md). autoscale.* knobs
# (forecast season/horizon/confidence floor) are searchable the same way —
# pass a space with `autoscale.forecast_*` keys; the winner's group feeds
# AutoscalePolicy.from_config and BurnForecaster.from_config.
DEFAULT_SPACE: Dict[str, Sequence] = {
    "engine.max_wait_ms": (0.5, 1.0, 2.0, 4.0, 8.0),
    "engine.queue_limit": (64, 128, 256, 512),
    "gen.slots": (2, 4, 8, 16),
    "gen.block_size": (8, 16, 32),
    "gen.prefill_chunk": (16, 32, 64, 128),
    "gen.decode_chunks": (1, 2, 4),
    "gen.queue_limit": (32, 64, 128),
    # prefix caching: whether to share whole-block prompt prefixes, and
    # how many pool blocks the cache may pin (None = bounded only by
    # capacity pressure via the reclaimer). Only differentiating when the
    # trace carries shared-prefix traffic (WorkloadSpec prefix_reuse > 0);
    # on legacy traces every candidate scores identically here.
    "gen.prefix_cache": (True, False),
    "gen.prefix_cache_blocks": (None, 16, 64),
}


class TuneResult(NamedTuple):
    """Search outcome: the winning knob dict plus its audit trail."""

    winner: dict
    winner_score: float
    default_score: float
    winner_report: dict
    evaluated: int              # total replay evaluations across rungs
    rungs: List[dict]           # per-rung: events, survivors, best score


def _canon(knobs: dict) -> str:
    return json.dumps(knobs, sort_keys=True, separators=(",", ":"))


class Tuner:
    """Search ``space`` over ``trace``, starting from ``base`` knobs."""

    def __init__(self, trace: Trace, *, space: Optional[dict] = None,
                 base: Optional[dict] = None,
                 cost_model: Optional[CostModel] = None, seed: int = 0):
        self.trace = trace
        self.space = dict(space if space is not None else DEFAULT_SPACE)
        self.base = merge_knobs(DEFAULT_KNOBS, base)
        self.cost_model = cost_model
        self.seed = int(seed)

    @classmethod
    def from_store(cls, trace: Trace, store, model_fp: str, *,
                   runtime: Optional[dict] = None, metrics=None,
                   **kwargs) -> "Tuner":
        """Boot with a **measured** cost model when the AOT store holds a
        profiler-captured :class:`~deeplearning4j_tpu.obs.costmodel
        .CostProfile` for (current runtime fingerprint, ``model_fp``) —
        resolution is counted on ``profile_store_hits_total`` /
        ``_misses_total``. A miss boots ``cost_model=None`` (the hand-set
        defaults), so virtual reports without a profile stay byte-identical
        to a plain :class:`Tuner`."""
        from ..obs.costmodel import get_profile

        profile = get_profile(store, model_fp, runtime=runtime,
                              metrics=metrics)
        if profile is not None:
            kwargs.setdefault("cost_model", CostModel.from_profile(profile))
        return cls(trace, **kwargs)

    def _sample(self, rng: random.Random) -> dict:
        cand = copy.deepcopy(self.base)
        for key in sorted(self.space):
            set_flat(cand, key, rng.choice(list(self.space[key])))
        return cand

    def _population(self, n: int) -> List[dict]:
        """Default first, then deduped random samples."""
        rng = random.Random(self.seed)
        pop = [copy.deepcopy(self.base)]
        seen = {_canon(self.base)}
        attempts = 0
        while len(pop) < n and attempts < n * 20:
            cand = self._sample(rng)
            attempts += 1
            key = _canon(cand)
            if key not in seen:
                seen.add(key)
                pop.append(cand)
        return pop

    def evaluate(self, knobs: dict, n_events: Optional[int] = None) -> dict:
        sliced = (self.trace if n_events is None
                  else self.trace.slice(n_events))
        return VirtualReplayer(sliced, knobs=knobs,
                               cost_model=self.cost_model).run()

    def search(self, candidates: int = 16, eta: int = 3,
               min_events: int = 128) -> TuneResult:
        """Successive halving; returns the full-trace winner."""
        pop = self._population(max(2, int(candidates)))
        n_total = max(1, len(self.trace))
        rung_events: List[int] = []
        b = min(min_events, n_total)
        while b < n_total:
            rung_events.append(b)
            b *= eta
        rung_events.append(n_total)

        # survivors carry (original_index, knobs); index 0 is the default
        survivors: List[Tuple[int, dict]] = list(enumerate(pop))
        evaluated = 0
        rungs: List[dict] = []
        scores: List[Tuple[float, int, dict, dict]] = []
        for depth, n_events in enumerate(rung_events):
            scores = []
            for idx, knobs in survivors:
                report = self.evaluate(knobs, n_events)
                evaluated += 1
                scores.append((float(report["score"]), idx, knobs, report))
            # stable rank: higher score first, earlier candidate on ties —
            # so re-runs are bit-identical and the default wins ties
            scores.sort(key=lambda s: (-s[0], s[1]))
            keep = max(2, len(scores) // max(2, int(eta)))
            if depth == len(rung_events) - 1:
                keep = len(scores)
            kept = scores[:keep]
            if not any(idx == 0 for _, idx, _, _ in kept):
                kept.append(next(s for s in scores if s[1] == 0))
            rungs.append({"events": n_events,
                          "candidates": len(scores),
                          "survivors": len(kept),
                          "best_score": kept[0][0]})
            survivors = [(idx, knobs) for _, idx, knobs, _ in kept]

        best_score, best_idx, best_knobs, best_report = scores[0]
        default_score = next(s[0] for s in scores if s[1] == 0)
        return TuneResult(winner=best_knobs, winner_score=best_score,
                          default_score=default_score,
                          winner_report=best_report, evaluated=evaluated,
                          rungs=rungs)


def record_winner(store, trace: Trace, result: TuneResult, *,
                  runtime: Optional[dict] = None) -> Optional[str]:
    """Persist the winner into the AOT store keyed by (runtime fingerprint,
    workload fingerprint); returns the store key (None if the put failed)."""
    from ..aot.tuned import put_tuned

    meta = {"score": result.winner_score,
            "default_score": result.default_score,
            "evaluated": result.evaluated}
    return put_tuned(store, trace.fingerprint(), result.winner,
                     runtime=runtime, extra_meta=meta)
