"""sim/ — deterministic trace-replay simulation + serving-config autotuning.

The scenario generator behind the "millions of users" claim: seeded
open-loop workload synthesis (``workload``), replay against either a
bit-deterministic discrete-event model of the serving stack or a live
in-process fleet (``replay``), deterministic scoring from the same
signals the obs stack exports (``score``), and a successive-halving
autotuner that persists winning knob sets into the AOT store keyed by
(runtime fingerprint, workload fingerprint) (``tune``).

Layering: sim/ sits ABOVE serve/, fleet/ and cluster/ — nothing below it
imports it. The store-side half of tuned-config resolution lives in
``aot/tuned.py`` so engines can resolve configs at boot without a sim
import.
"""

from .replay import (CostModel, DEFAULT_KNOBS, FleetTarget, LiveReplayer,
                     RouterTarget, VirtualReplayer, flatten_knobs,
                     merge_knobs, set_flat)
from .score import Outcome, REPORT_SCHEMA, TYPED_CAUSES, report_json, score, \
    summarize
from .tune import DEFAULT_SPACE, TuneResult, Tuner, record_winner
from .workload import (CLASS_DEADLINES_MS, Event, LengthDist, Trace,
                       WorkloadSpec, generate_trace, prompt_tokens,
                       smoke_spec)

__all__ = [
    "CLASS_DEADLINES_MS", "CostModel", "DEFAULT_KNOBS", "DEFAULT_SPACE",
    "Event", "FleetTarget", "LengthDist", "LiveReplayer", "Outcome",
    "REPORT_SCHEMA", "RouterTarget", "TYPED_CAUSES", "Trace", "TuneResult",
    "Tuner", "VirtualReplayer", "WorkloadSpec", "flatten_knobs",
    "generate_trace",
    "merge_knobs", "prompt_tokens", "record_winner", "report_json", "score",
    "set_flat", "smoke_spec", "summarize",
]
