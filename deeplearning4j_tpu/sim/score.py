"""Deterministic scoring — one replay (virtual or live) in, one JSON out.

The report schema is fixed (``sim-report-v1``) and every float is rounded
to 6 decimal places before serialization, so two runs of the same seeded
virtual replay produce **byte-identical** ``report_json`` strings — that
equality is the determinism gate in CI, not an eyeballed "close enough".

Scoring folds the same signals the obs stack exports for live fleets —
TTFT / inter-token p50/p99, SLO burn per class (``fleet_slo_burn_rate``
definition from ``obs.slo``: bad-fraction over the error budget), typed
shed counts by cause (``serve_shed_total``), and peak/mean KV-block
utilization (``serve_kv_block_utilization``) — into one higher-is-better
scalar so the tuner can rank configs:

    score = goodput_frac
            - 0.25 * min(burn_max, 4) / 4      # SLO budget overspend
            - 0.05 * min(ttft_p99_s, 2) / 2    # tail first-token latency
            - 0.05 * min(itl_p99_s, 0.5) / 0.5 # tail inter-token latency
            - 0.02 * kv_peak_utilization       # HBM headroom pressure
            - 0.05 * min(alert_firings, 4) / 4 # pages during the replay

Goodput dominates: a config that sheds half the trace can't win on
latency. The latency and KV terms break ties between configs with equal
goodput, which is exactly the regime successive halving operates in.
The alert term charges operator toil: a replay that stamped ``alerts``
firings (a live target with an AlertEngine attached) loses up to 0.05
for paging humans, so between two configs with equal goodput the tuner
prefers the quiet one. Reports without an ``alerts`` key are scored
exactly as before.
"""

from __future__ import annotations

import json
from typing import Dict, List, NamedTuple, Optional

from ..obs.slo import DEFAULT_TARGET, DEFAULT_TARGETS

REPORT_SCHEMA = "sim-report-v1"

# Causes that never count against the SLO budget (client-attributable or
# policy refusals) — mirrors fleet.registry._SLO_EXCLUDED.
SLO_EXCLUDED_CAUSES = frozenset(
    {"quota", "over_capacity", "bad_request", "client_gone"})

# Every cause a replay may legally record; anything else means an untyped
# failure leaked through (the smoke's "typed-errors-only" assertion).
TYPED_CAUSES = frozenset({
    "queue_full", "deadline", "over_capacity", "quota", "shutting_down",
    "worker_stall", "worker_dead", "drain_timeout", "publish_failed",
    "breaker_open", "no_replica", "bad_request", "client_gone",
    # router-tier causes (a replay through a ClusterRouter front door)
    "upstream_unreachable", "upstream_gone"})


class Outcome(NamedTuple):
    """One request's fate: ``ok`` with latencies, or a typed shed cause."""

    ok: bool
    cause: Optional[str]        # typed cause when not ok (or deadline-miss)
    slo: str
    model: str
    kind: str                   # "predict" | "generate"
    latency_s: Optional[float]  # arrival -> last byte (completed only)
    ttft_s: Optional[float]     # generate only
    itl_s: Optional[float]      # mean inter-token interval (generate only)
    tokens: int


def _pctile(sorted_vals: List[float], q: float) -> float:
    """Linear-interpolated percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


def _round(obj):
    """Recursively round floats so serialization is bit-stable."""
    if isinstance(obj, float):
        return round(obj, 6)
    if isinstance(obj, dict):
        return {k: _round(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_round(v) for v in obj]
    return obj


def summarize(workload_fp: str, outcomes: List[Outcome], *, mode: str,
              knobs: Optional[dict] = None,
              kv_peak_utilization: float = 0.0,
              kv_mean_utilization: float = 0.0,
              extra: Optional[dict] = None) -> dict:
    """Fold a replay's outcomes into the deterministic report dict."""
    sheds: Dict[str, int] = {}
    untyped = 0
    per_class: Dict[str, Dict[str, int]] = {}
    latencies: List[float] = []
    ttfts: List[float] = []
    itls: List[float] = []
    completed = 0
    tokens_out = 0
    for o in outcomes:
        cls = per_class.setdefault(o.slo, {"good": 0, "bad": 0})
        if o.ok:
            completed += 1
            tokens_out += o.tokens
            cls["good"] += 1
            if o.latency_s is not None:
                latencies.append(o.latency_s)
            if o.ttft_s is not None:
                ttfts.append(o.ttft_s)
            if o.itl_s is not None:
                # weight the mean interval by its token count so a long
                # generation influences the percentile like the stream of
                # per-token observations the live histogram records
                itls.extend([o.itl_s] * max(1, o.tokens))
        else:
            cause = o.cause or "internal"
            sheds[cause] = sheds.get(cause, 0) + 1
            if cause not in TYPED_CAUSES:
                untyped += 1
            if cause not in SLO_EXCLUDED_CAUSES:
                cls["bad"] += 1
    latencies.sort()
    ttfts.sort()
    itls.sort()

    slo: Dict[str, dict] = {}
    burn_max = 0.0
    for name in sorted(per_class):
        c = per_class[name]
        total = c["good"] + c["bad"]
        target = DEFAULT_TARGETS.get(name, DEFAULT_TARGET)
        bad_frac = (c["bad"] / total) if total else 0.0
        burn = bad_frac / max(1e-9, 1.0 - target)
        burn_max = max(burn_max, burn)
        slo[name] = {"good": c["good"], "bad": c["bad"],
                     "target": target, "burn": burn}

    n = len(outcomes)
    report = {
        "schema": REPORT_SCHEMA,
        "mode": mode,
        "workload_fingerprint": workload_fp,
        "requests": n,
        "completed": completed,
        "tokens_out": tokens_out,
        "goodput_frac": (completed / n) if n else 0.0,
        "shed": {k: sheds[k] for k in sorted(sheds)},
        "untyped_errors": untyped,
        "latency_ms": {"p50": _pctile(latencies, 0.50) * 1e3,
                       "p99": _pctile(latencies, 0.99) * 1e3},
        "ttft_ms": {"p50": _pctile(ttfts, 0.50) * 1e3,
                    "p99": _pctile(ttfts, 0.99) * 1e3},
        "inter_token_ms": {"p50": _pctile(itls, 0.50) * 1e3,
                           "p99": _pctile(itls, 0.99) * 1e3},
        "slo": slo,
        "burn_max": burn_max,
        "kv": {"peak_utilization": kv_peak_utilization,
               "mean_utilization": kv_mean_utilization},
    }
    if knobs is not None:
        report["knobs"] = knobs
    if extra:
        report.update(extra)
    report["score"] = score(report)
    return _round(report)


def score(report: dict) -> float:
    """Higher-is-better scalar over a report (see module docstring)."""
    goodput = float(report.get("goodput_frac", 0.0))
    burn = min(float(report.get("burn_max", 0.0)), 4.0) / 4.0
    ttft_p99 = min(float(report["ttft_ms"]["p99"]) / 1e3, 2.0) / 2.0
    itl_p99 = min(float(report["inter_token_ms"]["p99"]) / 1e3, 0.5) / 0.5
    kv_peak = float(report.get("kv", {}).get("peak_utilization", 0.0))
    pages = min(len(report.get("alerts") or []), 4) / 4.0
    return (goodput - 0.25 * burn - 0.05 * ttft_p99 - 0.05 * itl_p99
            - 0.02 * kv_peak - 0.05 * pages)


def report_json(report: dict) -> str:
    """Canonical serialization — the byte-identity surface for determinism."""
    return json.dumps(_round(report), sort_keys=True, indent=1)
