"""Deterministic workload synthesis — seed-replayable open-loop traces.

A :class:`WorkloadSpec` describes offered load the way a capacity planner
would: a base request rate shaped by a time-compressed **diurnal** sinusoid,
**Markov-modulated bursts** (an on/off process multiplies the rate while
"on"), **heavy-tailed lengths** (lognormal prompts, Pareto output lengths —
the shapes measured in production LLM traces), and weighted tenant/model
mixes with per-tenant SLO classes. :func:`generate_trace` expands a spec
into a :class:`Trace` of absolute-time :class:`Event`\\ s via Lewis
thinning of a non-homogeneous Poisson process.

Everything is deterministic by construction:

- all randomness flows from ``random.Random(seed)`` (Mersenne Twister —
  identical across processes and platforms, unlike builtin ``hash()``
  which varies with ``PYTHONHASHSEED``);
- weighted choices iterate mixes in sorted key order, never dict order;
- event times are integer **microseconds**, so no float-formatting drift;
- each event carries its own sha256-derived seed so prompt *content* can
  be regenerated anywhere without replaying the arrival process;
- ``Trace.to_bytes()`` is a fixed line format, so byte-equality is the
  determinism test, and the **workload fingerprint** is a sha256 over the
  canonical spec JSON plus those bytes.

Stdlib only — no jax, no numpy — so traces can be synthesized and
fingerprinted in processes that never load an accelerator runtime.
"""

from __future__ import annotations

import hashlib
import json
import math
import random
from typing import Dict, Iterator, List, NamedTuple, Optional, Tuple

_TRACE_SCHEMA = "sim-trace-v1"

# Deadlines per SLO class (ms); mirrors fleet.tenants.DEFAULT_SLO_CLASSES
# without importing fleet/ (sim sits above it in the layering, but the
# workload layer stays stdlib-light so the virtual path never loads jax).
CLASS_DEADLINES_MS: Dict[str, Optional[float]] = {
    "gold": 1000.0, "standard": 5000.0, "batch": None}


class LengthDist(NamedTuple):
    """A token-length distribution: ``lognormal``, ``pareto`` or ``fixed``.

    ``p1``/``p2`` are (median, sigma) for lognormal, (scale, shape alpha)
    for Pareto, (value, unused) for fixed. Samples are clipped to
    ``[1, max_len]`` — heavy tails are the point, but the serving stack
    has a hard capacity, and clipping keeps the tail mass *at* the cap
    instead of silently discarding it.
    """

    kind: str
    p1: float
    p2: float
    max_len: int

    def sample(self, rng: random.Random) -> int:
        if self.kind == "lognormal":
            v = rng.lognormvariate(math.log(max(self.p1, 1e-9)), self.p2)
        elif self.kind == "pareto":
            v = self.p1 * rng.paretovariate(self.p2)
        elif self.kind == "fixed":
            v = self.p1
        else:
            raise ValueError(f"unknown length distribution kind {self.kind!r}")
        return max(1, min(int(self.max_len), int(round(v))))

    def to_dict(self) -> dict:
        return {"kind": self.kind, "p1": self.p1, "p2": self.p2,
                "max_len": self.max_len}

    @classmethod
    def from_dict(cls, d: dict) -> "LengthDist":
        return cls(str(d["kind"]), float(d["p1"]), float(d["p2"]),
                   int(d["max_len"]))


class Event(NamedTuple):
    """One scheduled request. ``t_us`` is microseconds from trace start.

    ``prefix_len``/``prefix_seed`` describe a shared-prefix head: the
    first ``prefix_len`` prompt tokens are regenerated from
    ``prefix_seed`` (a per-tenant-pool seed shared by every event drawn
    from the same pool entry), the rest from the event's own ``seed``.
    ``prefix_len == 0`` (the default) is the legacy fully-private prompt
    and serializes to the legacy 9-field line, so traces without prefix
    pools stay byte-identical.
    """

    t_us: int
    seq: int
    tenant: str
    slo: str
    model: str
    kind: str           # "predict" | "generate"
    prompt_len: int
    max_new_tokens: int
    seed: int           # per-event content seed (sha256-derived)
    prefix_len: int = 0
    prefix_seed: int = 0

    @property
    def t_s(self) -> float:
        return self.t_us / 1e6

    def deadline_s(self) -> Optional[float]:
        """Absolute deadline in trace time, or None for the batch class."""
        ms = CLASS_DEADLINES_MS.get(self.slo)
        return None if ms is None else self.t_s + ms / 1e3

    def to_line(self) -> str:
        base = (f"{self.t_us} {self.seq} {self.tenant} {self.slo} "
                f"{self.model} {self.kind} {self.prompt_len} "
                f"{self.max_new_tokens} {self.seed}")
        if self.prefix_len > 0:
            return f"{base} {self.prefix_len} {self.prefix_seed}"
        return base

    @classmethod
    def from_line(cls, line: str) -> "Event":
        p = line.split()
        if len(p) not in (9, 11):
            raise ValueError(f"bad trace line: {line!r}")
        return cls(int(p[0]), int(p[1]), p[2], p[3], p[4], p[5],
                   int(p[6]), int(p[7]), int(p[8]),
                   int(p[9]) if len(p) == 11 else 0,
                   int(p[10]) if len(p) == 11 else 0)


class WorkloadSpec:
    """Declarative description of an offered-load scenario.

    ``tenants`` maps tenant name -> ``{"weight", "slo"}`` and ``models``
    maps model name -> ``{"weight", "generate_frac"}``; weights are
    relative. ``time_scale`` compresses wall time for *live* replay only —
    it is part of the spec (and fingerprint) because a compressed replay
    offers different instantaneous concurrency than a real-time one.

    ``days`` repeats the compressed diurnal curve: one "day" is
    ``duration_s`` long, the sinusoid repeats naturally (its default
    period IS the day), and each later day re-seeds the Markov burst
    process from a sha256-derived day seed — so a 3-day trace has three
    *different* burst patterns over the same diurnal shape, which is what
    makes multi-day autoscaler replays informative instead of three
    copies of day one. ``days=1`` (the default) is bit-identical to the
    legacy single-day expansion and is omitted from the canonical spec,
    so every existing fingerprint (and every tuned config keyed by one)
    survives unchanged.

    ``prefix_reuse``/``prefix_len``/``prefix_pool`` model shared-prefix
    traffic (system prompts, few-shot templates): each tenant owns
    ``prefix_pool`` prefix entries whose lengths are drawn from the
    ``prefix_len`` distribution and whose content seeds derive from the
    spec fingerprint — stable across processes, like per-event seeds.
    With probability ``prefix_reuse`` an event's prompt starts with one
    of its tenant's pool prefixes (uniformly chosen), which is exactly
    the traffic shape the serving prefix cache exists for. The default
    ``prefix_reuse=0`` draws nothing from the RNG stream and is omitted
    from the canonical spec, so legacy fingerprints AND trace bytes stay
    identical.
    """

    def __init__(self, *, seed: int = 0, duration_s: float = 60.0,
                 days: int = 1,
                 base_rate_rps: float = 4.0,
                 diurnal_amplitude: float = 0.5,
                 diurnal_period_s: Optional[float] = None,
                 diurnal_phase: float = -0.25,
                 burst_rate_mult: float = 1.0,
                 burst_mean_on_s: float = 0.0,
                 burst_mean_off_s: float = 0.0,
                 prompt_len: LengthDist = LengthDist("lognormal", 8.0, 0.7, 48),
                 output_len: LengthDist = LengthDist("pareto", 2.0, 1.6, 16),
                 prefix_len: Optional[LengthDist] = None,
                 prefix_reuse: float = 0.0,
                 prefix_pool: int = 4,
                 vocab: int = 50,
                 time_scale: float = 1.0,
                 tenants: Optional[Dict[str, dict]] = None,
                 models: Optional[Dict[str, dict]] = None):
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.days = int(days)
        if self.days < 1:
            raise ValueError("need days >= 1")
        self.base_rate_rps = float(base_rate_rps)
        self.diurnal_amplitude = min(1.0, max(0.0, float(diurnal_amplitude)))
        self.diurnal_period_s = float(
            duration_s if diurnal_period_s is None else diurnal_period_s)
        self.diurnal_phase = float(diurnal_phase)
        self.burst_rate_mult = max(1.0, float(burst_rate_mult))
        self.burst_mean_on_s = max(0.0, float(burst_mean_on_s))
        self.burst_mean_off_s = max(0.0, float(burst_mean_off_s))
        self.prompt_len = prompt_len
        self.output_len = output_len
        self.prefix_len = prefix_len
        self.prefix_reuse = min(1.0, max(0.0, float(prefix_reuse)))
        self.prefix_pool = max(1, int(prefix_pool))
        if self.prefix_reuse > 0.0 and self.prefix_len is None:
            raise ValueError("prefix_reuse > 0 needs a prefix_len "
                             "distribution")
        self.vocab = int(vocab)
        self.time_scale = float(time_scale)
        self.tenants = tenants or {"default": {"weight": 1.0,
                                               "slo": "standard"}}
        self.models = models or {"default": {"weight": 1.0,
                                             "generate_frac": 0.0}}

    @property
    def total_duration_s(self) -> float:
        """Full trace span: ``days`` diurnal days of ``duration_s`` each."""
        return self.duration_s * self.days

    def to_dict(self) -> dict:
        d = self._to_dict()
        if self.days != 1:
            # a single-day spec's canonical form predates `days`: omitting
            # the default keeps every legacy fingerprint byte-stable
            d["days"] = self.days
        if self.prefix_reuse > 0.0:
            # same discipline as `days`: prefix pools predate nothing a
            # legacy fingerprint covers, so the OFF default stays absent
            d["prefix_len"] = self.prefix_len.to_dict()
            d["prefix_reuse"] = self.prefix_reuse
            d["prefix_pool"] = self.prefix_pool
        return d

    def _to_dict(self) -> dict:
        return {
            "schema": _TRACE_SCHEMA,
            "seed": self.seed,
            "duration_s": self.duration_s,
            "base_rate_rps": self.base_rate_rps,
            "diurnal_amplitude": self.diurnal_amplitude,
            "diurnal_period_s": self.diurnal_period_s,
            "diurnal_phase": self.diurnal_phase,
            "burst_rate_mult": self.burst_rate_mult,
            "burst_mean_on_s": self.burst_mean_on_s,
            "burst_mean_off_s": self.burst_mean_off_s,
            "prompt_len": self.prompt_len.to_dict(),
            "output_len": self.output_len.to_dict(),
            "vocab": self.vocab,
            "time_scale": self.time_scale,
            "tenants": self.tenants,
            "models": self.models,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "WorkloadSpec":
        d = dict(d)
        d.pop("schema", None)
        d["prompt_len"] = LengthDist.from_dict(d["prompt_len"])
        d["output_len"] = LengthDist.from_dict(d["output_len"])
        if d.get("prefix_len") is not None:
            d["prefix_len"] = LengthDist.from_dict(d["prefix_len"])
        return cls(**d)

    def canonical(self) -> bytes:
        """Canonical JSON — sorted keys, no whitespace drift."""
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":")).encode("utf-8")

    def fingerprint(self) -> str:
        """Stable spec-level fingerprint (the *workload fingerprint*).

        Hash of the canonical spec alone: the arrival process is a pure
        function of the spec, so hashing the expanded events again would
        add cost without adding information — and it lets callers key
        tuned configs before paying for trace expansion. ``Trace.
        fingerprint()`` additionally covers the event bytes as a
        self-check that expansion really was deterministic.
        """
        return hashlib.sha256(self.canonical()).hexdigest()[:16]

    def rate_at(self, t_s: float) -> float:
        """Un-modulated (no burst) offered rate at trace time ``t_s``."""
        theta = 2.0 * math.pi * (t_s / self.diurnal_period_s
                                 + self.diurnal_phase)
        r = self.base_rate_rps * (1.0
                                  + self.diurnal_amplitude * math.sin(theta))
        return max(r, 0.02 * self.base_rate_rps)


class Trace:
    """An expanded event stream plus the spec that produced it."""

    def __init__(self, spec: WorkloadSpec, events: List[Event]):
        self.spec = spec
        self.events = events

    def __len__(self) -> int:
        return len(self.events)

    def __iter__(self) -> Iterator[Event]:
        return iter(self.events)

    def fingerprint(self) -> str:
        """Workload fingerprint (spec-derived; see WorkloadSpec)."""
        return self.spec.fingerprint()

    def content_hash(self) -> str:
        """sha256 over spec canonical + event bytes — expansion self-check."""
        h = hashlib.sha256(self.spec.canonical())
        h.update(b"\n")
        h.update(self._event_bytes())
        return h.hexdigest()[:16]

    def _event_bytes(self) -> bytes:
        return "\n".join(e.to_line() for e in self.events).encode("utf-8")

    def to_bytes(self) -> bytes:
        """Fixed serialization; byte-equality == trace equality."""
        header = (f"# {_TRACE_SCHEMA} fp={self.fingerprint()} "
                  f"events={len(self.events)}\n").encode("utf-8")
        spec_line = b"# spec " + self.spec.canonical() + b"\n"
        return header + spec_line + self._event_bytes() + b"\n"

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            f.write(self.to_bytes())

    @classmethod
    def load(cls, path: str) -> "Trace":
        with open(path, "rb") as f:
            lines = f.read().decode("utf-8").splitlines()
        spec = None
        events: List[Event] = []
        for line in lines:
            if line.startswith("# spec "):
                spec = WorkloadSpec.from_dict(json.loads(line[len("# spec "):]))
            elif line.startswith("#") or not line.strip():
                continue
            else:
                events.append(Event.from_line(line))
        if spec is None:
            raise ValueError(f"no spec header in trace file {path}")
        return cls(spec, events)

    def slice(self, n_events: int) -> "Trace":
        """Prefix of the trace — the tuner's successive-halving rungs.

        The slice keeps the parent spec (and therefore the parent
        fingerprint): rung evaluations are *of* the parent workload,
        just truncated.
        """
        return Trace(self.spec, self.events[:max(0, int(n_events))])


def _event_seed(spec_fp: str, seq: int) -> int:
    """Per-event content seed, stable across processes."""
    digest = hashlib.sha256(f"{spec_fp}:{seq}".encode("utf-8")).digest()
    return int.from_bytes(digest[:4], "big")


def _weighted_pick(rng: random.Random, mix: Dict[str, dict]) -> str:
    """Weighted choice iterating keys in sorted order (never dict order)."""
    names = sorted(mix)
    total = sum(float(mix[n].get("weight", 1.0)) for n in names)
    x = rng.random() * total
    acc = 0.0
    for n in names:
        acc += float(mix[n].get("weight", 1.0))
        if x < acc:
            return n
    return names[-1]


def _burst_windows(rng: random.Random,
                   spec: WorkloadSpec) -> List[Tuple[float, float]]:
    """Markov on/off burst intervals: exponential off then on holding times."""
    if (spec.burst_rate_mult <= 1.0 or spec.burst_mean_on_s <= 0.0
            or spec.burst_mean_off_s <= 0.0):
        return []
    windows: List[Tuple[float, float]] = []
    t = 0.0
    while t < spec.duration_s:
        t += rng.expovariate(1.0 / spec.burst_mean_off_s)
        if t >= spec.duration_s:
            break
        end = t + rng.expovariate(1.0 / spec.burst_mean_on_s)
        windows.append((t, min(end, spec.duration_s)))
        t = end
    return windows


def _day_seed(seed: int, day: int) -> int:
    """Per-day burst-process seed, stable across processes (sha256, not
    ``hash()`` — the same discipline as per-event content seeds)."""
    digest = hashlib.sha256(f"{seed}:day:{day}".encode("utf-8")).digest()
    return int.from_bytes(digest[:4], "big")


def _prefix_entry(spec: WorkloadSpec, spec_fp: str, tenant: str,
                  pid: int) -> Tuple[int, int]:
    """One tenant-pool prefix entry: ``(length, content seed)``.

    Both are pure functions of ``(spec fingerprint, tenant, pool id)`` —
    the length is a single draw from the ``prefix_len`` distribution
    under a dedicated sha256-derived RNG, so every event adopting this
    entry sees the same prefix regardless of arrival order or process.
    """
    digest = hashlib.sha256(
        f"{spec_fp}:prefix:{tenant}:{pid}".encode("utf-8")).digest()
    seed = int.from_bytes(digest[:4], "big")
    length = spec.prefix_len.sample(random.Random(seed))
    return length, seed


def generate_trace(spec: WorkloadSpec) -> Trace:
    """Expand a spec into a trace via Lewis thinning.

    Candidate arrivals come from a homogeneous Poisson process at the
    rate envelope ``base * (1 + amplitude) * burst_mult``; each candidate
    survives with probability ``rate(t) / envelope``. The thinned stream
    is exactly the non-homogeneous process with intensity ``rate(t)``,
    and — because every candidate consumes the same number of RNG draws —
    the stream is bit-stable under any spec change that only *lowers*
    local intensity.

    Multi-day specs draw day 0's burst windows from the main RNG stream
    (so ``days=1`` stays bit-identical to the legacy expansion) and each
    later day's from its own sha256-derived seed, offset into that day.
    """
    rng = random.Random(spec.seed)
    windows = list(_burst_windows(rng, spec))
    for day in range(1, spec.days):
        day_rng = random.Random(_day_seed(spec.seed, day))
        offset = day * spec.duration_s
        windows.extend((a + offset, b + offset)
                       for a, b in _burst_windows(day_rng, spec))
    spec_fp = spec.fingerprint()

    def modulated_rate(t: float) -> float:
        r = spec.rate_at(t)
        for (a, b) in windows:
            if a <= t < b:
                return r * spec.burst_rate_mult
        return r

    envelope = (spec.base_rate_rps * (1.0 + spec.diurnal_amplitude)
                * spec.burst_rate_mult)
    events: List[Event] = []
    t = 0.0
    seq = 0
    while True:
        t += rng.expovariate(envelope)
        if t >= spec.total_duration_s:
            break
        keep = rng.random() * envelope <= modulated_rate(t)
        # Draw the per-event attributes unconditionally so thinning
        # decisions don't shift the RNG stream of later events.
        tenant = _weighted_pick(rng, spec.tenants)
        model = _weighted_pick(rng, spec.models)
        gen_frac = float(spec.models[model].get("generate_frac", 0.0))
        kind = "generate" if rng.random() < gen_frac else "predict"
        plen = spec.prompt_len.sample(rng)
        ntok = spec.output_len.sample(rng) if kind == "generate" else 0
        pfx_len, pfx_seed = 0, 0
        if spec.prefix_reuse > 0.0:
            # two extra draws per candidate, but ONLY when the feature is
            # on: the legacy (prefix_reuse=0) stream stays byte-identical
            reuse = rng.random() < spec.prefix_reuse
            pid = rng.randrange(spec.prefix_pool)
            if reuse:
                pool_len, pfx_seed = _prefix_entry(spec, spec_fp, tenant, pid)
                # at least one private token stays: a fully-shared prompt
                # has nothing for the server to prefill
                pfx_len = min(pool_len, plen - 1)
                if pfx_len <= 0:
                    pfx_len, pfx_seed = 0, 0
        if not keep:
            continue
        events.append(Event(
            t_us=int(round(t * 1e6)), seq=seq, tenant=tenant,
            slo=str(spec.tenants[tenant].get("slo", "standard")),
            model=model, kind=kind, prompt_len=plen, max_new_tokens=ntok,
            seed=_event_seed(spec_fp, seq),
            prefix_len=pfx_len, prefix_seed=pfx_seed))
        seq += 1
    return Trace(spec, events)


def prompt_tokens(event: Event, vocab: int) -> List[int]:
    """Regenerate the event's prompt content from its embedded seed(s).

    A shared-prefix event regenerates its head from the tenant-pool
    ``prefix_seed`` — every adopter of the same pool entry produces the
    SAME head tokens, so replaying the trace against a real server
    exercises the prefix cache exactly as the spec intended."""
    v = max(2, int(vocab))
    out: List[int] = []
    if event.prefix_len > 0:
        rp = random.Random(event.prefix_seed)
        out = [rp.randrange(v) for _ in range(event.prefix_len)]
    r = random.Random(event.seed)
    out.extend(r.randrange(v)
               for _ in range(event.prompt_len - event.prefix_len))
    return out


def smoke_spec(seed: int = 0, duration_s: float = 60.0,
               base_rate_rps: float = 6.0,
               time_scale: float = 0.1) -> WorkloadSpec:
    """The CI smoke workload: one compressed diurnal day over a 2-model,
    3-tenant fleet with a bursty gold tier and heavy-tailed lengths."""
    return WorkloadSpec(
        seed=seed, duration_s=duration_s, base_rate_rps=base_rate_rps,
        diurnal_amplitude=0.6, diurnal_period_s=duration_s,
        diurnal_phase=-0.25,
        burst_rate_mult=2.5, burst_mean_on_s=4.0, burst_mean_off_s=12.0,
        prompt_len=LengthDist("lognormal", 6.0, 0.7, 12),
        output_len=LengthDist("pareto", 2.0, 1.6, 4),
        vocab=50, time_scale=time_scale,
        tenants={
            "acme": {"weight": 0.5, "slo": "gold"},
            "globex": {"weight": 0.35, "slo": "standard"},
            "free": {"weight": 0.15, "slo": "batch"},
        },
        models={
            "alpha": {"weight": 0.6, "generate_frac": 0.0},
            "beta": {"weight": 0.4, "generate_frac": 0.5},
        })
