"""Open-loop trace replay — virtual (deterministic) and live modes.

Both replayers consume the same :class:`~.workload.Trace` and emit the
same :func:`~.score.summarize` report, but they answer different
questions:

- :class:`VirtualReplayer` is a **discrete-event model** of the serving
  stack (batch-window predict queue, slot/KV-block generate path with
  chunked prefill, LRU weight paging). It is bit-deterministic — same
  trace + same knobs + same cost model ⇒ byte-identical report — and
  runs thousands of events per millisecond, which is what makes
  successive-halving autotuning (``sim/tune.py``) affordable. Its cost
  model is calibrated roughly to the CPU smoke stack; it predicts knob
  *orderings*, not absolute latencies.
- :class:`LiveReplayer` drives a real in-process
  :class:`~..fleet.registry.FleetRegistry` (via :class:`FleetTarget`)
  at trace-scheduled wall times, **never closed-loop**: an event fires
  at ``t0 + time_scale * event.t_s`` whether or not earlier requests
  have finished, so queue growth under overload is visible exactly as
  production would see it. Fates come back as the same typed causes the
  HTTP tier maps (``serve/errors.py``), so one scorer serves both modes.

The knob dictionary mirrors the real constructor surfaces: the
``engine`` group splats into :class:`~..serve.engine.ServeEngine`, the
``gen`` group into :class:`~..serve.continuous.ContinuousBatcher`
(``decode_chunks``/``idle_chunks`` fold into a ``PrefillScheduler``),
``fleet``/``cluster`` groups carry pager and router knobs. The same
nested dict is what the tuner persists into the AOT store.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, NamedTuple, Optional, Tuple

from .score import Outcome, summarize
from .workload import Event, Trace, prompt_tokens

# Hand-picked defaults — the same values the serve/fleet constructors
# default to today. Candidate 0 of every tuner search.
DEFAULT_KNOBS: Dict[str, dict] = {
    "engine": {"batch_buckets": [1, 2, 4, 8, 16, 32],
               "queue_limit": 256, "max_wait_ms": 2.0},
    "gen": {"slots": 4, "capacity": 256, "block_size": 16, "kv_blocks": None,
            "prefill_chunk": 64, "queue_limit": 64,
            "decode_chunks": 1, "idle_chunks": 4,
            "prefix_cache": True, "prefix_cache_blocks": None},
    # resident_models: how many models fit the pager's HBM budget at once
    # (None = all of them — paging never evicts)
    "fleet": {"resident_models": None},
    # router-tier knobs, modeled by the virtual hop stage when the cost
    # model carries nonzero hop costs (hop_rtt_s / hop_loss_p) — with the
    # default all-zero hop costs the stage is skipped and reports stay
    # byte-identical to the pre-hop model (documented in sim/README.md)
    "cluster": {"hedge_ms": 30.0, "retry_budget_per_s": 2.0},
    # predictive-autoscaler knobs: the confidence floor gates pre-spawn
    # (AutoscalePolicy.from_config), season/horizon shape the forecaster
    # (BurnForecaster.from_config) — one recorded winner configures both
    "autoscale": {"forecast_season_s": 86400.0, "forecast_horizon_s": 60.0,
                  "forecast_confidence": 0.5},
}


def merge_knobs(base: dict, override: Optional[dict]) -> dict:
    """Two-level merge: override group/key wins, base fills the rest."""
    out = {g: dict(v) for g, v in base.items()}
    for g, v in (override or {}).items():
        out.setdefault(g, {}).update(v or {})
    return out


def flatten_knobs(knobs: dict) -> Dict[str, object]:
    """``{"gen": {"slots": 4}}`` -> ``{"gen.slots": 4}`` (tuner space keys)."""
    flat: Dict[str, object] = {}
    for g in sorted(knobs):
        for k in sorted(knobs[g]):
            flat[f"{g}.{k}"] = knobs[g][k]
    return flat


def set_flat(knobs: dict, dotted: str, value) -> None:
    group, key = dotted.split(".", 1)
    knobs.setdefault(group, {})[key] = value


class CostModel(NamedTuple):
    """Virtual-time costs. Defaults are CPU-smoke-ish (PERF.md): they rank
    configs the way the live CPU stack does; recalibrate on real TPUs —
    :meth:`from_profile` does exactly that from a measured
    :class:`~deeplearning4j_tpu.obs.costmodel.CostProfile`."""

    predict_row_s: float = 2e-4       # per padded batch row
    predict_dispatch_s: float = 1.5e-3  # per device dispatch
    prefill_tok_s: float = 4000.0     # prefill throughput, tokens/s
    chunk_dispatch_s: float = 1e-3    # per prefill chunk overhead
    decode_base_s: float = 4e-3       # decode step, empty batch
    decode_slot_s: float = 1e-3       # decode step marginal cost per slot
    page_in_s: float = 0.5            # weight page-in (host -> device + warm)
    # router-hop costs (zero = in-process deployment, hop stage skipped —
    # reports stay byte-identical to the hop-free model). Nonzero values
    # activate the ``cluster.*`` knobs: hedge_ms bounds lost-attempt
    # recovery, retry_budget_per_s bounds un-hedged retries.
    hop_rtt_s: float = 0.0            # router <-> replica round trip
    hop_loss_p: float = 0.0           # P(first attempt lost in transit)

    @classmethod
    def from_profile(cls, profile,
                     base: Optional["CostModel"] = None) -> "CostModel":
        """Calibrate from a measured cost profile: each field the profiler
        actually observed replaces the hand-set value; everything the run
        never exercised keeps ``base`` (default: the class defaults) — so
        calibration degrades per-field, never whole-model."""
        cm = base if base is not None else cls()
        repl = {}
        for field in cm._fields:
            v = profile.cost(field)
            if v is not None:
                repl[field] = v
        return cm._replace(**repl) if repl else cm


def _blocks_needed(tokens: int, block_size: int) -> int:
    return -(-max(1, tokens) // max(1, block_size))


def _unit_hash(seq: int) -> float:
    """Deterministic per-event uniform in [0, 1) — splitmix64 of the
    event's trace sequence number (NEVER Python's salted ``hash``), so
    the same trace loses the same attempts in every process."""
    z = (int(seq) + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return ((z ^ (z >> 31)) & 0xFFFFFFFFFFFFFFFF) / 2.0 ** 64


def _shed(ev: Event, cause: str) -> Outcome:
    return Outcome(False, cause, ev.slo, ev.model, ev.kind,
                   None, None, None, 0)


class VirtualReplayer:
    """Deterministic discrete-event replay of a trace against one knob set.

    Per model, the predict path is a single batching server (window =
    first-arrival + ``max_wait_ms``, dispatch pads to the smallest bucket
    that fits) and the generate path is a slot + KV-block pool with
    chunked prefill contending against running decodes — the same shape,
    sheds, and knob tradeoffs as the live engine, in virtual time.
    """

    def __init__(self, trace: Trace, knobs: Optional[dict] = None,
                 cost_model: Optional[CostModel] = None):
        self.trace = trace
        self.knobs = merge_knobs(DEFAULT_KNOBS, knobs)
        self.cm = cost_model if cost_model is not None else CostModel()

    # ---------------------------------------------------------------- paging
    def _residency_adjusted(self) -> List[Tuple[float, Event]]:
        """Effective arrival times after LRU weight paging: a request to a
        cold model waits for the (serial) pager before it reaches a queue."""
        budget = self.knobs["fleet"].get("resident_models")
        models = {e.model for e in self.trace}
        if not budget or int(budget) >= len(models):
            return [(e.t_s, e) for e in self.trace]
        budget = int(budget)
        resident: "OrderedDict[str, float]" = OrderedDict()
        pager_free = 0.0
        out: List[Tuple[float, Event]] = []
        for ev in self.trace:
            t = ev.t_s
            if ev.model in resident:
                resident.move_to_end(ev.model)
                out.append((max(t, resident[ev.model]), ev))
                continue
            ready = max(t, pager_free) + self.cm.page_in_s
            pager_free = ready
            if len(resident) >= budget:
                resident.popitem(last=False)
            resident[ev.model] = ready
            out.append((ready, ev))
        return out

    # ------------------------------------------------------------ router hop
    def _router_adjusted(
            self, arrivals: List[Tuple[float, Event]],
            out: List[Outcome]) -> List[Tuple[float, Event]]:
        """The ``cluster.*`` knob model: each request pays half a hop RTT
        to reach its replica; a transit-lost first attempt (seeded by the
        event's sequence number) is recovered by the hedge after
        ``hedge_ms`` when one is armed, else by a retry one more RTT
        later IF the retry token bucket (refilled at
        ``retry_budget_per_s`` of virtual time, capped at one second of
        budget) still holds a token — a drained bucket sheds the request
        as ``upstream_unreachable``, exactly the router's storm-control
        tradeoff. Skipped entirely (and byte-identical) while both hop
        cost-model fields are zero."""
        cm = self.cm
        if cm.hop_rtt_s <= 0.0 and cm.hop_loss_p <= 0.0:
            return arrivals
        cl = self.knobs.get("cluster") or {}
        hedge_s = max(0.0, float(cl.get("hedge_ms") or 0.0)) / 1e3
        rate = max(0.0, float(cl.get("retry_budget_per_s") or 0.0))
        cap = max(1.0, rate)
        tokens, last_t = cap, 0.0
        kept: List[Tuple[float, Event]] = []
        for eff, ev in arrivals:
            delay = cm.hop_rtt_s / 2.0
            if cm.hop_loss_p > 0.0 and _unit_hash(ev.seq) < cm.hop_loss_p:
                if hedge_s > 0.0:
                    # the hedged duplicate (no budget spend) lands after
                    # the hedge timer plus its own half-hop
                    delay += hedge_s
                else:
                    tokens = min(cap, tokens + max(0.0, eff - last_t) * rate)
                    last_t = eff
                    if tokens >= 1.0:
                        tokens -= 1.0
                        delay += cm.hop_rtt_s  # full extra round trip
                    else:
                        out.append(_shed(ev, "upstream_unreachable"))
                        continue
            kept.append((eff + delay, ev))
        return kept

    # --------------------------------------------------------------- predict
    def _sim_predict(self, items: List[Tuple[float, Event]],
                     out: List[Outcome]) -> None:
        eng = self.knobs["engine"]
        cm = self.cm
        buckets = sorted(int(b) for b in eng["batch_buckets"])
        maxb = buckets[-1]
        qlimit = int(eng["queue_limit"])
        wait = float(eng["max_wait_ms"]) / 1e3
        pending: deque = deque()
        t_free = 0.0
        i, n = 0, len(items)
        while i < n or pending:
            if not pending:
                pending.append(items[i])
                i += 1
                continue
            first_t = pending[0][0]
            ready = max(t_free,
                        first_t if len(pending) >= maxb else first_t + wait)
            if i < n and items[i][0] <= ready:
                eff, ev = items[i]
                i += 1
                if len(pending) >= qlimit:
                    out.append(_shed(ev, "queue_full"))
                else:
                    pending.append((eff, ev))
                continue
            take = min(len(pending), maxb)
            batch = [pending.popleft() for _ in range(take)]
            live = []
            for eff, ev in batch:
                dl = ev.deadline_s()
                if dl is not None and ready > dl:
                    out.append(_shed(ev, "deadline"))
                else:
                    live.append(ev)
            if not live:
                continue
            bucket = next(b for b in buckets if b >= len(live))
            t_free = ready + cm.predict_dispatch_s + bucket * cm.predict_row_s
            for ev in live:
                dl = ev.deadline_s()
                if dl is not None and t_free > dl:
                    out.append(_shed(ev, "deadline"))
                else:
                    out.append(Outcome(True, None, ev.slo, ev.model,
                                       "predict", t_free - ev.t_s,
                                       None, None, 0))

    # -------------------------------------------------------------- generate
    def _sim_generate(self, items: List[Tuple[float, Event]],
                      out: List[Outcome],
                      util: List[float]) -> None:
        g = self.knobs["gen"]
        cm = self.cm
        slots = max(1, int(g["slots"]))
        capacity = max(1, int(g["capacity"]))
        bs = max(1, int(g["block_size"]))
        per_seq = _blocks_needed(capacity, bs)
        total_blocks = (int(g["kv_blocks"]) if g.get("kv_blocks")
                        else slots * per_seq + 1)
        chunk = max(1, int(g["prefill_chunk"] or capacity))
        dc = max(1, int(g.get("decode_chunks", 1)))
        qlimit = max(1, int(g["queue_limit"]))
        # prefix-cache model: whole blocks of a previously-seen shared
        # prefix (same tenant-pool seed) skip BOTH the prefill-chunk work
        # and the block charge. Insertion is modeled at admission (the
        # live cache inserts at prefill completion — a fidelity gap only
        # for near-simultaneous first arrivals of one pool entry), cached
        # blocks occupy pool capacity, and pressure reclaims LRU entries
        # before anything waits — the live reclaim-before-shed rule. With
        # no prefixed events in the trace (every legacy workload), the
        # cache never populates and reports stay byte-identical.
        px_on = bool(g.get("prefix_cache", True))
        px_cap = g.get("prefix_cache_blocks")
        px_cap = int(px_cap) if px_cap else None
        px: "OrderedDict[int, int]" = OrderedDict()  # seed -> whole blocks
        px_blocks = 0
        active: list = []          # heap of (done_t, seq, blocks)
        blocks_used = 0
        waiting: deque = deque()

        def release(upto: float) -> None:
            nonlocal blocks_used
            while active and active[0][0] <= upto:
                _, _, b = heapq.heappop(active)
                blocks_used -= b

        def px_insert(ev: Event) -> None:
            nonlocal px_blocks
            if not px_on or ev.prefix_len < bs:
                return
            nfull = ev.prefix_len // bs
            cur = px.get(ev.prefix_seed, 0)
            if nfull > cur:
                px[ev.prefix_seed] = nfull
                px_blocks += nfull - cur
            px.move_to_end(ev.prefix_seed)
            while px_cap is not None and px_blocks > px_cap and len(px) > 1:
                _, v = px.popitem(last=False)
                px_blocks -= v

        def try_start(now: float) -> None:
            nonlocal blocks_used, px_blocks
            while waiting:
                eff, ev = waiting[0]
                shared = 0
                if px_on and ev.prefix_len > 0 and ev.prefix_seed in px:
                    shared = min(px[ev.prefix_seed],
                                 (ev.prompt_len - 1) // bs)
                need = _blocks_needed(ev.prompt_len + ev.max_new_tokens,
                                      bs) - shared
                if len(active) >= slots:
                    return
                # capacity pressure reclaims idle cached runs before the
                # head request waits (the allocator's reclaimer hook)
                while blocks_used + px_blocks + need > total_blocks and px:
                    _, v = px.popitem(last=False)
                    px_blocks -= v
                    if shared:  # the adopted run may be what was evicted
                        shared = min(px.get(ev.prefix_seed, 0),
                                     (ev.prompt_len - 1) // bs)
                        need = _blocks_needed(
                            ev.prompt_len + ev.max_new_tokens, bs) - shared
                if blocks_used + px_blocks + need > total_blocks:
                    return
                waiting.popleft()
                start = max(now, eff)
                dl = ev.deadline_s()
                if dl is not None and start > dl:
                    out.append(_shed(ev, "deadline"))
                    continue
                if px_on and ev.prefix_len > 0:
                    px_insert(ev)
                nact = len(active) + 1
                decode_tick = cm.decode_base_s + cm.decode_slot_s * nact
                ptoks = ev.prompt_len - shared * bs
                nchunks = _blocks_needed(ptoks, chunk)
                prefill = (ptoks / cm.prefill_tok_s
                           + nchunks * cm.chunk_dispatch_s)
                if len(active) > 0:
                    # chunked prefill yields to running decodes every
                    # `decode_chunks` chunks — small chunks prefill slower
                    prefill += (nchunks / dc) * decode_tick
                # decode ticks stretch while *other* requests prefill:
                # large chunks stall decodes longer, queue pressure makes
                # overlap more likely
                pressure = min(1.0, len(waiting) / slots)
                stall = pressure * (chunk / cm.prefill_tok_s
                                    + cm.chunk_dispatch_s) / dc
                itl = decode_tick + stall
                ttft = (start - ev.t_s) + prefill + itl
                done = start + prefill + ev.max_new_tokens * itl
                heapq.heappush(active, (done, ev.seq, need))
                blocks_used += need
                util.append((blocks_used + px_blocks) / total_blocks)
                if dl is not None and done > dl:
                    out.append(Outcome(False, "deadline", ev.slo, ev.model,
                                       "generate", None, ttft, itl, 0))
                else:
                    out.append(Outcome(True, None, ev.slo, ev.model,
                                       "generate", done - ev.t_s, ttft, itl,
                                       ev.max_new_tokens))

        for eff, ev in items:
            release(eff)
            try_start(eff)  # completions freed slots: drain the queue first
            need = _blocks_needed(ev.prompt_len + ev.max_new_tokens, bs)
            if (ev.prompt_len + ev.max_new_tokens > capacity
                    or need > total_blocks):
                out.append(_shed(ev, "over_capacity"))
                continue
            if len(waiting) >= qlimit:
                out.append(_shed(ev, "queue_full"))
                continue
            waiting.append((eff, ev))
            try_start(eff)
        while waiting:
            if active:
                done_t = active[0][0]
                release(done_t)
                try_start(done_t)
                continue
            # idle engine, non-empty queue: start from the queued arrival
            try_start(waiting[0][0])
            if not active and waiting:
                # nothing startable even when idle — impossible given the
                # admission capacity check, but never spin
                _, ev = waiting.popleft()
                out.append(_shed(ev, "over_capacity"))

    # ------------------------------------------------------------------- run
    def run(self) -> dict:
        outcomes: List[Outcome] = []
        arrivals = self._router_adjusted(self._residency_adjusted(),
                                         outcomes)
        by_mk: Dict[Tuple[str, str], List[Tuple[float, Event]]] = {}
        for eff, ev in arrivals:
            by_mk.setdefault((ev.model, ev.kind), []).append((eff, ev))
        util: List[float] = []
        for key in sorted(by_mk):
            items = sorted(by_mk[key], key=lambda p: (p[0], p[1].seq))
            if key[1] == "generate":
                self._sim_generate(items, outcomes, util)
            else:
                self._sim_predict(items, outcomes)
        return summarize(
            self.trace.fingerprint(), outcomes, mode="virtual",
            knobs=self.knobs,
            kv_peak_utilization=max(util) if util else 0.0,
            kv_mean_utilization=(sum(util) / len(util)) if util else 0.0)


class FleetTarget:
    """Adapter: trace events -> in-process :class:`FleetRegistry` calls.

    Predict prompts are padded/cropped to the model's fixed input length;
    generate prompts keep their traced lengths (prompt buckets pad).
    Every failure maps to its typed ``ServeError.cause`` — an untyped
    exception is recorded as ``internal`` and fails the smoke's
    typed-errors-only gate.
    """

    def __init__(self, registry, *, input_len: int = 16, vocab: int = 50,
                 autoscaler=None, alerts=None):
        self.registry = registry
        self.input_len = int(input_len)
        self.vocab = int(vocab)
        #: Optional AutoscaleController-shaped hook: anything with a
        #: ``replica_stats() -> {min, max, final}`` surface. When set, the
        #: replay's report records how the fleet size moved — a single
        #: registry doesn't scale itself, but the hook lets one replayer
        #: code path serve both fixed and elastic targets.
        self.autoscaler = autoscaler
        #: Optional AlertEngine-shaped hook (``firings() -> [dict]``);
        #: when set, the replay's report records which alerts fired and
        #: when, so the tuner can penalize configs that page humans.
        self.alerts = alerts

    def replica_stats(self) -> Optional[Dict[str, int]]:
        """Fleet-size envelope from the attached autoscaler, if any."""
        if self.autoscaler is None:
            return None
        return self.autoscaler.replica_stats()

    def alert_firings(self) -> Optional[List[dict]]:
        """Alert firing log from the attached engine, if any."""
        if self.alerts is None:
            return None
        return self.alerts.firings()

    def kv_utilization(self) -> Tuple[float, float]:
        """(peak, mean) of serve_kv_block_utilization over resident models."""
        try:
            snap = self.registry.metrics.snapshot()
        except Exception:  # scrape is best-effort  # jaxlint: disable=broad-except
            return (0.0, 0.0)
        fam = snap.get("serve_kv_block_utilization") or {}
        vals = [float(s.get("value", 0.0)) for s in fam.get("series", [])]
        if not vals:
            return (0.0, 0.0)
        return (max(vals), sum(vals) / len(vals))

    def _outcome(self, ev: Event, t0: float, err: Optional[BaseException],
                 ttft: Optional[float] = None,
                 itl: Optional[float] = None,
                 tokens: int = 0) -> Outcome:
        from ..serve.errors import ServeError

        if err is None:
            return Outcome(True, None, ev.slo, ev.model, ev.kind,
                           time.monotonic() - t0, ttft, itl, tokens)
        cause = err.cause if isinstance(err, ServeError) else "internal"
        return Outcome(False, cause, ev.slo, ev.model, ev.kind,
                       None, None, None, 0)

    def predict(self, ev: Event) -> Outcome:
        import numpy as np

        toks = prompt_tokens(ev, self.vocab)[:self.input_len]
        x = np.zeros((self.input_len,), dtype=np.int64)
        x[:len(toks)] = toks
        t0 = time.monotonic()
        try:
            self.registry.predict(ev.model, x, tenant=ev.tenant)
        except Exception as e:  # mapped to a typed cause below  # jaxlint: disable=broad-except
            return self._outcome(ev, t0, e)
        return self._outcome(ev, t0, None)

    def generate(self, ev: Event) -> Outcome:
        import numpy as np

        prompt = np.asarray(prompt_tokens(ev, self.vocab), dtype=np.int32)
        t0 = time.monotonic()
        try:
            handle = self.registry.submit_generate(
                ev.model, prompt, ev.max_new_tokens, tenant=ev.tenant)
            ticks: List[float] = []
            for _ in handle.stream():
                ticks.append(time.monotonic())
            handle.wait()
        except Exception as e:  # mapped to a typed cause below  # jaxlint: disable=broad-except
            return self._outcome(ev, t0, e)
        ttft = (ticks[0] - t0) if ticks else None
        itl = ((ticks[-1] - ticks[0]) / (len(ticks) - 1)
               if len(ticks) > 1 else None)
        return self._outcome(ev, t0, None, ttft=ttft, itl=itl,
                             tokens=len(ticks))


class RouterTarget:
    """Adapter: trace events -> HTTP through a ClusterRouter front door.

    The cluster analogue of :class:`FleetTarget`: the same trace drives
    the whole serving stack — router admission, placement, failover,
    and (with an ``autoscaler=`` attached) an *elastic* fleet — instead
    of one in-process registry. Failures come back as the typed causes
    in the router's JSON error bodies, so the scorer's typed-errors-only
    gate applies unchanged; a transport failure to the router itself
    records ``upstream_unreachable``. KV utilization is a replica-local
    gauge the router does not aggregate, so this target reports none.
    """

    def __init__(self, host: str, port: int, *, input_len: int = 16,
                 vocab: int = 50, timeout_s: float = 30.0, autoscaler=None,
                 alerts=None):
        self.host = str(host)
        self.port = int(port)
        self.input_len = int(input_len)
        self.vocab = int(vocab)
        self.timeout_s = float(timeout_s)
        self.autoscaler = autoscaler
        self.alerts = alerts

    def replica_stats(self) -> Optional[Dict[str, int]]:
        """Fleet-size envelope from the attached autoscaler, if any."""
        if self.autoscaler is None:
            return None
        return self.autoscaler.replica_stats()

    def alert_firings(self) -> Optional[List[dict]]:
        """Alert firing log from the attached engine, if any."""
        if self.alerts is None:
            return None
        return self.alerts.firings()

    def _post(self, path: str, body: dict,
              tenant: str) -> Tuple[int, dict]:
        import http.client
        import json as _json

        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout_s)
        try:
            conn.request("POST", path,
                         body=_json.dumps(body).encode("utf-8"),
                         headers={"Content-Type": "application/json",
                                  "X-Tenant": tenant})
            resp = conn.getresponse()
            status, data = resp.status, resp.read()
        finally:
            conn.close()
        try:
            payload = _json.loads(data) if data else {}
        except ValueError:
            payload = {}
        return status, payload if isinstance(payload, dict) else {}

    @staticmethod
    def _cause(payload: dict) -> str:
        cause = payload.get("cause")
        # an error body without a typed cause is an untyped failure and
        # must score as one — that is the gate working, not a bug here
        return str(cause) if cause else "internal"

    def predict(self, ev: Event) -> Outcome:
        toks = prompt_tokens(ev, self.vocab)[:self.input_len]
        row = toks + [0] * (self.input_len - len(toks))
        t0 = time.monotonic()
        try:
            status, payload = self._post(
                f"/v1/models/{ev.model}/predict", {"ndarray": row},
                ev.tenant)
        except OSError:
            return _shed(ev, "upstream_unreachable")
        if status >= 400:
            return _shed(ev, self._cause(payload))
        return Outcome(True, None, ev.slo, ev.model, "predict",
                       time.monotonic() - t0, None, None, 0)

    def generate(self, ev: Event) -> Outcome:
        t0 = time.monotonic()
        try:
            status, payload = self._post(
                f"/v1/models/{ev.model}/generate?stream=false",
                {"prompt": prompt_tokens(ev, self.vocab),
                 "max_new_tokens": ev.max_new_tokens, "temperature": 0.0},
                ev.tenant)
        except OSError:
            return _shed(ev, "upstream_unreachable")
        if status >= 400:
            return _shed(ev, self._cause(payload))
        tokens = payload.get("tokens") or []
        return Outcome(True, None, ev.slo, ev.model, "generate",
                       time.monotonic() - t0, None, None, len(tokens))


class LiveReplayer:
    """Open-loop replay against a live target at trace-scheduled times.

    Each event fires at ``t0 + time_scale * event.t_s`` on its own thread
    regardless of whether earlier requests completed — the defining
    property of open-loop load (a closed-loop client self-throttles under
    overload and hides exactly the queueing the simulator exists to
    expose). Wall-clock results are *not* deterministic; determinism
    claims live in the virtual mode. ``time_scale`` defaults to the
    spec's own compression factor.
    """

    def __init__(self, trace: Trace, target, *,
                 time_scale: Optional[float] = None,
                 join_timeout_s: float = 60.0):
        self.trace = trace
        self.target = target
        self.time_scale = (trace.spec.time_scale if time_scale is None
                           else float(time_scale))
        self.join_timeout_s = float(join_timeout_s)
        self._lock = threading.Lock()
        self._outcomes: Dict[int, Outcome] = {}

    def _fire(self, idx: int, ev: Event) -> None:
        try:
            out = (self.target.generate(ev) if ev.kind == "generate"
                   else self.target.predict(ev))
        except Exception:  # a target bug scores as untyped, never hangs the run  # jaxlint: disable=broad-except
            out = _shed(ev, "internal")
        with self._lock:
            self._outcomes[idx] = out

    def run(self) -> dict:
        t0 = time.monotonic()
        threads: List[threading.Thread] = []
        for idx, ev in enumerate(self.trace):
            delay = t0 + ev.t_s * self.time_scale - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=self._fire, args=(idx, ev),
                                  daemon=True, name=f"sim-replay-{idx}")
            th.start()
            threads.append(th)
        deadline = time.monotonic() + self.join_timeout_s
        for th in threads:
            th.join(timeout=max(0.0, deadline - time.monotonic()))
        with self._lock:
            outcomes = [self._outcomes.get(i, _shed(ev, "client_gone"))
                        for i, ev in enumerate(self.trace)]
        peak, mean = (self.target.kv_utilization()
                      if hasattr(self.target, "kv_utilization")
                      else (0.0, 0.0))
        extra = {"time_scale": self.time_scale,
                 "wall_s": time.monotonic() - t0}
        stats = (self.target.replica_stats()
                 if hasattr(self.target, "replica_stats") else None)
        if stats is not None:
            # integer fleet-size envelope: how elastic capacity moved over
            # the replay (6-dp float rounding rules untouched)
            extra["replicas"] = {"min": int(stats["min"]),
                                 "max": int(stats["max"]),
                                 "final": int(stats["final"])}
        firings = (self.target.alert_firings()
                   if hasattr(self.target, "alert_firings") else None)
        if firings is not None:
            # which alerts would have paged during this replay (rule,
            # fired_at_s, resolved_at_s) — scored as an operator-toil
            # penalty so the tuner prefers configs that stay quiet
            extra["alerts"] = firings
        return summarize(
            self.trace.fingerprint(), outcomes, mode="live",
            kv_peak_utilization=peak, kv_mean_utilization=mean,
            extra=extra)
