"""Persistent AOT executable store (the Relay/TVM compile-once,
deploy-many lesson applied to the serving tier — PAPERS.md 1810.00952 /
1802.04799).

Every process start and registry hot-swap used to re-trace and re-compile
the serving executables from scratch. This package lowers them once
(``jax.jit(...).lower().compile()``), serializes the compiled artifacts
(``jax.experimental.serialize_executable``), and keys them by everything
that shaped the compile — so replicas boot from disk in seconds and a
publish warms the incoming generation *before* traffic flips onto it.

- :mod:`~.keys` — deterministic cache keys (jax/jaxlib, backend +
  topology, model-arch hash, bucket signature, donation spec)
- :mod:`~.store` — content-addressed on-disk store: atomic
  write-then-rename, index manifest, LRU GC, corrupt-entry quarantine
- :mod:`~.compile` — the serialize round-trip and :class:`AotFunction`,
  the store-backed wrapper ``serve/`` executes through; any store failure
  degrades to live tracing (counted on ``serve_aot_fallback_total``)

``python -m deeplearning4j_tpu.aot`` prebuilds, lists, verifies, and GCs
a store from the command line.
"""

from .compile import AotFunction, deserialize_compiled, serialize_compiled
from .keys import arch_fingerprint, cache_key, call_signature, \
    runtime_fingerprint
from .manifest import (load_coverage, load_manifest, missing_signatures,
                       record_coverage)
from .store import AotCorruptEntry, AotStore, AotStoreError, AotVersionError
from .tuned import get_tuned, put_tuned, tuned_group, tuned_key

__all__ = ["AotCorruptEntry", "AotFunction", "AotStore", "AotStoreError",
           "AotVersionError", "arch_fingerprint", "cache_key",
           "call_signature", "deserialize_compiled", "get_tuned",
           "load_coverage", "load_manifest", "missing_signatures",
           "put_tuned", "record_coverage", "runtime_fingerprint",
           "serialize_compiled", "tuned_group", "tuned_key"]
