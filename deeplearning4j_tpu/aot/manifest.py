"""Prebuild manifests and store coverage records.

The enumeration pass (``analysis/enumerate.py``) expands the committed
compile-surface budget against one concrete serving config into
``prebuild_manifest.json`` — the explicit list of (site, bucket-signature)
pairs a replica's boot will demand. This module owns the *deployment*
half of that contract:

- ``aot prebuild --from-surface`` compiles the manifest product into the
  store and stamps a **coverage record** — the concrete store keys it
  warmed, keyed on ``(runtime fingerprint, manifest hash)``. Cache keys
  fold in the jax/jaxlib pair, backend, topology and model architecture,
  so a record stamped on one runtime is simply *absent* on another — a
  build host with the wrong jaxlib cannot fake coverage.
- ``aot verify --manifest`` (and a strict boot) loads the record for the
  *current* runtime and lists every key the store no longer holds — the
  gate a build farm ships on and a strict replica refuses to pass
  readiness without.

Records live under ``<store-root>/coverage/`` — the store's entry scanner
only descends into two-character fan-out directories, so coverage records
are never mistaken for executables, never GC'd by the LRU, and ride along
when a store directory is rsync'd to a replica.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import List, Optional

from ..analysis.enumerate import manifest_hash
from .keys import runtime_fingerprint
from .store import AotStore

COVERAGE_SCHEMA = 1


def load_manifest(path: str) -> dict:
    """Read a prebuild manifest and verify its self-hash — a hand-edited
    manifest must fail loudly, not ship a partial surface."""
    with open(path, "r", encoding="utf-8") as fh:
        manifest = json.load(fh)
    if not isinstance(manifest, dict) or "sites" not in manifest:
        raise ValueError(f"{path}: not a prebuild manifest")
    want = manifest.get("hash")
    got = manifest_hash(manifest)
    if want != got:
        raise ValueError(f"{path}: manifest hash mismatch "
                         f"(stamped {want}, computed {got}) — regenerate "
                         "it with --enumerate-manifest")
    return manifest


def runtime_hash(runtime: Optional[dict] = None) -> str:
    """16-hex digest of one runtime fingerprint — the file-name-safe half
    of the coverage key."""
    rt = runtime if runtime is not None else runtime_fingerprint()
    canon = "|".join(f"{k}={rt[k]}" for k in sorted(rt))
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def coverage_path(store: AotStore, manifest: dict,
                  runtime: Optional[dict] = None) -> str:
    return os.path.join(
        store.root, "coverage",
        f"{runtime_hash(runtime)}-{manifest['hash']}.json")


def record_coverage(store: AotStore, manifest: dict, tags: dict, *,
                    runtime: Optional[dict] = None,
                    extra: Optional[dict] = None) -> str:
    """Stamp a coverage record after a prebuild: ``tags`` maps each AOT
    tag to the list of store keys warmed for it. Written atomically
    (write-then-rename, same discipline as store entries); returns the
    record path."""
    rt = runtime if runtime is not None else runtime_fingerprint()
    path = coverage_path(store, manifest, rt)
    record = {
        "schema": COVERAGE_SCHEMA,
        "manifest_hash": manifest["hash"],
        "runtime": rt,
        "runtime_hash": runtime_hash(rt),
        "created": time.time(),
        "tags": {tag: sorted(keys) for tag, keys in sorted(tags.items())},
        "total_keys": sum(len(keys) for keys in tags.values()),
        **(extra or {}),
    }
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(record, fh, indent=2)
        fh.write("\n")
    os.replace(tmp, path)
    return path


def load_coverage(store: AotStore, manifest: dict,
                  runtime: Optional[dict] = None) -> Optional[dict]:
    """The coverage record for (current runtime, this manifest), or None
    when no prebuild ever stamped one — which verify/boot treats exactly
    like an empty store: nothing is covered."""
    path = coverage_path(store, manifest, runtime)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            record = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(record, dict) \
            or record.get("schema") != COVERAGE_SCHEMA:
        return None
    return record


def missing_signatures(store: AotStore, manifest: dict,
                       runtime: Optional[dict] = None) -> List[str]:
    """Every manifest obligation the store cannot currently serve, as
    human/CI-readable ``tag key…`` lines. Three failure layers, checked
    in order: no coverage record for this (runtime, manifest) pair at
    all; a manifest site whose tag the record never warmed; a recorded
    key whose store entry has since been evicted, deleted, or
    quarantined."""
    record = load_coverage(store, manifest, runtime)
    if record is None:
        return [f"(no coverage record for runtime "
                f"{runtime_hash(runtime)} × manifest {manifest['hash']} "
                "— run `aot prebuild --from-surface` on this runtime)"]
    out: List[str] = []
    recorded = record.get("tags", {})
    on_disk = set(store.keys())
    for site in manifest.get("sites", []):
        tag = site["tag"]
        keys = recorded.get(tag)
        if not keys:
            out.append(f"{tag}: never prebuilt "
                       f"({site['cardinality']} signature(s) of "
                       f"{site['site']})")
            continue
        if len(keys) < site["cardinality"]:
            out.append(f"{tag}: prebuild warmed {len(keys)} of "
                       f"{site['cardinality']} signature(s)")
        for key in keys:
            if key not in on_disk:
                out.append(f"{tag}: store entry {key[:16]}… is gone")
    return out
