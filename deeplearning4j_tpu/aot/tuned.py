"""Tuned serving configs in the AOT store.

The autotuner (``sim/tune.py``) produces a winning knob dict per
workload; this module persists it *next to the compiled executables* so a
booting replica resolves both from the same place with the same key
discipline. The key is :func:`~.keys.cache_key` over

- ``tag="sim_tuned_config"`` (never collides with executable entries),
- the **runtime/topology fingerprint** (a config tuned on a CPU smoke
  box must be a clean miss on a v5e slice — the knobs encode hardware
  throughput assumptions exactly like a compiled program does), and
- the **workload fingerprint** (``sim/workload.py``) as the call
  signature — a config tuned for a bursty gold-heavy mix must not be
  served to a batch-heavy one.

Values are canonical JSON; corrupt or unparseable entries degrade to a
miss (the store quarantines integrity failures itself). Resolution is
counted on ``sim_tuned_config_hits_total`` / ``_misses_total`` so the
smoke can assert a fresh boot actually picked its tuned config up.
"""

from __future__ import annotations

import json
from typing import Optional

from .keys import cache_key
from .store import AotStoreError

_TAG = "sim_tuned_config"
_HITS = "sim_tuned_config_hits_total"
_MISSES = "sim_tuned_config_misses_total"
_HELP_HITS = "Tuned serving configs resolved from the AOT store at boot."
_HELP_MISSES = ("Tuned-config lookups that missed (no entry for this "
                "runtime+workload, or corrupt).")


def tuned_group(config: Optional[dict], group: str) -> dict:
    """One group of a resolved tuned config as a plain dict (empty on a
    miss or malformed entry) — the accessor every consumer shares (the
    fleet's ``engine``/``gen`` knob groups, the autoscale policy's
    ``autoscale`` group), so a corrupt or partial config degrades to
    defaults at each call site instead of raising."""
    if not isinstance(config, dict):
        return {}
    g = config.get(group)
    return dict(g) if isinstance(g, dict) else {}


def tuned_key(workload_fp: str, runtime: Optional[dict] = None) -> str:
    """Store key for one (runtime fingerprint, workload fingerprint) pair."""
    return cache_key(_TAG, "config", (str(workload_fp),), runtime=runtime)


def put_tuned(store, workload_fp: str, config: dict, *,
              runtime: Optional[dict] = None,
              extra_meta: Optional[dict] = None) -> Optional[str]:
    """Persist a knob dict; returns the key, or None if the store refused
    (store puts never raise — same degraded-mode contract as executables)."""
    key = tuned_key(workload_fp, runtime=runtime)
    blob = json.dumps(config, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    meta = {"kind": _TAG, "workload_fingerprint": str(workload_fp)}
    if extra_meta:
        meta.update(extra_meta)
    return key if store.put(key, blob, meta=meta) else None


def get_tuned(store, workload_fp: str, *, runtime: Optional[dict] = None,
              metrics=None) -> Optional[dict]:
    """Resolve a tuned knob dict, or None. Counts hit/miss on ``metrics``."""
    def _count(name: str, help_: str) -> None:
        if metrics is not None:
            metrics.counter(name, help=help_).inc()

    if store is None:
        _count(_MISSES, _HELP_MISSES)
        return None
    key = tuned_key(workload_fp, runtime=runtime)
    try:
        blob = store.get(key)
    except AotStoreError:
        blob = None  # corrupt entry: store already quarantined it
    if blob is None:
        _count(_MISSES, _HELP_MISSES)
        return None
    try:
        config = json.loads(blob.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        config = None
    if not isinstance(config, dict):
        _count(_MISSES, _HELP_MISSES)
        return None
    _count(_HITS, _HELP_HITS)
    return config
