"""AOT compile/serialize round-trip and the store-backed function wrapper.

The serving tier's contract is a *bounded executable set*; this module
makes that set *persistent*. :class:`AotFunction` wraps one jitted
function and resolves each call signature in order:

1. in-memory executable map (steady state: one dict lookup),
2. the persistent :class:`~.store.AotStore` — ``deserialize_and_load`` of
   an executable some earlier process compiled (cold-start/hot-swap win),
3. live ``jit(...).lower(...).compile()`` — the normal tracing path,
   whose result is serialized back into the store for the next boot.

The hard rule: **every failure in (2) degrades to (3)** — a corrupt
entry, a jax/jaxlib version skew, an unpicklable payload, a store I/O
error. Each is counted on ``serve_aot_fallback_total{cause=...}`` and
costs one trace, exactly what the process would have paid with no store
at all. ``serve_aot_hits_total`` / ``serve_aot_misses_total`` make the
cold-start win measurable.

``warm()`` ensures an executable *exists* (store hit or fresh compile)
without executing it — safe for donated operands and abstract
``jax.ShapeDtypeStruct`` arguments — which is what lets
``ModelRegistry.publish`` precompile an incoming generation against every
live bucket signature *before* flipping traffic onto it.
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Callable, Optional, Sequence, Tuple

from ..chaos.retry import RetryPolicy
from ..obs import profile as _prof
from ..obs import reqtrace as _rt
from .keys import arch_fingerprint, cache_key, call_signature, \
    runtime_fingerprint
from .store import AotCorruptEntry, AotStore, AotStoreError, AotVersionError

_BLOB_SCHEMA = 1


def serialize_compiled(compiled) -> bytes:
    """One compiled executable -> portable bytes (payload + arg pytrees +
    the jax/jaxlib pair that built it, double-checked at load time)."""
    import jax
    import jaxlib
    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = se.serialize(compiled)
    return pickle.dumps({"schema": _BLOB_SCHEMA, "jax": jax.__version__,
                         "jaxlib": jaxlib.__version__,
                         "exe": (payload, in_tree, out_tree)})


def deserialize_compiled(blob: bytes):
    """Bytes -> loaded executable. Raises :class:`AotVersionError` on a
    jax/jaxlib skew (the key scheme should already have missed; this is
    defense in depth), or whatever the unpickler raises on garbage — the
    caller maps every failure to a counted fallback."""
    import jax
    import jaxlib
    from jax.experimental import serialize_executable as se

    rec = pickle.loads(blob)
    if not isinstance(rec, dict) or rec.get("schema") != _BLOB_SCHEMA:
        raise AotStoreError("unrecognized AOT payload schema")
    if rec.get("jax") != jax.__version__ \
            or rec.get("jaxlib") != jaxlib.__version__:
        raise AotVersionError(
            f"executable built by jax {rec.get('jax')}/jaxlib "
            f"{rec.get('jaxlib')}, running {jax.__version__}/"
            f"{jaxlib.__version__}")
    return se.deserialize_and_load(*rec["exe"])


class AotFunction:
    """Store-backed drop-in for a jitted function.

    ``fn`` must expose ``.lower`` (a ``jax.jit`` result); anything else —
    e.g. a test's plain-python forward override — passes through untouched
    with the store disabled. ``donate_argnums`` only *keys* the cache (the
    aliasing contract is baked into ``fn`` itself); ``compile_counter`` is
    incremented on live traces only, so a warm boot reads as zero compile
    misses on the serving counters.

    ``strict=True`` inverts the degradation rule: a signature the store
    does not yield a loadable executable for (absent entry, corrupt blob,
    version skew, store I/O failure) raises a typed
    :class:`~..serve.errors.AotTraceError` instead of tracing — counted on
    ``serve_aot_strict_misses_total`` — so a replica deployed against a
    prebuilt store can never silently compile at request time.
    """

    def __init__(self, fn: Callable, *, tag: str,
                 store: Optional[AotStore] = None, metrics=None,
                 arch: str = "", component: str = "serve",
                 donate_argnums: Sequence[int] = (),
                 compile_counter=None, retry: Optional[RetryPolicy] = None,
                 strict: bool = False):
        self._fn = fn
        self.tag = tag
        self.store = store if hasattr(fn, "lower") else None
        self.arch = arch
        self.component = component
        self.donate = tuple(donate_argnums)
        self.strict = bool(strict) and self.store is not None
        if strict and self.store is None:
            raise ValueError(
                f"AotFunction(tag={tag!r}): strict mode requires a store "
                "and a lowerable (jitted) function")
        self._compile_counter = compile_counter
        # transient store-read failures (NFS hiccup, torn page cache) are
        # retried before falling back to a live trace; corrupt entries are
        # quarantined immediately — re-reading garbage can't help
        self._retry = retry if retry is not None else RetryPolicy(
            attempts=3, base_s=0.02, cap_s=0.5, metrics=metrics)
        self._runtime = None  # resolved lazily: jax may not be booted yet
        self._exes: dict = {}
        self._keys: dict = {}  # signature -> store key, for coverage records
        self._lock = threading.RLock()
        self._acquire_seconds = 0.0
        if metrics is not None and self.store is not None:
            labels = {"component": component}
            self._m_hits = metrics.counter(
                "serve_aot_hits_total", labels,
                help="executables loaded from the persistent AOT store")
            self._m_misses = metrics.counter(
                "serve_aot_misses_total", labels,
                help="AOT store lookups that found no entry")
            self._m_fallback = lambda cause: metrics.counter(
                "serve_aot_fallback_total", {**labels, "cause": cause},
                help="store entries abandoned for live tracing, by cause")
            self._m_strict = metrics.counter(
                "serve_aot_strict_misses_total", labels,
                help="signatures refused (typed 503) by strict AOT mode")
        else:
            from ..obs.metrics import MetricsRegistry

            null = MetricsRegistry(enabled=False)
            # same label shape as the live registry above: a disabled
            # series is still part of the family's one-labelset contract
            labels = {"component": component}
            self._m_hits = null.counter("serve_aot_hits_total", labels)
            self._m_misses = null.counter("serve_aot_misses_total", labels)
            self._m_fallback = lambda cause: null.counter(
                "serve_aot_fallback_total", {**labels, "cause": cause})
            self._m_strict = null.counter(
                "serve_aot_strict_misses_total", labels)

    # ------------------------------------------------------------------ calls
    def __call__(self, *args):
        if self.store is None:
            return self._fn(*args)
        sig = call_signature(args)
        with self._lock:
            exe = self._exes.get(sig)
        if exe is None:
            exe = self._acquire(sig, args)
        # continuous-profiler seam (obs/profile): one attribute load + a
        # None check when profiling is off — the hot decode tick's cost
        prof = _prof.ACTIVE
        if prof is None:
            return exe(*args)
        return prof.dispatch(self, sig, exe, args)

    def warm(self, *args) -> bool:
        """Ensure the executable for this signature exists (store hit or
        fresh compile) WITHOUT executing it. Accepts
        ``jax.ShapeDtypeStruct`` leaves. Returns True when AOT-capable."""
        if self.store is None:
            return False
        sig = call_signature(args)
        with self._lock:
            if sig not in self._exes:
                self._acquire(sig, args)
        return True

    @property
    def executables(self) -> dict:
        """Signature -> loaded executable (diagnostic)."""
        with self._lock:
            return dict(self._exes)

    def store_key(self, sig: Tuple[str, ...]) -> str:
        """The store key of one acquired signature ("" before acquire) —
        how the profiler stamps its (component, tag, sig, key) identity."""
        with self._lock:
            return self._keys.get(sig, "")

    def warmed_keys(self) -> list:
        """Sorted store keys of every executable this wrapper acquired —
        the concrete coverage a prebuild run stamps into the store's
        coverage record (``aot/manifest.py``)."""
        with self._lock:
            return sorted(set(self._keys.values()))

    @property
    def acquire_seconds(self) -> float:
        """Cumulative wall time spent loading/compiling executables — the
        cold-start cost this wrapper exists to amortize."""
        with self._lock:
            return self._acquire_seconds

    # ---------------------------------------------------------------- acquire
    def _key(self, sig: Tuple[str, ...]) -> str:
        if self._runtime is None:
            self._runtime = runtime_fingerprint()
        return cache_key(self.tag, self.arch, sig, donate=self.donate,
                         runtime=self._runtime)

    def _acquire(self, sig: Tuple[str, ...], args: Sequence[Any]):
        """Store -> live trace, under the lock (a concurrent publish warm
        and the dispatch thread must not double-compile one signature)."""
        with self._lock:
            exe = self._exes.get(sig)
            if exe is not None:
                return exe
            t0 = time.perf_counter()
            key = self._key(sig)
            with _rt.span("aot.acquire", tag=self.tag):
                exe = self._load(key)
                if exe is None:
                    if self.strict:
                        # the deployment contract: every signature was
                        # prebuilt from the static surface — a miss is a
                        # typed 503, NEVER a trace
                        from ..serve.errors import AotTraceError

                        self._m_strict.inc()
                        raise AotTraceError(
                            f"strict AOT: no store executable for "
                            f"tag={self.tag!r} key={key[:16]}… — prebuild "
                            "the store from the compile-surface manifest "
                            "(aot prebuild --from-surface)")
                    with _rt.span("aot.trace", tag=self.tag):
                        exe = self._fn.lower(*args).compile()
                    if self._compile_counter is not None:
                        self._compile_counter.inc()  # a real trace happened
                    self._save(key, exe)
            self._exes[sig] = exe
            self._keys[sig] = key
            self._acquire_seconds += time.perf_counter() - t0
            return exe

    def _load(self, key: str):
        try:
            blob = self._retry.call(
                lambda: self.store.get(key), op="aot.store_read",
                retry_on=(AotStoreError,), give_up=(AotCorruptEntry,))
        except AotCorruptEntry:
            self._m_fallback("corrupt").inc()
            return None
        except AotStoreError:
            self._m_fallback("store_read").inc()
            return None
        if blob is None:
            self._m_misses.inc()
            return None
        try:
            exe = deserialize_compiled(blob)
        except AotVersionError:
            self._m_fallback("version").inc()
            return None
        except Exception:  # any bad payload degrades to tracing, never crashes  # jaxlint: disable=broad-except
            self._m_fallback("deserialize").inc()
            return None
        self._m_hits.inc()
        return exe

    def _save(self, key: str, exe) -> None:
        try:
            blob = serialize_compiled(exe)
        except Exception:  # unserializable backend/executable: serve live  # jaxlint: disable=broad-except
            self._m_fallback("serialize").inc()
            return
        if not self.store.put(key, blob,
                              meta={"tag": self.tag, "arch": self.arch}):
            self._m_fallback("store_write").inc()


def arch_of(params, state=None) -> str:
    """Convenience re-export: the model-architecture key component."""
    return arch_fingerprint(params, state)
