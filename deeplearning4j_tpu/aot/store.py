"""Content-addressed on-disk store for serialized AOT executables.

Layout under one root directory::

    <root>/<key[:2]>/<key>.aotx     one entry per cache key (see keys.py)
    <root>/index.json               manifest: key -> {size, created, used, ...}
    <root>/quarantine/<key>.aotx    entries that failed integrity checks

Durability rules, in order of importance:

- **A reader can never observe a half-written entry.** Writes go to a
  temp file in the same directory, fsync, then ``os.replace`` — the POSIX
  atomic-publish idiom (and the same discipline orbax/TensorStore use for
  checkpoint commits).
- **Corruption degrades, never crashes.** Every entry carries a magic tag
  and a SHA-256 of its body; a failed check moves the file to
  ``quarantine/`` (atomically, so it cannot be re-read) and surfaces as a
  typed :class:`AotCorruptEntry` for the caller to count and trace around.
- **The entry files are ground truth.** ``index.json`` is a best-effort
  LRU/bookkeeping cache, rebuilt from the entry files whenever it is
  missing or unreadable — losing it loses recency ordering, not data.
- **Bounded size.** ``max_bytes`` triggers least-recently-used eviction at
  write time; concurrent readers of an evicted entry simply see a miss
  (the open-or-FileNotFound race is benign and tested).

The payload format is pickle (jax's own ``serialize_executable`` is
pickle-based); like JAX's persistent compilation cache, the store root is
trusted local state — point it at a directory with the same permissions
you would give the checkpoint directory.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..chaos import faults as _faults

_MAGIC = b"DL4JAOT1"
_SUFFIX = ".aotx"
_DIGEST_LEN = 32  # raw sha256


class AotStoreError(RuntimeError):
    """Base class for typed store failures."""


class AotCorruptEntry(AotStoreError):
    """An entry failed its integrity check and was quarantined."""


class AotVersionError(AotStoreError):
    """A deserialized payload was built by an incompatible jax/jaxlib."""


class AotStore:
    """Thread-safe persistent executable store.

    ``max_bytes`` bounds the sum of entry sizes (default 4 GiB — a few
    hundred serving executables); ``0``/``None`` disables eviction.
    """

    def __init__(self, root: str, max_bytes: Optional[int] = 4 << 30):
        self.root = os.path.abspath(os.fspath(root))
        self.max_bytes = int(max_bytes) if max_bytes else 0
        self._lock = threading.Lock()  # guards index read-modify-write
        os.makedirs(self.root, exist_ok=True)
        os.makedirs(self._qdir, exist_ok=True)

    # ------------------------------------------------------------------ paths
    @property
    def _index_path(self) -> str:
        return os.path.join(self.root, "index.json")

    @property
    def _qdir(self) -> str:
        return os.path.join(self.root, "quarantine")

    def _entry_path(self, key: str) -> str:
        self._check_key(key)
        return os.path.join(self.root, key[:2], key + _SUFFIX)

    @staticmethod
    def _check_key(key: str) -> None:
        if not key or not all(c in "0123456789abcdef" for c in key):
            raise ValueError(f"malformed store key {key!r}")

    # ---------------------------------------------------------------- entries
    def put(self, key: str, blob: bytes, meta: Optional[dict] = None) -> bool:
        """Atomically publish one entry; returns False (never raises) on
        I/O failure — a store write must not take the serving path down."""
        path = self._entry_path(key)
        body = _MAGIC + hashlib.sha256(blob).digest() + blob
        tmp = os.path.join(os.path.dirname(path),
                           f".{key}.{os.getpid()}.{threading.get_ident()}.tmp")
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(body)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # atomic publish: readers see all or nothing
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        now = time.time()
        with self._lock:
            index = self._load_index()
            index[key] = {"size": len(body), "created": now, "used": now,
                          **({"meta": meta} if meta else {})}
            self._evict_locked(index)
            self._write_index(index)
        return True

    def get(self, key: str) -> Optional[bytes]:
        """Verified payload bytes, or None on a miss. A failed integrity
        check quarantines the entry and raises :class:`AotCorruptEntry`."""
        path = self._entry_path(key)
        try:
            with open(path, "rb") as f:
                body = f.read()
            if _faults.ACTIVE is not None:
                # inside the try so an injected OSError surfaces exactly as
                # a real torn read would (typed AotStoreError); corrupt mode
                # mangles the body and exercises quarantine below
                body = _faults.ACTIVE.hit("aot.store_read", body)
        except FileNotFoundError:
            return None
        except OSError as e:
            raise AotStoreError(f"unreadable store entry {key}: {e}") from e
        head = len(_MAGIC) + _DIGEST_LEN
        if (len(body) < head or not body.startswith(_MAGIC)
                or hashlib.sha256(body[head:]).digest()
                != body[len(_MAGIC):head]):
            self._quarantine(key)
            raise AotCorruptEntry(
                f"store entry {key} failed its integrity check; quarantined")
        with self._lock:
            index = self._load_index()
            if key in index:
                index[key]["used"] = time.time()
                self._write_index(index)
        return body[head:]

    def _quarantine(self, key: str) -> None:
        """Move a bad entry aside atomically so it can never be re-read."""
        try:
            os.replace(self._entry_path(key),
                       os.path.join(self._qdir, key + _SUFFIX))
        except OSError:
            pass  # lost the race with another quarantiner/GC: already gone
        with self._lock:
            index = self._load_index()
            if index.pop(key, None) is not None:
                self._write_index(index)

    # ------------------------------------------------------------------ index
    def _load_index(self) -> Dict[str, dict]:
        """Best-effort manifest; a missing/corrupt file rebuilds from the
        entry files (ground truth) with recency reset to mtime."""
        try:
            with open(self._index_path) as f:
                loaded = json.load(f)
            if isinstance(loaded, dict):
                return {k: v for k, v in loaded.items()
                        if isinstance(v, dict) and "size" in v}
        except (OSError, ValueError):
            pass
        index: Dict[str, dict] = {}
        for key, path in self._scan_entries():
            try:
                st = os.stat(path)
            except OSError:
                continue
            index[key] = {"size": st.st_size, "created": st.st_mtime,
                          "used": st.st_mtime}
        return index

    def _write_index(self, index: Dict[str, dict]) -> None:
        tmp = self._index_path + f".{os.getpid()}.{threading.get_ident()}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(index, f)
            os.replace(tmp, self._index_path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass  # manifest is advisory; entries remain ground truth

    def _scan_entries(self) -> List[Tuple[str, str]]:
        out = []
        for sub in sorted(os.listdir(self.root)):
            d = os.path.join(self.root, sub)
            if len(sub) != 2 or not os.path.isdir(d):
                continue
            for name in sorted(os.listdir(d)):
                if name.endswith(_SUFFIX) and not name.startswith("."):
                    out.append((name[:-len(_SUFFIX)], os.path.join(d, name)))
        return out

    def rebuild_index(self) -> int:
        """Regenerate the manifest from the entry files; returns entry count."""
        with self._lock:
            try:
                os.remove(self._index_path)
            except OSError:
                pass
            index = self._load_index()
            self._write_index(index)
            return len(index)

    # --------------------------------------------------------------- eviction
    def _evict_locked(self, index: Dict[str, dict]) -> List[str]:
        if not self.max_bytes:
            return []
        total = sum(e["size"] for e in index.values())
        evicted = []
        for key in sorted(index, key=lambda k: index[k].get("used", 0.0)):
            if total <= self.max_bytes:
                break
            total -= index[key]["size"]
            del index[key]
            evicted.append(key)
            try:
                os.remove(self._entry_path(key))
            except OSError:
                pass  # already gone; a concurrent reader sees a clean miss
        return evicted

    def gc(self, max_bytes: Optional[int] = None) -> List[str]:
        """LRU-evict down to ``max_bytes`` (default: the store's bound);
        returns the evicted keys. Also drops index entries whose files have
        vanished."""
        with self._lock:
            index = self._load_index()
            on_disk = {k for k, _ in self._scan_entries()}
            for k in list(index):
                if k not in on_disk:
                    del index[k]
            bound = self.max_bytes
            if max_bytes is not None:
                self.max_bytes = int(max_bytes)
            try:
                evicted = self._evict_locked(index)
            finally:
                if max_bytes is not None:
                    self.max_bytes = bound
            self._write_index(index)
            return evicted

    # ------------------------------------------------------------ maintenance
    def verify(self) -> dict:
        """Integrity-check every entry; corrupt ones are quarantined.
        Returns {"ok": [...keys], "quarantined": [...keys]}."""
        ok, bad = [], []
        for key, _path in self._scan_entries():
            try:
                if self.get(key) is not None:
                    ok.append(key)
            except AotStoreError:
                bad.append(key)
        return {"ok": ok, "quarantined": bad}

    def keys(self) -> List[str]:
        return [k for k, _ in self._scan_entries()]

    def entries(self) -> Dict[str, dict]:
        """Manifest snapshot (key -> size/created/used/meta)."""
        with self._lock:
            return self._load_index()

    def stats(self) -> dict:
        with self._lock:
            index = self._load_index()
            try:
                quarantined = len([n for n in os.listdir(self._qdir)
                                   if n.endswith(_SUFFIX)])
            except OSError:
                quarantined = 0
            return {"root": self.root,
                    "entries": len(index),
                    "bytes": sum(e["size"] for e in index.values()),
                    "max_bytes": self.max_bytes,
                    "quarantined": quarantined}
