"""CLI for the persistent AOT executable store.

::

    python -m deeplearning4j_tpu.aot --store DIR list
    python -m deeplearning4j_tpu.aot --store DIR stats
    python -m deeplearning4j_tpu.aot --store DIR verify \
        [--manifest prebuild_manifest.json]
    python -m deeplearning4j_tpu.aot --store DIR gc [--max-bytes N]
    python -m deeplearning4j_tpu.aot --store DIR prebuild --model causallm \
        --model-kwargs '{"input_shape":[16],"num_layers":2,"d_model":32,
                         "num_heads":4,"vocab":50}' \
        --slots 4 --capacity 16 --batch-buckets 1,2,4,8
    python -m deeplearning4j_tpu.aot --store DIR prebuild \
        --from-surface prebuild_manifest.json

``prebuild`` boots the real serving stacks (``ServeEngine`` +
``ContinuousBatcher``) against the store with warm-at-construction on, so
the exact executables a replica will need are compiled and persisted
*now* — a new replica (or the next hot-swap) then boots from disk instead
of the tracer. Run it on the same jax/jaxlib + device topology the fleet
serves on; the cache keys make a mismatched prebuild a harmless miss.

``prebuild --from-surface`` is the build-farm mode: the manifest written
by ``python -m deeplearning4j_tpu.analysis --enumerate-manifest`` carries
the serving config, so the warm pass compiles exactly the statically
budgeted signature product (abstract leaves only — nothing executes,
donation-safe), cross-checks the warmed key count against every site's
enumerated cardinality, and stamps a coverage record keyed on (runtime
fingerprint, manifest hash). ``verify --manifest`` then gates shipping:
exit 1 listing every manifest obligation the store cannot serve.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .manifest import load_manifest, missing_signatures, record_coverage
from .store import AotStore


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GiB"


def _cmd_list(store: AotStore, _args) -> int:
    entries = store.entries()
    if not entries:
        print("(empty store)")
        return 0
    for key in sorted(entries, key=lambda k: -entries[k].get("used", 0.0)):
        e = entries[key]
        meta = e.get("meta") or {}
        print(f"{key[:16]}  {_fmt_bytes(e['size']):>10}  "
              f"tag={meta.get('tag', '?')}  arch={meta.get('arch', '?')}")
    print(f"-- {len(entries)} entries, "
          f"{_fmt_bytes(sum(e['size'] for e in entries.values()))}")
    return 0


def _cmd_stats(store: AotStore, _args) -> int:
    print(json.dumps(store.stats(), indent=1))
    return 0


def _cmd_verify(store: AotStore, args) -> int:
    out = store.verify()
    print(f"ok={len(out['ok'])} quarantined={len(out['quarantined'])}")
    for key in out["quarantined"]:
        print(f"quarantined: {key}")
    rc = 1 if out["quarantined"] else 0
    if getattr(args, "manifest", None):
        manifest = load_manifest(args.manifest)
        missing = missing_signatures(store, manifest)
        for line in missing:
            print(f"missing: {line}")
        if missing:
            print(f"manifest {manifest['hash']}: "
                  f"{len(missing)} obligation(s) unmet")
            rc = 1
        else:
            print(f"manifest {manifest['hash']}: fully covered "
                  f"({manifest.get('total_signatures')} signature(s))")
    return rc


def _cmd_gc(store: AotStore, args) -> int:
    evicted = store.gc(max_bytes=args.max_bytes)
    print(f"evicted {len(evicted)} entries")
    return 0


def _cmd_rebuild_index(store: AotStore, _args) -> int:
    print(f"indexed {store.rebuild_index()} entries")
    return 0


def _prebuild_from_surface(store: AotStore, args) -> int:
    """Build-farm mode: compile exactly the manifest's signature product
    into the store (abstract leaves — nothing executes) and stamp the
    coverage record strict replicas verify against at boot. Exits 1 on
    *surface drift*: a site whose warmed executable count differs from
    the enumerated cardinality, i.e. the static analysis and the booted
    code no longer agree on the compile surface."""
    import time

    import numpy as np

    from ..models import model_by_name
    from ..obs.metrics import MetricsRegistry
    from ..serve import ContinuousBatcher, ServeEngine
    from ..serve.continuous import gen_opts_from_config
    from ..serve.engine import ENGINE_KNOBS

    manifest = load_manifest(args.from_surface)
    config = manifest.get("config") or {}
    if not config.get("model"):
        print("prebuild --from-surface: manifest carries no serving "
              "config (regenerate with --enumerate-manifest "
              "--serve-config)", file=sys.stderr)
        return 1
    model = model_by_name(config["model"], seed=int(config.get("seed", 0)),
                          **(config.get("model_kwargs") or {})).init()
    metrics = MetricsRegistry()
    m_secs = metrics.gauge(
        "aot_prebuild_seconds",
        help="wall time of the last prebuild --from-surface warm pass")
    m_drift = metrics.counter(
        "aot_prebuild_drift_total",
        help="manifest sites whose warmed executable count diverged from "
             "the enumerated cardinality")

    engine_opts = {k: v for k, v in (config.get("engine") or {}).items()
                   if k in ENGINE_KNOBS}
    fns: dict = {}
    t0 = time.perf_counter()
    eng = ServeEngine(model, aot_store=store, metrics=metrics,
                      **engine_opts)
    try:
        eng.warm(np.dtype(config.get("dtype") or "int32"))
        fns.update(eng.aot_functions())
    finally:
        eng.shutdown()
    if not config.get("predict_only"):
        try:
            cb = ContinuousBatcher(model, aot_store=store, metrics=metrics,
                                   **gen_opts_from_config(config))
            fns.update(cb.aot_functions())
            cb.shutdown()  # warm-at-construction already persisted all
        except ValueError as e:
            # non-token model: no generation stack exists to prebuild
            print(f"prebuild: skipping generation stack ({e})",
                  file=sys.stderr)
    elapsed = time.perf_counter() - t0
    m_secs.set(elapsed)

    tags = {tag: fn.warmed_keys() for tag, fn in fns.items()}
    drift = []
    for site in manifest.get("sites", []):
        tag = site["tag"]
        got = len(tags.get(tag, []))
        metrics.counter("aot_prebuild_signatures_total", {"tag": tag},
                        help="signatures compiled+persisted by prebuild "
                             "--from-surface").inc(got)
        if got != site["cardinality"]:
            m_drift.inc()
            drift.append(
                f"{tag}: warmed {got} executable(s) but the manifest "
                f"enumerates {site['cardinality']} for {site['site']}")
    if drift:
        for line in drift:
            print(f"surface drift: {line}", file=sys.stderr)
        print("prebuild --from-surface: the booted stacks and the static "
              "enumeration disagree — re-run the compile-surface pass and "
              "regenerate the manifest", file=sys.stderr)
        return 1
    record = record_coverage(
        store, manifest, tags,
        extra={"model": config["model"], "prebuild_seconds": elapsed})
    print(json.dumps({
        "manifest": manifest["hash"],
        "model": config["model"],
        "sites": {tag: len(keys) for tag, keys in sorted(tags.items())},
        "total_signatures": sum(len(k) for k in tags.values()),
        "prebuild_seconds": elapsed,
        "coverage_record": record,
        "store": store.stats(),
    }, indent=1))
    return 0


def _cmd_prebuild(store: AotStore, args) -> int:
    if getattr(args, "from_surface", None):
        return _prebuild_from_surface(store, args)
    if not args.model:
        print("prebuild: --model (or --from-surface MANIFEST) is required",
              file=sys.stderr)
        return 2

    import numpy as np

    from ..models import model_by_name
    from ..obs.metrics import MetricsRegistry
    from ..serve import ContinuousBatcher, ServeEngine

    kwargs = json.loads(args.model_kwargs) if args.model_kwargs else {}
    model = model_by_name(args.model, seed=args.seed, **kwargs).init()
    metrics = MetricsRegistry()
    buckets = tuple(int(b) for b in args.batch_buckets.split(","))

    eng = ServeEngine(model, batch_buckets=buckets, aot_store=store,
                      metrics=metrics)
    try:
        eng.warm(np.dtype(args.dtype))
    finally:
        eng.shutdown()
    warmed = ["engine"]
    try:
        cb = ContinuousBatcher(model, slots=args.slots,
                               capacity=args.capacity,
                               block_size=args.block_size,
                               prefill_chunk=args.prefill_chunk,
                               aot_store=store, metrics=metrics)
        cb.shutdown()  # warm-at-construction already persisted everything
        warmed.append("generate")
    except ValueError as e:
        # non-token model: no generation stack to prebuild — predict only
        print(f"prebuild: skipping generation stack ({e})", file=sys.stderr)
    cold = {s["labels"].get("component"): s["value"]
            for s in metrics.snapshot().get(
                "serve_cold_start_seconds", {}).get("series", [])}
    print(json.dumps({"model": args.model, "warmed": warmed,
                      "cold_start_seconds": cold,
                      "store": store.stats()}, indent=1))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.aot",
        description="persistent AOT executable store maintenance")
    p.add_argument("--store", default=os.environ.get("DL4J_TPU_AOT_STORE"),
                   help="store root directory (or $DL4J_TPU_AOT_STORE)")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list", help="list entries, most recently used first")
    sub.add_parser("stats", help="entry/byte/quarantine totals as JSON")
    vf = sub.add_parser("verify",
                        help="integrity-check (and quarantine) entries")
    vf.add_argument("--manifest", default=None,
                    help="also gate on a prebuild manifest: exit 1 listing "
                         "every enumerated signature the store cannot serve")
    sub.add_parser("rebuild-index", help="regenerate the manifest from disk")
    gc = sub.add_parser("gc", help="LRU-evict down to the size bound")
    gc.add_argument("--max-bytes", type=int, default=None)
    pb = sub.add_parser("prebuild",
                        help="compile + persist a model's serving executables")
    pb.add_argument("--from-surface", default=None, metavar="MANIFEST",
                    help="compile the enumerated compile-surface manifest "
                         "(from analysis --enumerate-manifest) and stamp a "
                         "coverage record; all other prebuild flags are "
                         "taken from the manifest's embedded config")
    pb.add_argument("--model", default=None,
                    help="zoo model name (e.g. causallm)")
    pb.add_argument("--model-kwargs", default="",
                    help="JSON kwargs for the zoo constructor")
    pb.add_argument("--seed", type=int, default=0)
    pb.add_argument("--slots", type=int, default=4)
    pb.add_argument("--capacity", type=int, default=256)
    pb.add_argument("--block-size", type=int, default=16)
    pb.add_argument("--prefill-chunk", type=int, default=64)
    pb.add_argument("--batch-buckets", default="1,2,4,8,16,32")
    pb.add_argument("--dtype", default="int32",
                    help="predict-path input dtype to warm")
    args = p.parse_args(argv)
    if not args.store:
        p.error("--store (or $DL4J_TPU_AOT_STORE) is required")
    store = AotStore(args.store)
    return {"list": _cmd_list, "stats": _cmd_stats, "verify": _cmd_verify,
            "gc": _cmd_gc, "rebuild-index": _cmd_rebuild_index,
            "prebuild": _cmd_prebuild}[args.cmd](store, args)


if __name__ == "__main__":
    sys.exit(main())
