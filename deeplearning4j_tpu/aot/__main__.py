"""CLI for the persistent AOT executable store.

::

    python -m deeplearning4j_tpu.aot --store DIR list
    python -m deeplearning4j_tpu.aot --store DIR stats
    python -m deeplearning4j_tpu.aot --store DIR verify
    python -m deeplearning4j_tpu.aot --store DIR gc [--max-bytes N]
    python -m deeplearning4j_tpu.aot --store DIR prebuild --model causallm \
        --model-kwargs '{"input_shape":[16],"num_layers":2,"d_model":32,
                         "num_heads":4,"vocab":50}' \
        --slots 4 --capacity 16 --batch-buckets 1,2,4,8

``prebuild`` boots the real serving stacks (``ServeEngine`` +
``ContinuousBatcher``) against the store with warm-at-construction on, so
the exact executables a replica will need are compiled and persisted
*now* — a new replica (or the next hot-swap) then boots from disk instead
of the tracer. Run it on the same jax/jaxlib + device topology the fleet
serves on; the cache keys make a mismatched prebuild a harmless miss.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .store import AotStore


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GiB"


def _cmd_list(store: AotStore, _args) -> int:
    entries = store.entries()
    if not entries:
        print("(empty store)")
        return 0
    for key in sorted(entries, key=lambda k: -entries[k].get("used", 0.0)):
        e = entries[key]
        meta = e.get("meta") or {}
        print(f"{key[:16]}  {_fmt_bytes(e['size']):>10}  "
              f"tag={meta.get('tag', '?')}  arch={meta.get('arch', '?')}")
    print(f"-- {len(entries)} entries, "
          f"{_fmt_bytes(sum(e['size'] for e in entries.values()))}")
    return 0


def _cmd_stats(store: AotStore, _args) -> int:
    print(json.dumps(store.stats(), indent=1))
    return 0


def _cmd_verify(store: AotStore, _args) -> int:
    out = store.verify()
    print(f"ok={len(out['ok'])} quarantined={len(out['quarantined'])}")
    for key in out["quarantined"]:
        print(f"quarantined: {key}")
    return 1 if out["quarantined"] else 0


def _cmd_gc(store: AotStore, args) -> int:
    evicted = store.gc(max_bytes=args.max_bytes)
    print(f"evicted {len(evicted)} entries")
    return 0


def _cmd_rebuild_index(store: AotStore, _args) -> int:
    print(f"indexed {store.rebuild_index()} entries")
    return 0


def _cmd_prebuild(store: AotStore, args) -> int:
    import numpy as np

    from ..models import model_by_name
    from ..obs.metrics import MetricsRegistry
    from ..serve import ContinuousBatcher, ServeEngine

    kwargs = json.loads(args.model_kwargs) if args.model_kwargs else {}
    model = model_by_name(args.model, seed=args.seed, **kwargs).init()
    metrics = MetricsRegistry()
    buckets = tuple(int(b) for b in args.batch_buckets.split(","))

    eng = ServeEngine(model, batch_buckets=buckets, aot_store=store,
                      metrics=metrics)
    try:
        eng.warm(np.dtype(args.dtype))
    finally:
        eng.shutdown()
    warmed = ["engine"]
    try:
        cb = ContinuousBatcher(model, slots=args.slots,
                               capacity=args.capacity,
                               block_size=args.block_size,
                               prefill_chunk=args.prefill_chunk,
                               aot_store=store, metrics=metrics)
        cb.shutdown()  # warm-at-construction already persisted everything
        warmed.append("generate")
    except ValueError as e:
        # non-token model: no generation stack to prebuild — predict only
        print(f"prebuild: skipping generation stack ({e})", file=sys.stderr)
    cold = {s["labels"].get("component"): s["value"]
            for s in metrics.snapshot().get(
                "serve_cold_start_seconds", {}).get("series", [])}
    print(json.dumps({"model": args.model, "warmed": warmed,
                      "cold_start_seconds": cold,
                      "store": store.stats()}, indent=1))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.aot",
        description="persistent AOT executable store maintenance")
    p.add_argument("--store", default=os.environ.get("DL4J_TPU_AOT_STORE"),
                   help="store root directory (or $DL4J_TPU_AOT_STORE)")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list", help="list entries, most recently used first")
    sub.add_parser("stats", help="entry/byte/quarantine totals as JSON")
    sub.add_parser("verify", help="integrity-check (and quarantine) entries")
    sub.add_parser("rebuild-index", help="regenerate the manifest from disk")
    gc = sub.add_parser("gc", help="LRU-evict down to the size bound")
    gc.add_argument("--max-bytes", type=int, default=None)
    pb = sub.add_parser("prebuild",
                        help="compile + persist a model's serving executables")
    pb.add_argument("--model", required=True,
                    help="zoo model name (e.g. causallm)")
    pb.add_argument("--model-kwargs", default="",
                    help="JSON kwargs for the zoo constructor")
    pb.add_argument("--seed", type=int, default=0)
    pb.add_argument("--slots", type=int, default=4)
    pb.add_argument("--capacity", type=int, default=256)
    pb.add_argument("--block-size", type=int, default=16)
    pb.add_argument("--prefill-chunk", type=int, default=64)
    pb.add_argument("--batch-buckets", default="1,2,4,8,16,32")
    pb.add_argument("--dtype", default="int32",
                    help="predict-path input dtype to warm")
    args = p.parse_args(argv)
    if not args.store:
        p.error("--store (or $DL4J_TPU_AOT_STORE) is required")
    store = AotStore(args.store)
    return {"list": _cmd_list, "stats": _cmd_stats, "verify": _cmd_verify,
            "gc": _cmd_gc, "rebuild-index": _cmd_rebuild_index,
            "prebuild": _cmd_prebuild}[args.cmd](store, args)


if __name__ == "__main__":
    sys.exit(main())
