"""Deterministic cache keys for persisted AOT executables.

An XLA executable is only reusable when *everything* that shaped its
compilation matches: the jax/jaxlib pair that lowered it, the backend and
device topology it was compiled for, the model architecture (param pytree
structure + leaf shapes/dtypes — values never matter, shapes always do),
the exact call signature (the bucket the serving tier padded to), and the
donation spec (donated operands change the executable's aliasing contract).
Every component lands in one SHA-256 so a mismatch in ANY of them is a
clean cache *miss* — never a crash, never a silently-wrong executable.
Changing jaxlib, moving from CPU smoke to a v5e slice, or publishing a
model with different head counts each simply re-keys the store.

Key strings are pure functions of their inputs (no timestamps, no paths),
so two processes on identical machines — or the same replica across
restarts, which is the whole point — compute identical keys.
"""

from __future__ import annotations

import hashlib
from typing import Any, Iterable, Optional, Sequence, Tuple

_SCHEMA = "aot-v1"  # bump to invalidate every existing key on format change


def runtime_fingerprint() -> dict:
    """jax/jaxlib versions + backend + device topology, as a stable dict.

    Device *kind* and count are what XLA specializes for; device ordinals
    are not (the same executable serves any chip of the slice).
    """
    import jax
    import jaxlib

    devices = jax.devices()
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "device_kind": str(devices[0].device_kind),
        "device_count": len(devices),
        "process_count": jax.process_count(),
    }


def _leaf_sig(leaf: Any) -> str:
    """One pytree leaf as a stable string: arrays by shape/dtype, python
    scalars by type (their value is traced, not compiled in)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{tuple(shape)}:{str(dtype)}"
    if leaf is None:
        return "none"
    return f"py:{type(leaf).__name__}"


def arch_fingerprint(params: Any, state: Any = None) -> str:
    """Model-architecture hash: param (+state) treedef and leaf
    shapes/dtypes. Two checkpoints of the same architecture share it; a
    resized layer, changed dtype, or restructured tree does not."""
    import jax

    parts = []
    for tag, tree in (("params", params), ("state", state)):
        leaves, treedef = jax.tree.flatten(tree)
        parts.append(f"{tag}|{str(treedef)}|" +
                     ";".join(_leaf_sig(leaf) for leaf in leaves))
    h = hashlib.sha256("\n".join(parts).encode())
    return h.hexdigest()[:16]


def call_signature(args: Sequence[Any]) -> Tuple[str, ...]:
    """The bucket signature of one call: flattened leaf shapes/dtypes plus
    the argument treedef. This is what the serving tier's shape buckets
    vary over — and exactly what a compiled executable is specialized to.
    Hashable (a tuple of strings), so it doubles as the in-memory
    executable-map key."""
    import jax

    leaves, treedef = jax.tree.flatten(tuple(args))
    return tuple(_leaf_sig(leaf) for leaf in leaves) + (str(treedef),)


def cache_key(tag: str, arch: str, sig: Iterable[str],
              donate: Sequence[int] = (),
              runtime: Optional[dict] = None,
              extra: str = "") -> str:
    """One SHA-256 hex key from every compilation-shaping component.

    ``tag`` names the function (``gen_decode``, ``engine_forward``, ...);
    two different programs with identical signatures must not collide.
    ``runtime`` defaults to :func:`runtime_fingerprint` — injectable so
    tests can simulate a jaxlib upgrade and assert it misses cleanly.
    """
    rt = runtime if runtime is not None else runtime_fingerprint()
    material = "\x1f".join([
        _SCHEMA, tag, arch,
        "|".join(f"{k}={rt[k]}" for k in sorted(rt)),
        "|".join(sig),
        "donate=" + ",".join(str(int(i)) for i in donate),
        extra,
    ])
    return hashlib.sha256(material.encode()).hexdigest()
