"""ModelGuesser — heuristic model-file loader.

Reference parity: ``deeplearning4j-core/.../util/ModelGuesser.java`` — guess
whether a file is a DL4J zip, a Keras HDF5 file, or a bare config JSON, and
load it with the right importer.
"""

from __future__ import annotations

import json
import zipfile


def guess_model_format(path: str) -> str:
    """Return one of: 'native-zip', 'keras-h5', 'keras-v3', 'config-json',
    'unknown'."""
    try:
        if zipfile.is_zipfile(path):
            with zipfile.ZipFile(path) as zf:
                names = zf.namelist()
                if "configuration.json" in names:
                    return "native-zip"
                if "config.json" in names and "model.weights.h5" in names:
                    return "keras-v3"  # Keras 3 native .keras archive
            return "unknown"
        with open(path, "rb") as f:
            magic = f.read(8)
        if magic.startswith(b"\x89HDF\r\n\x1a\n"):
            return "keras-h5"
        with open(path, "r", encoding="utf-8", errors="strict") as f:
            json.load(f)
        return "config-json"
    except (OSError, ValueError, UnicodeDecodeError):
        return "unknown"


def load_model_guess(path: str):
    """Load a model file of any supported format (ModelGuesser.loadModelGuess)."""
    fmt = guess_model_format(path)
    if fmt == "native-zip":
        from ..train.serialization import load_model

        return load_model(path)[0]
    if fmt in ("keras-h5", "keras-v3"):
        from .keras_import import import_keras_model_and_weights

        return import_keras_model_and_weights(path)
    if fmt == "config-json":
        from ..nn.model import Graph, Sequential

        with open(path) as f:
            cfg = f.read()
        fmt_tag = json.loads(cfg).get("format", "")
        return Sequential.from_json(cfg) if "sequential" in fmt_tag else Graph.from_json(cfg)
    raise ValueError(f"Cannot determine model format of {path}")
