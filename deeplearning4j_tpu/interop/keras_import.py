"""Keras HDF5 model import — TPU-native equivalent of deeplearning4j-modelimport.

Reference parity (SURVEY.md §2.7):
- ``keras/KerasModelImport.java:41`` — entry points
  ``importKerasModelAndWeights`` (:50, → ComputationGraph) and
  ``importKerasSequentialModelAndWeights`` (:74, → MultiLayerNetwork).
- ``keras/Hdf5Archive.java:22-58`` — the reference reads HDF5 through the
  JavaCPP native hdf5 preset; here ``KerasHdf5Archive`` wraps ``h5py``.
- ``keras/config/Keras1LayerConfiguration.java`` / ``Keras2LayerConfiguration``
  — dual Keras 1.x / 2.x config dialects; ``_normalize_config`` folds the
  Keras 1 field names (``nb_filter``, ``border_mode``, ``subsample``,
  ``dim_ordering``, ``init``, ``output_dim``) into the Keras 2 vocabulary so a
  single converter per layer type serves both. Keras 3 legacy-H5 files (which
  ``keras.saving.save_model(m, "m.h5")`` still writes) parse through the same
  path.
- ``keras/layers/**`` — ~40 KerasLayer subclasses mapping Keras layers onto
  DL4J layer configs, including weight-layout transposes (``KerasLstm.java``
  gate reordering). Here the converter table ``_LAYER_CONVERTERS`` maps Keras
  class names onto our config dataclasses, and ``_convert_weights`` maps the
  stored weight arrays onto our param pytrees. Because this framework is
  natively NHWC with HWIO conv kernels and (in, 4H) fused ``[i,f,g,o]`` LSTM
  blocks — the same layouts Keras uses — most weights import with **zero
  copies or transposes**, unlike the reference's permute-heavy import. Only
  Keras-1 Theano-ordered kernels (OIHW) and GRU gate blocks need reordering.

Import failure semantics mirror the reference's
``InvalidKerasConfigurationException`` / ``UnsupportedKerasConfigurationException``.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..nn.api import Layer
from ..nn.layers import (LRN, ActivationLayer, AlphaDropout, BatchNorm,
                         Bidirectional,
                         Conv1D, Conv2D, Cropping1D, Cropping2D, Deconv2D,
                         Dense,
                         DepthwiseConv2D, DropoutLayer, EmbeddingSequence,
                         Flatten, GaussianDropout, GaussianNoise,
                         GlobalPooling, GRU, LastTimeStep, LSTM,
                         LayerNorm, MultiHeadAttention, PReLU, Reshape,
                         SeparableConv2D, SimpleRnn, Subsampling1D,
                         Subsampling2D, Upsampling1D, Upsampling2D,
                         ZeroPadding1D, ZeroPadding2D)
from ..nn.model import Graph, GraphBuilder, NetConfig, Sequential
from ..nn.vertices import ElementWise, GraphVertex, Merge


class InvalidKerasConfigurationException(ValueError):
    """Config is malformed / missing required fields (KerasModelImport parity)."""


class UnsupportedKerasConfigurationException(ValueError):
    """Config is valid Keras but has no equivalent here (yet)."""


# ---------------------------------------------------------------------------
# HDF5 archive
# ---------------------------------------------------------------------------


class KerasHdf5Archive:
    """Thin h5py wrapper — parity with ``keras/Hdf5Archive.java`` (which uses
    the native JavaCPP hdf5 preset; on TPU hosts h5py is the idiomatic path)."""

    def __init__(self, path: str):
        import h5py

        self.f = h5py.File(path, "r")

    def close(self):
        self.f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @staticmethod
    def _decode(v) -> str:
        return v.decode("utf-8") if isinstance(v, bytes) else str(v)

    def model_config(self) -> dict:
        if "model_config" not in self.f.attrs:
            raise InvalidKerasConfigurationException(
                "No 'model_config' attribute in HDF5 file (not a Keras model file?)")
        return json.loads(self._decode(self.f.attrs["model_config"]))

    def keras_version(self) -> str:
        for holder in (self.f, self.f.get("model_weights")):
            if holder is not None and "keras_version" in holder.attrs:
                return self._decode(holder.attrs["keras_version"])
        return "1.0.0"  # Keras 1 files predate the attribute

    def weight_group(self):
        return self.f["model_weights"] if "model_weights" in self.f else self.f

    def layer_weights(self, layer_name: str) -> List[np.ndarray]:
        """Weight arrays for one layer, in the order listed by ``weight_names``."""
        g = self.weight_group()
        if layer_name not in g:
            return []
        lg = g[layer_name]
        names = [self._decode(n) for n in lg.attrs.get("weight_names", [])]
        import h5py

        out = []
        for n in names:
            # names are like "dense_1/kernel:0" relative to the layer group;
            # some dialects repeat the layer name as a nested group and some
            # don't, so a missing *intermediate* component is skipped — but the
            # final node must be a dataset or the entry is malformed
            node = lg
            for part in n.split("/"):
                if part in node:
                    node = node[part]
            if not isinstance(node, h5py.Dataset):
                raise InvalidKerasConfigurationException(
                    f"weight_names entry '{n}' for layer '{layer_name}' does not "
                    f"resolve to a dataset (got {type(node).__name__})")
            out.append(np.asarray(node))
        return out


class KerasV3Archive:
    """Keras 3 native ``.keras`` archive (a zip of config.json +
    model.weights.h5) — the format ``model.save("m.keras")`` writes today.
    Presents the same surface as :class:`KerasHdf5Archive`, so every
    converter/golden-test path is shared; only the weight layout differs
    (``layers/<name>/.../vars/<i>`` instead of ``weight_names``-ordered
    datasets). Beyond the reference (which predates Keras 3)."""

    # composite layers store sub-weights in NAMED subgroups that h5py walks
    # alphabetically; the converters expect the legacy weight_names order
    _SUB_ORDER = {"query_dense": 0, "key_dense": 1, "value_dense": 2,
                  "output_dense": 3, "forward_layer": 0, "backward_layer": 1}

    def __init__(self, path: str):
        import zipfile

        self._zf = zipfile.ZipFile(path)
        try:
            self._cfg = json.loads(self._zf.read("config.json"))
            try:
                self._meta = json.loads(self._zf.read("metadata.json"))
            except KeyError:
                self._meta = {}
            if "model.weights.h5" not in self._zf.namelist():
                raise InvalidKerasConfigurationException(
                    f"{path}: zip has config.json but no model.weights.h5 "
                    f"(not a Keras v3 archive)")
        except Exception:
            self._zf.close()
            raise
        self._f = None  # weights h5 opened lazily: config-only probes and
        #                 the first import pass never pay the decompress
        # the weight store IGNORES layer.name: groups are class-name slugs
        # deduped per file in model order (an explicitly-named "my_first"
        # Dense still stores as "dense"). Map config names -> store names.
        import re as _re

        def snake(cls: str) -> str:  # keras.src.utils.naming.to_snake_case
            cls = _re.sub(r"\W+", "", cls)
            cls = _re.sub("(.)([A-Z][a-z]+)", r"\1_\2", cls)
            return _re.sub("([a-z])([A-Z])", r"\1_\2", cls).lower()

        mc = self._cfg.get("config", {})
        layer_list = mc.get("layers", []) if isinstance(mc, dict) else []
        self._store_map: Dict[str, str] = {}
        counts: Dict[str, int] = {}
        for lc in layer_list:
            cls = lc.get("class_name", "")
            nm = lc.get("config", {}).get("name")
            if cls == "InputLayer" or nm is None:
                continue
            slug = snake(cls)
            k = counts.get(slug, 0)
            counts[slug] = k + 1
            self._store_map[nm] = slug if k == 0 else f"{slug}_{k}"

    @property
    def f(self):
        if self._f is None:
            import io

            import h5py

            self._f = h5py.File(
                io.BytesIO(self._zf.read("model.weights.h5")), "r")
        return self._f

    def close(self):
        if self._f is not None:
            self._f.close()
        self._zf.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def model_config(self) -> dict:
        return self._cfg

    def keras_version(self) -> str:
        return str(self._meta.get("keras_version", "3.0.0"))

    def layer_weights(self, layer_name: str) -> List[np.ndarray]:
        import h5py

        root = self.f.get("layers")
        if root is None:
            return []
        layer_name = self._store_map.get(layer_name, layer_name)
        if layer_name not in root:
            return []
        out: List[np.ndarray] = []

        def collect(g):
            if "vars" in g:
                v = g["vars"]
                out.extend(np.asarray(v[k]) for k in sorted(v, key=int))
            subs = [k for k in g
                    if k != "vars" and not isinstance(g[k], h5py.Dataset)]
            for k in sorted(subs, key=lambda n: (self._SUB_ORDER.get(n, 50), n)):
                collect(g[k])

        collect(root[layer_name])
        return out


def open_keras_archive(path: str):
    """HDF5 (Keras 1/2 + Keras-3 legacy H5) or native Keras-3 ``.keras``
    zip — dispatched by content, not extension."""
    import zipfile

    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as z:
            if "config.json" in z.namelist():
                return KerasV3Archive(path)
        raise InvalidKerasConfigurationException(
            f"{path} is a zip but not a Keras v3 archive (no config.json)")
    return KerasHdf5Archive(path)


# ---------------------------------------------------------------------------
# Config normalization (Keras 1 → Keras 2 vocabulary)
# ---------------------------------------------------------------------------

_K1_CLASS_RENAMES = {
    "Convolution2D": "Conv2D",
    "Convolution1D": "Conv1D",
    "Deconvolution2D": "Conv2DTranspose",
    "AtrousConvolution2D": "Conv2D",
    "AtrousConvolution1D": "Conv1D",
    "SeparableConvolution2D": "SeparableConv2D",
}

def _tuple2(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1] if len(v) > 1 else v[0]))
    return (int(v), int(v))


def _normalize_config(class_name: str, conf: dict, keras_major: int) -> Tuple[str, dict]:
    """Fold Keras 1 field names into the Keras 2 vocabulary
    (Keras1LayerConfiguration.java field table)."""
    if keras_major >= 2:
        return class_name, conf
    c = dict(conf)
    class_name = _K1_CLASS_RENAMES.get(class_name, class_name)
    if "nb_filter" in c:
        c["filters"] = c.pop("nb_filter")
    if "nb_row" in c:
        c["kernel_size"] = [c.pop("nb_row"), c.pop("nb_col")]
    if "filter_length" in c:
        c["kernel_size"] = [c.pop("filter_length")]
    if "subsample" in c:
        c["strides"] = c.pop("subsample")
    if "subsample_length" in c:
        c["strides"] = [c.pop("subsample_length")]
    if "atrous_rate" in c:  # AtrousConvolution1D/2D: the dilation IS the layer
        r = c.pop("atrous_rate")
        c["dilation_rate"] = list(r) if isinstance(r, (list, tuple)) else [r]
    if "border_mode" in c:
        c["padding"] = c.pop("border_mode")
    if "dim_ordering" in c:
        c["data_format"] = {"tf": "channels_last", "th": "channels_first",
                            "default": "channels_last"}[c.pop("dim_ordering")]
    if "output_dim" in c:
        c["units"] = c.pop("output_dim")
    if "input_dim" in c and class_name == "Embedding":
        pass  # same name in keras 2
    if "init" in c:
        c["kernel_initializer"] = c.pop("init")
    if "inner_activation" in c:
        c["recurrent_activation"] = c.pop("inner_activation")
    if "p" in c and class_name == "Dropout":
        c["rate"] = c.pop("p")
    if "pool_length" in c:
        c["pool_size"] = [c.pop("pool_length")]
    if "stride" in c and class_name.endswith("Pooling1D"):
        c["strides"] = [c.pop("stride")]
    if "length" in c and class_name == "UpSampling1D":
        c["size"] = c.pop("length")
    return class_name, c


_ACTIVATION_MAP = {
    "linear": "identity", "relu": "relu", "relu6": "relu6", "sigmoid": "sigmoid",
    "tanh": "tanh", "softmax": "softmax", "softplus": "softplus",
    "softsign": "softsign", "elu": "elu", "selu": "selu", "gelu": "gelu",
    "swish": "swish", "silu": "silu", "exponential": "exp", "mish": "mish",
    "hard_sigmoid": "hardsigmoid", "leaky_relu": "leakyrelu",
}


def _act(conf: dict, default: str = "identity") -> str:
    a = conf.get("activation", default) or default
    if isinstance(a, dict):  # keras 3 serialized activation object
        a = a.get("config", {}).get("name", a.get("class_name", "linear"))
        a = str(a).lower()
    if a not in _ACTIVATION_MAP:
        raise UnsupportedKerasConfigurationException(f"Unsupported activation '{a}'")
    return _ACTIVATION_MAP[a]


def _padding(conf: dict):
    p = conf.get("padding", "valid")
    if p not in ("same", "valid"):
        raise UnsupportedKerasConfigurationException(f"Unsupported padding '{p}'")
    return p


def _data_format(conf: dict) -> str:
    return conf.get("data_format") or "channels_last"


# ---------------------------------------------------------------------------
# Layer converters: keras config dict -> our Layer / GraphVertex / None (skip)
# ---------------------------------------------------------------------------


def _conv2d(conf):
    return Conv2D(n_out=int(conf["filters"]), kernel=_tuple2(conf["kernel_size"]),
                  stride=_tuple2(conf.get("strides", (1, 1))), padding=_padding(conf),
                  dilation=_tuple2(conf.get("dilation_rate", (1, 1))),
                  activation=_act(conf), use_bias=bool(conf.get("use_bias", True)),
                  groups=int(conf.get("groups", 1)))


def _conv1d(conf):
    ks = conf["kernel_size"]
    return Conv1D(n_out=int(conf["filters"]), kernel=int(ks[0] if isinstance(ks, (list, tuple)) else ks),
                  stride=int(_first(conf.get("strides", 1))), padding=_padding(conf),
                  dilation=int(_first(conf.get("dilation_rate", 1))),
                  activation=_act(conf), use_bias=bool(conf.get("use_bias", True)))


def _first(v):
    return v[0] if isinstance(v, (list, tuple)) else v


def _deconv2d(conf):
    return Deconv2D(n_out=int(conf["filters"]), kernel=_tuple2(conf["kernel_size"]),
                    stride=_tuple2(conf.get("strides", (1, 1))), padding=_padding(conf),
                    activation=_act(conf), use_bias=bool(conf.get("use_bias", True)))


def _depthwise(conf):
    return DepthwiseConv2D(depth_multiplier=int(conf.get("depth_multiplier", 1)),
                           kernel=_tuple2(conf["kernel_size"]),
                           stride=_tuple2(conf.get("strides", (1, 1))), padding=_padding(conf),
                           activation=_act(conf), use_bias=bool(conf.get("use_bias", True)))


def _separable(conf):
    return SeparableConv2D(n_out=int(conf["filters"]), kernel=_tuple2(conf["kernel_size"]),
                           stride=_tuple2(conf.get("strides", (1, 1))), padding=_padding(conf),
                           depth_multiplier=int(conf.get("depth_multiplier", 1)),
                           activation=_act(conf), use_bias=bool(conf.get("use_bias", True)))


def _pool2d(mode):
    def cv(conf):
        return Subsampling2D(kernel=_tuple2(conf.get("pool_size", (2, 2))),
                             stride=_tuple2(conf.get("strides") or conf.get("pool_size", (2, 2))),
                             padding=_padding(conf), mode=mode)
    return cv


def _pool1d(mode):
    def cv(conf):
        ps = int(_first(conf.get("pool_size", 2)))
        return Subsampling1D(kernel=ps, stride=int(_first(conf.get("strides") or ps)),
                             padding=_padding(conf), mode=mode)
    return cv


def _global_pool(mode):
    def cv(conf):
        if conf.get("keepdims"):
            raise UnsupportedKerasConfigurationException("GlobalPooling keepdims=True unsupported")
        return GlobalPooling(mode=mode)
    return cv


def _batchnorm(conf):
    # partial scale/center (3 stored weights) imports as a full BatchNorm with
    # the missing gamma/beta synthesized to 1/0 in _convert_weights
    return BatchNorm(decay=float(conf.get("momentum", 0.99)), eps=float(conf.get("epsilon", 1e-3)),
                     lock_gamma_beta=not (conf.get("scale", True) or conf.get("center", True)))


def _ln_axis(conf) -> int:
    """Normalize the serialized LayerNormalization axis to one int (-1 or a
    positive spelling to be validated against the input rank later)."""
    axis = conf.get("axis", -1)
    if isinstance(axis, (list, tuple)):
        if len(axis) != 1:
            raise UnsupportedKerasConfigurationException(
                f"LayerNormalization over multiple axes {axis} unsupported")
        axis = axis[0]
    if axis is None:
        return -1
    axis = int(axis)
    if axis < -1:
        raise UnsupportedKerasConfigurationException(
            f"LayerNormalization over axis {axis} unsupported (last-axis only)")
    return axis


def _layernorm(conf):
    """Keras LayerNormalization -> LayerNorm (the transformer/BERT-import
    path; no reference equivalent — DL4J 0.9 predates LN). Positive axis
    spellings are validated against the input rank post-build (tf.keras 2.x
    stores the built axis, e.g. [2])."""
    _ln_axis(conf)  # reject multi-axis / below -1 up front
    if not conf.get("scale", True):
        raise UnsupportedKerasConfigurationException(
            "LayerNormalization(scale=False) unsupported")
    return LayerNorm(eps=float(conf.get("epsilon", 1e-3)),
                     use_bias=bool(conf.get("center", True)))


def _mha(conf):
    """Keras MultiHeadAttention -> fused-QKV MultiHeadAttention (self-
    attention only; BERT-import path). Attention dropout carries over."""
    if conf.get("output_shape") not in (None, []):
        raise UnsupportedKerasConfigurationException(
            "MultiHeadAttention with custom output_shape unsupported")
    return MultiHeadAttention(num_heads=int(conf["num_heads"]),
                              attn_dropout=float(conf.get("dropout", 0.0)))


def _softmax_layer(conf):
    if conf.get("axis", -1) not in (-1, None):
        raise UnsupportedKerasConfigurationException(
            f"Softmax over axis {conf.get('axis')} unsupported (last-axis only)")
    return ActivationLayer(activation="softmax")


def _lstm(conf):
    if conf.get("go_backwards"):
        raise UnsupportedKerasConfigurationException("LSTM go_backwards unsupported")
    return LSTM(n_out=int(conf["units"]), activation=_act(conf, "tanh"),
                gate_activation=_ACTIVATION_MAP.get(_raw_rec_act(conf), "sigmoid"),
                forget_gate_bias_init=1.0 if conf.get("unit_forget_bias", True) else 0.0)


def _raw_rec_act(conf) -> str:
    a = conf.get("recurrent_activation", "sigmoid") or "sigmoid"
    if isinstance(a, dict):
        a = a.get("config", {}).get("name", "sigmoid")
    return str(a).lower()


def _gru(conf):
    if conf.get("go_backwards"):
        raise UnsupportedKerasConfigurationException("GRU go_backwards unsupported")
    return GRU(n_out=int(conf["units"]), activation=_act(conf, "tanh"),
               gate_activation=_ACTIVATION_MAP.get(_raw_rec_act(conf), "sigmoid"),
               reset_after=bool(conf.get("reset_after", False)))


def _simple_rnn(conf):
    if conf.get("go_backwards"):
        raise UnsupportedKerasConfigurationException("SimpleRNN go_backwards unsupported")
    return SimpleRnn(n_out=int(conf["units"]), activation=_act(conf, "tanh"))


def _bidirectional(conf, ctx):
    sub_cls = conf["layer"]["class_name"]
    sub_conf = conf["layer"]["config"]
    sub_cls, sub_conf = _normalize_config(sub_cls, sub_conf, ctx.keras_major)
    if conf.get("merge_mode", "concat") not in ("concat", "sum", "ave", "mul"):
        raise UnsupportedKerasConfigurationException(f"merge_mode {conf.get('merge_mode')}")
    mode = {"concat": "concat", "sum": "add", "ave": "average", "mul": "mul"}[
        conf.get("merge_mode", "concat")]
    sub = _convert_layer(sub_cls, sub_conf, ctx)
    if not isinstance(sub, (LSTM, GRU, SimpleRnn)):
        raise UnsupportedKerasConfigurationException(
            f"Bidirectional wraps unsupported layer {sub_cls}")
    return Bidirectional(fwd=sub.to_dict(), mode=mode)


def _embedding(conf):
    return EmbeddingSequence(n_in=int(conf["input_dim"]),
                             n_out=int(conf.get("output_dim") or conf["units"]),
                             mask_zero=bool(conf.get("mask_zero", False)))


def _dense(conf):
    return Dense(n_out=int(conf["units"]), activation=_act(conf),
                 use_bias=bool(conf.get("use_bias", True)))


def _activation_layer(conf):
    return ActivationLayer(activation=_act(conf, "identity"))


def _dropout(conf):
    return DropoutLayer(rate=float(conf.get("rate", 0.5)))


def _zero_pad2d(conf):
    p = conf.get("padding", 1)
    if isinstance(p, (list, tuple)) and isinstance(p[0], (list, tuple)):
        if p[0][0] != p[0][1] or p[1][0] != p[1][1]:
            raise UnsupportedKerasConfigurationException("Asymmetric ZeroPadding2D")
        p = (p[0][0], p[1][0])
    return ZeroPadding2D(padding=_tuple2(p))


def _zero_pad1d(conf):
    p = conf.get("padding", 1)
    if isinstance(p, (list, tuple)):
        if isinstance(p[0], (list, tuple)):
            p = p[0]
        if p[0] != p[-1]:
            raise UnsupportedKerasConfigurationException("Asymmetric ZeroPadding1D")
        p = p[0]
    return ZeroPadding1D(padding=int(p))


def _cropping2d(conf):
    cr = conf.get("cropping", ((0, 0), (0, 0)))
    if isinstance(cr, int):
        cr = ((cr, cr), (cr, cr))
    if isinstance(cr[0], int):
        cr = ((cr[0], cr[0]), (cr[1], cr[1]))
    if cr[0][0] != cr[0][1] or cr[1][0] != cr[1][1]:
        raise UnsupportedKerasConfigurationException("Asymmetric Cropping2D")
    return Cropping2D(cropping=(cr[0][0], cr[1][0]))


def _upsampling2d(conf):
    if str(conf.get("interpolation", "nearest")) != "nearest":
        raise UnsupportedKerasConfigurationException("Only nearest-neighbor UpSampling2D")
    return Upsampling2D(size=_tuple2(conf.get("size", (2, 2))))


def _reshape(conf):
    return Reshape(shape=tuple(int(d) for d in conf["target_shape"]))


def _leaky_relu(conf):
    alpha = float(conf.get("alpha", conf.get("negative_slope", 0.3)))
    if abs(alpha - 0.01) > 1e-9:
        # our registry's leakyrelu has a fixed 0.01 slope; other slopes would
        # silently change the function, so refuse rather than approximate
        raise UnsupportedKerasConfigurationException(
            f"LeakyReLU alpha={alpha} != 0.01; wrap as PReLU instead")
    return ActivationLayer(activation="leakyrelu")


def _prelu(conf):
    shared = conf.get("shared_axes")
    if shared:
        raise UnsupportedKerasConfigurationException("PReLU shared_axes unsupported")
    return PReLU()


_MERGE_CLASSES = {
    "Add": ElementWise(op="add"),
    "Subtract": ElementWise(op="subtract"),
    "Multiply": ElementWise(op="product"),
    "Average": ElementWise(op="average"),
    "Maximum": ElementWise(op="max"),
    "Concatenate": Merge(),
}

_SKIP_CLASSES = {"InputLayer"}  # handled at the container level


class _Ctx:
    def __init__(self, keras_major: int):
        self.keras_major = keras_major
        # (concat layer name, positive axis) pairs to validate against actual
        # input ranks once the graph's shapes are known
        self.concat_axis_checks: List[Tuple[Optional[str], int]] = []
        # LayerNormalization with a positive axis spelling (tf.keras 2.x
        # serializes the built axis, e.g. [2]) — validate it IS the last
        # axis once input ranks are known
        self.ln_axis_checks: List[Tuple[Optional[str], int]] = []


def _convert_layer(class_name: str, conf: dict, ctx: _Ctx):
    """Dispatch one Keras layer config to our Layer/Vertex. Returns None to skip."""
    if class_name in _SKIP_CLASSES:
        return None
    if class_name in _MERGE_CLASSES:
        if class_name == "Concatenate":
            ax = conf.get("axis", -1)
            if ax not in (-1, None):
                # positive spellings of the channel axis (e.g. axis=3 on NHWC
                # 4D) are fine; validated against actual input rank post-build
                ctx.concat_axis_checks.append((conf.get("name"), int(ax)))
        return _MERGE_CLASSES[class_name]
    simple = {
        "Dense": _dense, "Conv2D": _conv2d, "Conv1D": _conv1d,
        "Conv2DTranspose": _deconv2d, "DepthwiseConv2D": _depthwise,
        "SeparableConv2D": _separable,
        "MaxPooling2D": _pool2d("max"), "AveragePooling2D": _pool2d("avg"),
        "MaxPooling1D": _pool1d("max"), "AveragePooling1D": _pool1d("avg"),
        "GlobalMaxPooling2D": _global_pool("max"),
        "GlobalAveragePooling2D": _global_pool("avg"),
        "GlobalMaxPooling1D": _global_pool("max"),
        "GlobalAveragePooling1D": _global_pool("avg"),
        "BatchNormalization": _batchnorm, "LSTM": _lstm, "GRU": _gru,
        "SimpleRNN": _simple_rnn, "Embedding": _embedding,
        "Activation": _activation_layer, "Dropout": _dropout,
        "SpatialDropout1D": _dropout, "SpatialDropout2D": _dropout,
        "Flatten": lambda c: Flatten(), "Reshape": _reshape,
        "ZeroPadding2D": _zero_pad2d, "ZeroPadding1D": _zero_pad1d,
        "Cropping2D": _cropping2d, "UpSampling2D": _upsampling2d,
        "UpSampling1D": lambda c: Upsampling1D(size=int(_first(c.get("size", 2)))),
        "LeakyReLU": _leaky_relu, "PReLU": _prelu,
        "ELU": lambda c: ActivationLayer(activation="elu"),
        "ThresholdedReLU": lambda c: ActivationLayer(activation="thresholdedrelu"),
        "MultiHeadAttention": _mha,
        "Softmax": _softmax_layer,
        # noise/ converters (KerasGaussianNoise/GaussianDropout/AlphaDropout)
        "GaussianNoise": lambda c: GaussianNoise(stddev=float(c.get("stddev", 0.1))),
        "GaussianDropout": lambda c: GaussianDropout(rate=float(c.get("rate", 0.5))),
        "AlphaDropout": lambda c: AlphaDropout(rate=float(c.get("rate", 0.5))),
        "Cropping1D": lambda c: Cropping1D(
            cropping=tuple(int(v) for v in _tuple2(c.get("cropping", (1, 1))))),
    }
    if class_name == "LayerNormalization":
        ln = _layernorm(conf)  # validates the axis spelling itself
        ax = _ln_axis(conf)
        if ax >= 0:  # positive spelling: defer rank validation
            ctx.ln_axis_checks.append((conf.get("name"), ax))
        return ln
    if class_name == "Bidirectional":
        bidi = _bidirectional(conf, ctx)
        if not conf["layer"]["config"].get("return_sequences", False):
            raise UnsupportedKerasConfigurationException(
                "Bidirectional(return_sequences=False) unsupported; re-save with "
                "return_sequences=True + downstream pooling")
        return bidi
    if class_name in ("LSTM", "GRU", "SimpleRNN"):
        rnn = simple[class_name](conf)
        if not conf.get("return_sequences", False):
            # KerasLstm.java parity: keras return_sequences=False == DL4J
            # LastTimeStep-wrapped RNN
            return LastTimeStep(fwd=rnn.to_dict())
        return rnn
    if class_name == "TimeDistributed":
        # TimeDistributed(Dense) == Dense over the last axis of (B,T,F)
        inner_cls = conf["layer"]["class_name"]
        inner_conf = conf["layer"]["config"]
        inner_cls, inner_conf = _normalize_config(inner_cls, inner_conf, ctx.keras_major)
        if inner_cls != "Dense":
            raise UnsupportedKerasConfigurationException(
                f"TimeDistributed({inner_cls}) unsupported")
        return _dense(inner_conf)
    if class_name not in simple:
        raise UnsupportedKerasConfigurationException(
            f"Unsupported Keras layer '{class_name}' "
            f"(KerasLayer mapping table, KerasModelImport parity)")
    return simple[class_name](conf)


# ---------------------------------------------------------------------------
# Weight conversion: keras stored arrays -> our params (+state)
# ---------------------------------------------------------------------------


def _convert_weights(layer: Layer, arrays: List[np.ndarray], *, keras_major: int,
                     th_ordering: bool = False,
                     conf: Optional[dict] = None) -> Tuple[dict, dict]:
    """Map keras weight arrays (in ``weight_names`` order) onto our param/state
    pytrees. Returns (params, state)."""
    a = [np.asarray(x) for x in arrays]
    j = lambda x: jnp.asarray(x)
    if isinstance(layer, Dense):
        p = {"w": j(a[0])}
        if layer.use_bias:
            p["b"] = j(a[1])
        return p, {}
    if isinstance(layer, (Conv2D, Deconv2D)):
        w = a[0]
        if th_ordering and w.ndim == 4:
            w = np.transpose(w, (2, 3, 1, 0))  # OIHW -> HWIO
        p = {"w": j(w)}
        if layer.use_bias:
            p["b"] = j(a[1])
        return p, {}
    if isinstance(layer, DepthwiseConv2D):
        # keras depthwise kernel (kh,kw,C,M); ours (kh,kw,1,C*M) — output
        # channel c*M+m maps to input channel c in both, so reshape suffices
        kh, kw, c, m = a[0].shape
        p = {"w": j(a[0].reshape(kh, kw, 1, c * m))}
        if layer.use_bias:
            p["b"] = j(a[1])
        return p, {}
    if isinstance(layer, SeparableConv2D):
        kh, kw, c, m = a[0].shape
        p = {"w_depth": j(a[0].reshape(kh, kw, 1, c * m)), "w_point": j(a[1])}
        if layer.use_bias:
            p["b"] = j(a[2])
        return p, {}
    if isinstance(layer, Conv1D):
        p = {"w": j(a[0])}  # keras (k, in, out) == our WIO
        if layer.use_bias:
            p["b"] = j(a[1])
        return p, {}
    if isinstance(layer, BatchNorm):
        # keras order: [gamma], [beta], moving_mean, moving_variance
        # (gamma present iff scale=True, beta iff center=True); partials are
        # imported as a full BatchNorm with the missing param synthesized
        vals = list(a)
        scale = bool(conf.get("scale", True)) if conf else len(vals) == 4
        center = bool(conf.get("center", True)) if conf else len(vals) == 4
        expected = 2 + int(scale) + int(center)
        if len(vals) != expected:
            raise InvalidKerasConfigurationException(
                f"BatchNormalization: scale={scale} center={center} expects "
                f"{expected} weights, got {len(vals)}")
        mean, var = vals[-2], vals[-1]
        n = mean.shape[0]
        gamma = vals[0] if scale else np.ones(n, np.float32)
        beta = (vals[1] if scale else vals[0]) if center else np.zeros(n, np.float32)
        params = {} if layer.lock_gamma_beta else {"gamma": j(gamma), "beta": j(beta)}
        return params, {"mean": j(mean), "var": j(var)}
    if isinstance(layer, LayerNorm):
        p = {"gamma": j(a[0])}
        if layer.use_bias:
            if len(a) < 2:
                raise InvalidKerasConfigurationException(
                    "LayerNormalization(center=True) expects gamma+beta weights")
            p["beta"] = j(a[1])
        return p, {}
    if isinstance(layer, MultiHeadAttention):
        # keras MHA stores per-projection kernels: query/key/value (d, H, hd)
        # + optional biases (H, hd), then attention_output (H, hd, d) + (d,).
        # Our layer fuses them: w_qkv (d, 3d), w_o (d, d) — requires the
        # standard BERT geometry H*hd == d.
        use_bias = len(a) == 8
        if len(a) not in (4, 8):
            raise InvalidKerasConfigurationException(
                f"MultiHeadAttention expects 4 or 8 weights, got {len(a)}")
        if use_bias:
            wq, bq_, wk, bk_, wv, bv_, wo, bo = a
        else:
            wq, wk, wv, wo = a
        d, H, hd = wq.shape
        if H * hd != d:
            raise UnsupportedKerasConfigurationException(
                f"MultiHeadAttention num_heads*key_dim={H * hd} != d_model={d}; "
                f"the fused-QKV layer requires the standard geometry")
        if wk.shape != wq.shape or wv.shape != wq.shape:
            raise UnsupportedKerasConfigurationException(
                f"MultiHeadAttention with value_dim/key_dim mismatch "
                f"(q{wq.shape} k{wk.shape} v{wv.shape}) unsupported — the "
                f"fused-QKV layer requires identical projection shapes")
        w_qkv = np.concatenate([w.reshape(d, d) for w in (wq, wk, wv)], axis=1)
        if use_bias:
            b_qkv = np.concatenate([b.reshape(d) for b in (bq_, bk_, bv_)])
        else:
            b_qkv, bo = np.zeros(3 * d, np.float32), np.zeros(d, np.float32)
        return {"w_qkv": j(w_qkv), "b_qkv": j(b_qkv),
                "w_o": j(wo.reshape(d, d)), "b_o": j(bo)}, {}
    if isinstance(layer, LSTM):
        # keras: kernel (in,4H) [i,f,c,o], recurrent_kernel (H,4H), bias (4H)
        # ours:  w_ih (in,4H) [i,f,g,o],  w_hh (H,4H),              b (4H)
        b = a[2] if len(a) > 2 else np.zeros(a[0].shape[-1], np.float32)
        return {"w_ih": j(a[0]), "w_hh": j(a[1]), "b": j(b)}, {}
    if isinstance(layer, GRU):
        # keras blocks [z,r,h] -> ours [r,u,n] where u==z
        def perm(m):
            H = m.shape[-1] // 3
            z, r, h = m[..., :H], m[..., H:2 * H], m[..., 2 * H:]
            return np.concatenate([r, z, h], axis=-1)
        p = {"w_ih": j(perm(a[0])), "w_hh": j(perm(a[1]))}
        H3 = a[0].shape[-1]
        bias = a[2] if len(a) > 2 else (
            np.zeros((2, H3), np.float32) if layer.reset_after else np.zeros(H3, np.float32))
        if layer.reset_after:
            # keras reset_after bias is (2, 3H): [input bias, recurrent bias]
            if bias.ndim != 2:
                raise InvalidKerasConfigurationException(
                    f"reset_after GRU expects (2,3H) bias, got {bias.shape}")
            p["b"] = j(perm(bias[0]))
            p["b_hh"] = j(perm(bias[1]))
        else:
            p["b"] = j(perm(bias.reshape(-1)))
        return p, {}
    if isinstance(layer, SimpleRnn):
        b = a[2] if len(a) > 2 else np.zeros(a[0].shape[-1], np.float32)
        return {"w": j(a[0]), "r": j(a[1]), "b": j(b)}, {}
    if isinstance(layer, LastTimeStep):
        return _convert_weights(layer._sub(), arrays, keras_major=keras_major,
                                th_ordering=th_ordering, conf=conf)
    if isinstance(layer, Bidirectional):
        sub = layer._sub()
        n = len(a) // 2
        pf, _ = _convert_weights(sub, a[:n], keras_major=keras_major, th_ordering=th_ordering)
        pb, _ = _convert_weights(sub, a[n:], keras_major=keras_major, th_ordering=th_ordering)
        return {"fwd": pf, "bwd": pb}, {}
    if isinstance(layer, EmbeddingSequence):
        return {"w": j(a[0])}, {}
    if isinstance(layer, PReLU):
        alpha = a[0]
        return {"alpha": j(alpha.reshape(-1))}, {}
    if not arrays:
        return {}, {}
    raise UnsupportedKerasConfigurationException(
        f"No weight converter for {type(layer).__name__}")


# ---------------------------------------------------------------------------
# Containers
# ---------------------------------------------------------------------------


def _input_shape_from_conf(conf: dict) -> Optional[Tuple[int, ...]]:
    bis = conf.get("batch_input_shape") or conf.get("batch_shape")
    if bis is not None:
        return tuple(int(d) for d in bis[1:] if d is not None)
    if conf.get("input_shape"):
        return tuple(int(d) for d in conf["input_shape"] if d is not None)
    return None


def _nhwc_shape(shape: Tuple[int, ...], data_format: str) -> Tuple[int, ...]:
    if data_format == "channels_first" and len(shape) == 3:
        c, h, w = shape
        return (h, w, c)
    return shape


def import_keras_sequential_model_and_weights(path: str, *, input_shape=None) -> Sequential:
    """KerasModelImport.importKerasSequentialModelAndWeights (:74) equivalent:
    Keras Sequential HDF5 → our ``Sequential`` with weights loaded."""
    with open_keras_archive(path) as ar:
        cfg = ar.model_config()
        if cfg.get("class_name") not in ("Sequential",):
            raise InvalidKerasConfigurationException(
                f"Not a Sequential model: {cfg.get('class_name')}")
        keras_major = int(ar.keras_version().split(".")[0])
        ctx = _Ctx(keras_major)
        layer_confs = cfg["config"]
        if isinstance(layer_confs, dict):  # keras 2: {"name":..., "layers":[...]}
            layer_confs = layer_confs.get("layers", [])
        layers: List[Layer] = []
        confs: Dict[str, dict] = {}
        # normalize once; then decide channels_first BEFORE converting any
        # shape (the conf holding the input shape — e.g. a Keras-3
        # InputLayer — may not carry data_format)
        normalized = [(_normalize_config(lc["class_name"], lc["config"], keras_major), lc)
                      for lc in layer_confs]
        th = any(conf.get("data_format") == "channels_first"
                 for (_, conf), _ in normalized)
        in_shape = tuple(input_shape) if input_shape is not None else None
        for (cls, conf), lc in normalized:
            if in_shape is None:
                s = _input_shape_from_conf(conf)
                if s is not None:
                    df = conf.get("data_format") or (
                        "channels_first" if th else "channels_last")
                    in_shape = _nhwc_shape(s, df)
            converted = _convert_layer(cls, conf, ctx)
            if converted is None:
                continue
            if isinstance(converted, GraphVertex):
                raise InvalidKerasConfigurationException(
                    f"Merge layer {cls} inside a Sequential model")
            converted = dataclass_replace(converted, name=conf.get("name", lc["config"].get("name")))
            layers.append(converted)
            if converted.name:
                confs[converted.name] = conf
        if in_shape is None:
            raise InvalidKerasConfigurationException(
                "Could not infer input shape; pass input_shape=...")
        model = Sequential(NetConfig(), layers, in_shape)
        # deferred LayerNormalization positive-axis validation (same contract
        # as the functional path): the axis must be the LAST axis of the
        # layer's actual input
        if ctx.ln_axis_checks:
            by_name = {layer.name: i for i, layer in enumerate(model.layers)}
            for lname, ax in ctx.ln_axis_checks:
                if lname in by_name:
                    rank = len(model.layer_input_shape(by_name[lname])) + 1
                    if ax != rank - 1:
                        raise UnsupportedKerasConfigurationException(
                            f"LayerNormalization '{lname}' axis={ax} is not "
                            f"the last axis for rank-{rank} inputs")
        model.init()
        _load_weights_sequential(model, ar, keras_major, confs,
                                 th_ordering=th and keras_major < 2,
                                 channels_first=th)
        return model


def dataclass_replace(layer: Layer, **kw) -> Layer:
    import dataclasses

    return dataclasses.replace(layer, **kw)


_FLATTEN_PASSTHROUGH = (DropoutLayer, ActivationLayer)


def _chw_flatten_feeding_dense(model: Sequential, i: int,
                               confs: Dict[str, dict]):
    """If layer i (a Dense) is fed — possibly through weightless passthrough
    layers (Dropout/Activation) — by a Flatten that emitted raw CHW order,
    return that Flatten's 3D input shape, else None."""
    j = i - 1
    while j > 0 and isinstance(model.layers[j], _FLATTEN_PASSTHROUGH):
        j -= 1
    if (j >= 0 and isinstance(model.layers[j], Flatten)
            and len(model.layer_input_shape(j)) == 3
            and _flatten_was_chw(confs.get(model.layers[j].name))):
        return model.layer_input_shape(j)
    return None


def _flatten_was_chw(flatten_conf: Optional[dict]) -> bool:
    """True when the Keras Flatten emitted raw CHW order. Keras 2/3 Flatten
    with data_format='channels_first' transposes to channels_last BEFORE
    flattening (so no fix is needed); Keras 1 'th' and a default-format
    Flatten fed a CHW tensor flatten raw."""
    return (flatten_conf or {}).get("data_format") != "channels_first"


def _reorder_flatten_dense_kernel(w: np.ndarray, pre_shape_hwc) -> np.ndarray:
    """channels_first models flatten CHW at runtime but our NHWC runtime
    flattens HWC; reorder the first post-Flatten Dense kernel's rows so
    Flatten->Dense CNNs import correctly (reference parity: KerasFlatten.java
    inserts a dim-order-aware CnnToFeedForwardPreProcessor)."""
    h, wd, c = (int(d) for d in pre_shape_hwc)
    n_out = w.shape[-1]
    if w.shape[0] != h * wd * c:
        raise InvalidKerasConfigurationException(
            f"post-Flatten Dense kernel rows {w.shape[0]} != flattened input "
            f"{h}*{wd}*{c}")
    return np.ascontiguousarray(
        w.reshape(c, h, wd, n_out).transpose(1, 2, 0, 3).reshape(h * wd * c, n_out))


def _load_weights_sequential(model: Sequential, ar: KerasHdf5Archive, keras_major: int,
                             confs: Dict[str, dict], th_ordering: bool = False,
                             channels_first: bool = False) -> None:
    for i, layer in enumerate(model.layers):
        if layer.name is None:
            continue
        arrays = ar.layer_weights(layer.name)
        if not arrays:
            continue
        if channels_first and isinstance(layer, Dense) and i > 0:
            pre_shape = _chw_flatten_feeding_dense(model, i, confs)
            if pre_shape is not None:
                arrays = [_reorder_flatten_dense_kernel(
                    np.asarray(arrays[0]), pre_shape)] + list(arrays[1:])
        p, s = _convert_weights(layer, arrays, keras_major=keras_major,
                                th_ordering=th_ordering, conf=confs.get(layer.name))
        key = layer.name or f"layer_{i}"
        if p:
            model.params[key] = jnp_cast_tree(p, model.dtype)
        if s:
            model.state[key] = jnp_cast_tree(s, model.dtype)


def jnp_cast_tree(tree, dtype):
    import jax

    return jax.tree.map(lambda x: jnp.asarray(x, dtype), tree)


# --- functional (DAG) models ---


def _inbound_refs(inbound_nodes) -> List[List[Tuple[str, int]]]:
    """Parse inbound node specs into per-application reference lists.

    A Keras layer called at N sites has N inbound nodes; each reference is
    ``(layer_name, node_index)`` where node_index selects *which application*
    of the referenced layer produced the tensor (shared-layer support).
    Handles keras 1/2 list form and keras 3 ``__keras_tensor__`` dict form.
    """
    apps: List[List[Tuple[str, int]]] = []
    for node in inbound_nodes or []:
        refs: List[Tuple[str, int]] = []
        if isinstance(node, dict):  # keras 3: {"args": [...], "kwargs": {...}}
            def walk(obj):
                if isinstance(obj, dict):
                    if obj.get("class_name") == "__keras_tensor__":
                        h = obj["config"]["keras_history"]
                        refs.append((h[0], int(h[1])))
                        return
                    for v in obj.values():
                        walk(v)
                elif isinstance(obj, (list, tuple)):
                    for v in obj:
                        walk(v)
            walk(node.get("args", []))
        else:  # keras 1/2: [["name", node_idx, tensor_idx, {...}], ...]
            for entry in node:
                refs.append((entry[0], int(entry[1])))
        apps.append(refs)
    return apps


def _inbound_call_kwargs(inbound_nodes) -> List[dict]:
    """Per-application CALL kwargs (keras 3 dict form / keras 1-2 4th entry).
    Needed for layers whose call signature carries semantics (e.g.
    MultiHeadAttention's value=/key= tensors and use_causal_mask)."""
    out: List[dict] = []
    for node in inbound_nodes or []:
        if isinstance(node, dict):
            out.append(node.get("kwargs") or {})
        else:
            kw = {}
            for entry in node:
                if len(entry) > 3 and isinstance(entry[3], dict):
                    kw.update(entry[3])
            out.append(kw)
    return out


def _kwargs_tensor_refs(kwargs: dict) -> List[Tuple[str, int]]:
    """Tensor references hiding in call kwargs (value=/key= passed by name)."""
    refs: List[Tuple[str, int]] = []

    def walk(obj):
        if isinstance(obj, dict):
            if obj.get("class_name") == "__keras_tensor__":
                h = obj["config"]["keras_history"]
                refs.append((h[0], int(h[1])))
                return
            for v in obj.values():
                walk(v)
        elif isinstance(obj, (list, tuple)):
            for v in obj:
                walk(v)

    walk(kwargs)
    return refs


def _app_node_name(layer_name: str, app_idx: int) -> str:
    """Graph-node name for the app_idx'th application of a shared layer."""
    return layer_name if app_idx == 0 else f"{layer_name}__shared{app_idx}"


def import_keras_model_and_weights(path: str):
    """KerasModelImport.importKerasModelAndWeights (:50) equivalent. Auto-detects
    Sequential vs Functional; returns ``Sequential`` or ``Graph`` accordingly."""
    with open_keras_archive(path) as ar:
        cfg = ar.model_config()
    if cfg.get("class_name") == "Sequential":
        return import_keras_sequential_model_and_weights(path)
    if cfg.get("class_name") not in ("Model", "Functional"):
        raise InvalidKerasConfigurationException(f"Unknown model class {cfg.get('class_name')}")
    with open_keras_archive(path) as ar:
        keras_major = int(ar.keras_version().split(".")[0])
        ctx = _Ctx(keras_major)
        mc = cfg["config"]
        gb = GraphBuilder(NetConfig())
        imported: Dict[str, Layer] = {}
        def _node_names(spec) -> List[str]:
            """['a',0,0] | [['a',0,0],['b',0,0]] | ['a','b'] -> graph node names
            (resolving shared-layer application indices)."""
            if not spec:
                return []
            if (isinstance(spec, (list, tuple)) and len(spec) == 3
                    and isinstance(spec[0], str) and not isinstance(spec[1], (list, tuple))):
                return [_app_node_name(spec[0], int(spec[1]))]
            out = []
            for n in spec:
                if isinstance(n, (list, tuple)):
                    out.append(_app_node_name(n[0], int(n[1]) if len(n) > 1 else 0))
                else:
                    out.append(n)
            return out

        input_names = _node_names(mc.get("input_layers", []))
        # keras_name -> [graph node name per application] (shared-layer dup)
        app_nodes: Dict[str, List[str]] = {}
        confs: Dict[str, dict] = {}
        # normalize once; detect channels_first before any shape conversion
        # (same reason as the Sequential loader)
        normalized = [(_normalize_config(lc["class_name"], lc["config"], keras_major), lc)
                      for lc in mc["layers"]]
        th = any(conf.get("data_format") == "channels_first"
                 for (_, conf), _ in normalized)
        for (cls, conf), lc in normalized:
            name = lc.get("name") or conf.get("name")
            apps = _inbound_refs(lc.get("inbound_nodes", []))
            if cls == "InputLayer":
                s = _input_shape_from_conf(conf)
                if s is None:
                    raise InvalidKerasConfigurationException(f"InputLayer {name} missing shape")
                df = conf.get("data_format") or (
                    "channels_first" if th else "channels_last")
                gb.add_input(name, _nhwc_shape(s, df))
                app_nodes[name] = [name]
                continue
            converted = _convert_layer(cls, conf, ctx)
            if converted is None:
                continue
            node_names = []
            for i, refs in enumerate(apps or [[]]):
                node_name = _app_node_name(name, i)
                inbound = [_app_node_name(rn, ri) for rn, ri in refs]
                per_app = converted  # per-application variant (e.g. causal
                # flag) must NOT leak into later applications of a shared layer
                if isinstance(converted, MultiHeadAttention):
                    # keras calls MHA as (query, value[, key]) positionally OR
                    # by keyword; only SELF-attention maps to our layer
                    call_kwargs = _inbound_call_kwargs(lc.get("inbound_nodes", []))
                    kw = call_kwargs[i] if i < len(call_kwargs) else {}
                    kw_refs = [_app_node_name(rn, ri)
                               for rn, ri in _kwargs_tensor_refs(kw)]
                    if len(set(inbound + kw_refs)) != 1:
                        raise UnsupportedKerasConfigurationException(
                            f"MultiHeadAttention '{name}': cross-attention "
                            f"(distinct query/value inputs "
                            f"{inbound + kw_refs}) unsupported")
                    if kw.get("use_causal_mask"):
                        per_app = dataclass_replace(per_app, causal=True)
                    inbound = (inbound or kw_refs)[:1]
                if isinstance(per_app, GraphVertex):
                    gb.add_vertex(node_name, per_app, *inbound)
                else:
                    named = dataclass_replace(per_app, name=node_name)
                    imported[node_name] = named
                    confs[node_name] = conf
                    gb.add_layer(node_name, named, *inbound)
                node_names.append(node_name)
            app_nodes[name] = node_names
        gb.set_outputs(*_node_names(mc.get("output_layers", [])))
        graph = gb.build()
        # positive Concatenate axes must equal the channel (last) axis for the
        # actual input rank; anything else has no Merge-vertex equivalent
        for cname, ax in ctx.concat_axis_checks:
            nodes = app_nodes.get(cname, [cname])
            for node_name in nodes:
                if node_name not in graph.nodes:
                    continue
                in0 = graph.nodes[node_name].inputs[0]
                rank = len(graph._shapes[in0]) + 1  # + batch dim
                if ax != rank - 1:
                    raise UnsupportedKerasConfigurationException(
                        f"Concatenate '{cname}' axis={ax} is not the channel "
                        f"axis for rank-{rank} inputs")
        for lname, ax in ctx.ln_axis_checks:
            for node_name in app_nodes.get(lname, [lname]):
                if node_name not in graph.nodes:
                    continue
                in0 = graph.nodes[node_name].inputs[0]
                rank = len(graph._shapes[in0]) + 1
                if ax != rank - 1:
                    raise UnsupportedKerasConfigurationException(
                        f"LayerNormalization '{lname}' axis={ax} is not the "
                        f"last axis for rank-{rank} inputs")
        graph.init()
        th_ordering = th and keras_major < 2
        for node_name, layer in imported.items():
            # a shared layer's applications all read the same stored weights;
            # training after import unties them (documented import limitation)
            keras_name = node_name.split("__shared")[0]
            arrays = ar.layer_weights(keras_name)
            if not arrays:
                continue
            if th and isinstance(layer, Dense):
                # walk back through weightless passthrough layers to the
                # Flatten (if any) feeding this Dense
                cur = graph.nodes[node_name].inputs[0] if graph.nodes[node_name].inputs else None
                while (cur in graph.nodes and graph.nodes[cur].is_layer()
                       and isinstance(graph.nodes[cur].spec, _FLATTEN_PASSTHROUGH)
                       and graph.nodes[cur].inputs):
                    cur = graph.nodes[cur].inputs[0]
                if cur in graph.nodes:
                    pred = graph.nodes[cur]
                    pre_in = pred.inputs[0] if pred.inputs else None
                    if (pred.is_layer() and isinstance(pred.spec, Flatten)
                            and pre_in is not None
                            and len(graph._shapes[pre_in]) == 3
                            and _flatten_was_chw(confs.get(cur))):
                        arrays = [_reorder_flatten_dense_kernel(
                            np.asarray(arrays[0]), graph._shapes[pre_in])] + list(arrays[1:])
            p, s = _convert_weights(layer, arrays, keras_major=keras_major,
                                    th_ordering=th_ordering, conf=confs.get(node_name))
            if p:
                graph.params[node_name] = jnp_cast_tree(p, graph.dtype)
            if s:
                graph.state[node_name] = jnp_cast_tree(s, graph.dtype)
        return graph
