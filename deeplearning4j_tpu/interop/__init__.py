"""Interop — model import/export (deeplearning4j-modelimport equivalent)."""

from .keras_import import (InvalidKerasConfigurationException,
                           KerasHdf5Archive,
                           UnsupportedKerasConfigurationException,
                           import_keras_model_and_weights,
                           import_keras_sequential_model_and_weights)
from .guesser import guess_model_format, load_model_guess
from .pretrained import convert_keras_application

__all__ = [
    "InvalidKerasConfigurationException", "KerasHdf5Archive",
    "UnsupportedKerasConfigurationException", "convert_keras_application",
    "import_keras_model_and_weights",
    "import_keras_sequential_model_and_weights", "guess_model_format",
    "load_model_guess",
]
