"""Pretrained zoo weights via the Keras bridge (ZooModel.java:51-81 parity).

The reference ships trained ImageNet weights from its CDN with checksum
validation (``ZooModel.initPretrained`` downloads, md5-checks, deletes on
corruption — ZooModel.java:54-66; per-model URLs e.g. ResNet50.java:54-66).
The TPU-native pipeline replaces the CDN with the (golden-tested) Keras
importer: ``keras.applications`` weights convert through
``import_keras_model_and_weights`` into the standard checkpoint zip,
publish into the zoo cache with a recorded sha256, and
``ZooModel.init_pretrained()`` serves + verifies the checksum on load.

On an egress-less machine the conversion needs a warm ``~/.keras`` weight
cache; everything downstream of the download (conversion, checksum,
serve, logits parity vs Keras) is exercised by ``tests/test_pretrained.py``
with Keras-initialized weights — the identical path trained weights ride.
"""

from __future__ import annotations

import hashlib
from pathlib import Path

# zoo name -> keras.applications factory attribute
KERAS_APPLICATIONS = {
    "vgg16": "VGG16",
    "vgg19": "VGG19",
    "resnet50": "ResNet50",
}


def sha256_of(path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_checksum(path) -> Path:
    """Record ``<zip>.sha256`` next to a published checkpoint (the cache's
    integrity sidecar — the reference embeds expected md5s in each zoo
    class, ZooModel.java:62)."""
    side = Path(str(path) + ".sha256")
    side.write_text(sha256_of(path) + "\n")
    return side


class ChecksumMismatch(OSError):
    """Cached checkpoint digest != recorded sidecar digest. A dedicated
    type so delete-on-corrupt logic can't be triggered by transient I/O
    errors (permissions, NFS hiccups) that also surface as OSError."""


def verify_checksum(path) -> bool:
    """True if no sidecar exists (nothing to verify) or the digest matches;
    raises ``ChecksumMismatch`` on mismatch (mirroring the reference's
    delete-and-fail on a corrupt download)."""
    side = Path(str(path) + ".sha256")
    if not side.exists():
        return True
    expected = side.read_text().strip()
    actual = sha256_of(path)
    if actual != expected:
        raise ChecksumMismatch(
            f"pretrained checkpoint {path} is corrupt: sha256 {actual} != "
            f"recorded {expected} — delete it and re-run the conversion "
            f"(interop.pretrained.convert_keras_application)")
    return True


def convert_keras_application(name: str, *, weights: str = "imagenet",
                              pretrained_type: str = "imagenet",
                              classes: int = 1000, keras_model=None):
    """Convert a ``keras.applications`` network into this zoo entry's
    pretrained checkpoint zip: build the Keras model (downloading its
    weights when ``weights='imagenet'`` and egress/cache allow), run it
    through the Keras importer, publish via ``save_pretrained`` and record
    the sha256. Returns the checkpoint path.

    ``keras_model`` supplies a prebuilt Keras network (skipping the
    factory); ``weights=None`` converts the Keras-initialized network —
    the golden tests use both to prove the pipeline end-to-end without
    egress."""
    import tempfile

    from ..models.zoo import model_by_name
    from .keras_import import import_keras_model_and_weights

    key = name.lower()
    if key not in KERAS_APPLICATIONS:
        raise ValueError(
            f"No keras.applications mapping for zoo model '{name}'; "
            f"available: {sorted(KERAS_APPLICATIONS)}")
    km = keras_model
    if km is None:
        import keras

        factory = getattr(keras.applications, KERAS_APPLICATIONS[key])
        km = factory(weights=weights, classes=classes)
    with tempfile.TemporaryDirectory() as d:
        h5 = str(Path(d) / f"{key}.h5")
        km.save(h5)
        net = import_keras_model_and_weights(h5)
    zoo = model_by_name(key)
    return zoo.save_pretrained(net, pretrained_type)
