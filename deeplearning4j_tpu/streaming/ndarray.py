"""NDArray pub/sub — ``streaming/kafka/NDArrayPublisher.java`` /
``NDArrayConsumer.java`` equivalents over a pluggable transport.

Frames are raw ``.npy`` bytes (dtype+shape self-describing), length-prefixed
on the wire. ``TCPTransport`` is the stdlib broker-less default; a Kafka
binding activates when ``kafka-python`` (or ``confluent_kafka``) is
importable — the hosting image does not bake a Kafka client, so that path is
gated, matching how the reference gates on a running broker.
"""

from __future__ import annotations

import io
import logging
import queue
import socket
import struct
import threading
from typing import Callable, List, Optional

import numpy as np

from ..obs.metrics import default_registry

logger = logging.getLogger("deeplearning4j_tpu.streaming")


def _encode(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    return buf.getvalue()


def _decode(data: bytes) -> np.ndarray:
    return np.load(io.BytesIO(data), allow_pickle=False)


def _default_on_error(e: Exception) -> None:
    """Default drop path: count it (process-global registry, so any server's
    /metrics surfaces it) and log it — a stream quietly losing frames is a
    production incident, not stderr noise."""
    default_registry().counter(
        "streaming_dropped_frames_total",
        help="frames dropped by NDArrayConsumer (decode or callback error)"
    ).inc()
    logger.warning("NDArrayConsumer: dropped frame/callback error: %r", e)


def kafka_available() -> bool:
    try:
        import kafka  # noqa: F401

        return True
    except ImportError:
        try:
            import confluent_kafka  # noqa: F401

            return True
        except ImportError:
            return False


class TCPTransport:
    """Broker-less transport: the consumer side listens, publishers connect
    and push length-prefixed frames. One transport == one 'topic'."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_queued: int = 1024):
        self.host = host
        self.port = port
        self._server: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        # bounded: a stalled consumer applies backpressure through TCP
        # instead of growing host memory without limit
        self._queue: "queue.Queue[bytes]" = queue.Queue(maxsize=max_queued)
        self._stop = threading.Event()

    # --- consumer side ---
    def listen(self) -> "TCPTransport":
        self._server = socket.create_server((self.host, self.port))
        self.port = self._server.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            t = threading.Thread(target=self._recv_loop, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _recv_loop(self, conn: socket.socket):
        with conn:
            while not self._stop.is_set():
                hdr = self._recv_exact(conn, 8)
                if hdr is None:
                    return
                (n,) = struct.unpack(">Q", hdr)
                data = self._recv_exact(conn, n)
                if data is None:
                    return
                self._queue.put(data)

    @staticmethod
    def _recv_exact(conn, n) -> Optional[bytes]:
        chunks = []
        while n > 0:
            try:
                c = conn.recv(min(n, 1 << 20))
            except OSError:
                return None
            if not c:
                return None
            chunks.append(c)
            n -= len(c)
        return b"".join(chunks)

    def receive(self, timeout: Optional[float] = None) -> bytes:
        return self._queue.get(timeout=timeout)

    # --- publisher side ---
    def connect(self) -> "TCPTransport":
        self._sock = socket.create_connection((self.host, self.port))
        return self

    def send(self, data: bytes) -> None:
        self._sock.sendall(struct.pack(">Q", len(data)) + data)

    def close(self):
        self._stop.set()
        if self._server:
            try:
                self._server.close()
            except OSError:
                pass
        if getattr(self, "_sock", None):
            try:
                self._sock.close()
            except OSError:
                pass


class NDArrayPublisher:
    """``NDArrayPublisher.java`` — publish(arr) pushes one array frame."""

    def __init__(self, transport: TCPTransport):
        self.transport = transport

    def publish(self, arr) -> None:
        self.transport.send(_encode(arr))

    def publish_batch(self, arrs) -> None:
        for a in arrs:
            self.publish(a)


class NDArrayConsumer:
    """``NDArrayConsumer.java`` — pull or callback-driven consumption."""

    def __init__(self, transport: TCPTransport):
        self.transport = transport
        self._cb_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def receive(self, timeout: Optional[float] = None) -> np.ndarray:
        return _decode(self.transport.receive(timeout=timeout))

    def start(self, on_array: Callable[[np.ndarray], None],
              on_error: Optional[Callable[[Exception], None]] = None
              ) -> "NDArrayConsumer":
        def loop():
            while not self._stop.is_set():
                try:
                    arr = self.receive(timeout=0.25)
                except queue.Empty:
                    continue
                except Exception as e:  # corrupt frame: report, keep consuming  # jaxlint: disable=broad-except
                    (on_error or _default_on_error)(e)
                    continue
                try:
                    on_array(arr)
                except Exception as e:  # callback bug must not kill the stream  # jaxlint: disable=broad-except
                    (on_error or _default_on_error)(e)
        self._cb_thread = threading.Thread(target=loop, daemon=True)
        self._cb_thread.start()
        return self

    def stop(self):
        self._stop.set()
