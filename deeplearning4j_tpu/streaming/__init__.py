"""Streaming ingest & serving — dl4j-streaming equivalent (SURVEY.md §2.4:
Kafka+Camel NDArray pub/sub + serving route).

The reference moves ND arrays over Kafka topics (``streaming/kafka/
NDArrayPublisher.java`` / ``NDArrayConsumer.java``) and exposes a Camel
serving route (``streaming/routes/DL4jServeRouteBuilder.java``). Here the
transport is a pluggable interface with a stdlib TCP implementation
(length-prefixed npy frames — no broker needed for host-to-host streams) and
an optional Kafka binding that activates when a kafka client library is
installed; the serving route is an HTTP inference endpoint over the shared
http.server scaffolding.
"""

from .ndarray import (NDArrayConsumer, NDArrayPublisher, TCPTransport,
                      kafka_available)
from .serve import InferenceRoute

__all__ = ["InferenceRoute", "NDArrayConsumer", "NDArrayPublisher",
           "TCPTransport", "kafka_available"]
