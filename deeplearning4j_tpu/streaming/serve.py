"""Model-serving route — ``streaming/routes/DL4jServeRouteBuilder.java``
equivalent: expose a trained model as an HTTP inference endpoint, optionally
backed by the dynamic-batching ``ParallelInference`` worker (SURVEY.md
§2.4.6).

Endpoints:
- POST /predict  {"ndarray": [[...]]}  → {"output": [[...]]}
- GET  /health
- GET  /metrics — Prometheus scrape (request latency histograms; see obs/)
"""

from __future__ import annotations

import json

import numpy as np

from ..obs.metrics import MetricsRegistry
from ..utils.httpd import JsonHTTPServerMixin, JsonRequestHandler


class InferenceRoute(JsonHTTPServerMixin):
    def __init__(self, model, params=None, state=None, port: int = 9010,
                 host: str = "127.0.0.1", use_parallel_inference: bool = False,
                 batch_limit: int = 32, metrics: MetricsRegistry = None):
        self.model = model
        self.params = params if params is not None else model.params
        self.state = state if state is not None else model.state
        self.port = port
        self.host = host
        # per-endpoint latency + GET /metrics, provided by the httpd layer
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._pi = None
        if use_parallel_inference:
            from ..parallel.inference import ParallelInference

            self._pi = ParallelInference(model, params=self.params,
                                         state=self.state,
                                         batch_limit=batch_limit)

    def _predict(self, x: np.ndarray) -> np.ndarray:
        if self._pi is not None:
            return np.asarray(self._pi.output(x))
        out = self.model.output(x, self.params, self.state)
        return np.asarray(out[0] if isinstance(out, list) else out)

    def _handler(self):
        server = self

        class Handler(JsonRequestHandler):
            owner = server

            def do_GET(self):
                if self.path == "/health":
                    self.reply(200, {"status": "ok",
                                     "model": type(server.model).__name__})
                else:
                    self.reply(404, {"error": "unknown endpoint"})

            def do_POST(self):
                try:
                    req = self.read_json()
                    if self.path == "/predict":
                        x = np.asarray(req["ndarray"], np.float32)
                        y = server._predict(x)
                        self.reply(200, {"output": y.tolist()})
                    else:
                        self.reply(404, {"error": "unknown endpoint"})
                except (KeyError, ValueError, TypeError, AttributeError,
                        json.JSONDecodeError) as e:
                    self.reply(400, {"error": str(e)})
                except Exception as e:  # server must answer every request  # jaxlint: disable=broad-except
                    self.reply(500, {"error": f"{type(e).__name__}: {e}"})

        return Handler

    def stop(self):
        super().stop()
        if self._pi is not None:
            self._pi.shutdown()
