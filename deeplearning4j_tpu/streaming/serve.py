"""Model-serving route — ``streaming/routes/DL4jServeRouteBuilder.java``
equivalent (compat shim): the original 80-line single-request route is now
a thin subclass of :class:`~deeplearning4j_tpu.serve.http.ModelServer`, so
the HTTP path gets micro-batching, deadlines, admission control, graceful
drain, and a ``/generate`` endpoint without any change to existing callers.

Endpoints (superset of the old surface):
- POST /predict  {"ndarray": [[...]]}  → {"output": [[...]]}
- POST /generate {"prompt": [...], "max_new_tokens": n} → {"tokens": [...]}
- GET  /health · GET /ready · GET /models
- GET  /metrics — Prometheus scrape (request latency histograms; see obs/)

``use_parallel_inference`` is kept for signature compatibility but is
vestigial: every request now flows through the serving engine's bucketed
batch path (with ``use_parallel_inference=False`` the engine still
coalesces; there is no longer an unbatched fast path to preserve, and the
outputs are identical).
"""

from __future__ import annotations

from ..obs.metrics import MetricsRegistry
from ..serve.http import ModelServer


class InferenceRoute(ModelServer):
    def __init__(self, model, params=None, state=None, port: int = 9010,
                 host: str = "127.0.0.1", use_parallel_inference: bool = False,
                 batch_limit: int = 32, metrics: MetricsRegistry = None):
        buckets = tuple(b for b in (1, 2, 4, 8, 16, 32) if b <= batch_limit) \
            or (batch_limit,)
        super().__init__(model, params=params, state=state, host=host,
                         port=port, batch_buckets=buckets,
                         queue_limit=max(64, 2 * batch_limit),
                         metrics=metrics)
        self.use_parallel_inference = use_parallel_inference
