"""Interprocedural exception-propagation model (jaxlint v5).

The serving contract says every failure that reaches a client is a typed
:class:`~deeplearning4j_tpu.serve.errors.ServeError` mapped to exactly one
HTTP status, counted on a ``{cause}`` label, and SSE-safe after the
streaming commit point. PR 16 found the contract broken at runtime — the
engine dispatcher silently wrapped typed ``AotTraceError``s into generic
500s — a bug shape no per-file rule can see. This module makes the
contract statically checkable: a per-function *raise-set* fixpoint over
the v2 ``Program`` call graph, in the style of the v3 lock model.

Per function the model computes ``escapes``: the set of exception classes
that may propagate out of it, each with a witness chain
("f calls g (line n); g raises ShedError (path:line)"). Direct ``raise``
sites seed the set; ``try/except`` ladders narrow it with subclass-aware
matching over a nominal exception-class table (program classes + the
builtin hierarchy + a few known externals such as
``json.JSONDecodeError``); call edges — resolved through
:mod:`.typeinfo` so ``self._pager.ensure(...)`` counts — propagate callee
escapes through the caller's enclosing handlers. Re-raise (bare
``raise``), ``raise e`` of the bound exception, and ``raise X from e``
wrap edges are modeled; ``raise`` of a value whose class is not
statically nameable (``raise self.error``) is *untracked* — the model
reports only provable escapes, never guesses. ``NotImplementedError``
and ``AssertionError`` raises are deliberately untracked too: they are
contract markers ("subclass must override", "cannot happen"), not
error-surface citizens.

On top of the fixpoint, :meth:`ErrorModel.boundary_flows` answers the
question the v5 rules and :mod:`.errorsurface` need: for an HTTP handler
entry (a ``do_*`` method), where does each reachable exception *land* —
a specific ``except`` clause (a deliberate status mapping), the generic
catch-all (an untyped 500), or nowhere (it escapes the boundary and the
client gets a reset instead of an answer)?

A function whose escape is a designed contract opts out per rule with a
sanction comment on its ``def`` line, same grammar as the lock model::

    def free(self, blocks):  # jaxlint: sanction=untyped-escape-to-http

Sanctions mute the named rule for findings whose witness chain starts or
ends at the sanctioned function; the model itself — and the committed
error-surface budget — always reflect the unsanctioned truth.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

from .typeinfo import dotted_expr, get_types

_ERRORS_CACHE = "errorflow:model"

_SANCTION_RE = re.compile(r"#\s*jaxlint:\s*sanction=([A-Za-z0-9_\-, ]+)")

#: chain length cap, matching the lock model's witness chains
_MAX_CHAIN = 6

#: raises of these are contract markers, not error-surface citizens
_UNTRACKED = {"NotImplementedError", "AssertionError"}

#: builtin exception -> immediate base (enough of the CPython hierarchy
#: for subclass-aware handler matching; no imports, ever)
BUILTIN_EXC_BASES: Dict[str, Optional[str]] = {
    "BaseException": None,
    "Exception": "BaseException",
    "KeyboardInterrupt": "BaseException",
    "SystemExit": "BaseException",
    "GeneratorExit": "BaseException",
    "ArithmeticError": "Exception",
    "ZeroDivisionError": "ArithmeticError",
    "OverflowError": "ArithmeticError",
    "FloatingPointError": "ArithmeticError",
    "AssertionError": "Exception",
    "AttributeError": "Exception",
    "BufferError": "Exception",
    "EOFError": "Exception",
    "ImportError": "Exception",
    "ModuleNotFoundError": "ImportError",
    "LookupError": "Exception",
    "IndexError": "LookupError",
    "KeyError": "LookupError",
    "MemoryError": "Exception",
    "NameError": "Exception",
    "UnboundLocalError": "NameError",
    "OSError": "Exception",
    "IOError": "OSError",
    "FileNotFoundError": "OSError",
    "FileExistsError": "OSError",
    "IsADirectoryError": "OSError",
    "NotADirectoryError": "OSError",
    "PermissionError": "OSError",
    "InterruptedError": "OSError",
    "TimeoutError": "OSError",
    "ConnectionError": "OSError",
    "BrokenPipeError": "ConnectionError",
    "ConnectionAbortedError": "ConnectionError",
    "ConnectionRefusedError": "ConnectionError",
    "ConnectionResetError": "ConnectionError",
    "ReferenceError": "Exception",
    "RuntimeError": "Exception",
    "NotImplementedError": "RuntimeError",
    "RecursionError": "RuntimeError",
    "StopIteration": "Exception",
    "StopAsyncIteration": "Exception",
    "SyntaxError": "Exception",
    "SystemError": "Exception",
    "TypeError": "Exception",
    "ValueError": "Exception",
    "UnicodeError": "ValueError",
}

#: known external exception classes -> base (dotted, alias-resolved)
EXTERNAL_EXC_BASES: Dict[str, str] = {
    "json.JSONDecodeError": "ValueError",
    "json.decoder.JSONDecodeError": "ValueError",
    "http.client.HTTPException": "Exception",
    "http.client.BadStatusLine": "http.client.HTTPException",
    "http.client.RemoteDisconnected": "ConnectionResetError",
    "socket.timeout": "TimeoutError",
    "socket.gaierror": "OSError",
    "queue.Empty": "Exception",
    "queue.Full": "Exception",
}

#: "the client is gone" family: nothing in-band can be said to them
CLIENT_GONE = ("ConnectionError", "BrokenPipeError", "ConnectionResetError",
               "ConnectionAbortedError")


def short(qual: str) -> str:
    """Last component of an exception qual, for human-facing messages."""
    return qual.rsplit(".", 1)[-1]


class Clause(NamedTuple):
    """One ``except`` clause: resolved type quals (None = bare except,
    '?' entries = unresolvable, treated as catch-all) + its AST node."""

    types: Optional[Tuple[str, ...]]
    node: ast.excepthandler

    @property
    def generic(self) -> bool:
        """Catches everything: bare ``except``, ``except Exception`` /
        ``BaseException``, or a clause type the model cannot resolve."""
        if self.types is None:
            return True
        return any(t in ("Exception", "BaseException", "?")
                   for t in self.types)


class Escape(NamedTuple):
    """One exception class escaping a function, with provenance."""

    chain: Tuple[str, ...]
    origin: object  # FuncInfo of the raise site


class Flow(NamedTuple):
    """One exception reaching a boundary function: where it lands."""

    qual: str
    escape: Escape
    clause: Optional[Clause]  # None -> escapes the boundary entirely
    fn: object  # the boundary FuncInfo


class ErrorModel:
    """Program-wide exception-flow facts. Build via :func:`get_error_model`."""

    def __init__(self, program):
        self.program = program
        self.types = get_types(program)
        #: program class qual -> tuple of resolved base quals
        self.class_bases: Dict[str, Tuple[str, ...]] = {}
        #: program class qual -> {attr: literal value} (class-body Assigns)
        self.class_attrs: Dict[str, Dict[str, object]] = {}
        #: module qual -> {NAME: tuple of exc quals} for module-level
        #: ``_BAD_REQUEST = (KeyError, ValueError, ...)`` constants
        self.module_exc_tuples: Dict[str, Dict[str, Tuple[str, ...]]] = {}
        #: FuncInfo -> rule names sanctioned on its def line
        self.sanctions: Dict[object, Set[str]] = {}
        #: FuncInfo -> escaping exception qual -> Escape
        self.escapes: Dict[object, Dict[str, Escape]] = {}
        self._events: Dict[object, list] = {}
        self._catch_cache: Dict[Tuple[Tuple[str, ...], str], bool] = {}
        self._families: Dict[object, Set[str]] = {}

        self._collect_classes()
        self._collect_module_tuples()
        self._collect_sanctions()
        self._all_funcs = sorted(
            (fi for mi in program.modules.values() for fi in mi.all_funcs),
            key=lambda fi: (fi.module.module, fi.qual, fi.node.lineno))
        #: quals of every class named ServeError / ShedError in the program
        self.serve_error_roots = frozenset(
            q for q in self.class_bases if short(q) == "ServeError")
        self.shed_error_roots = frozenset(
            q for q in self.class_bases if short(q) == "ShedError")
        self._fixpoint()

    # -- nominal exception table -----------------------------------------
    def _collect_classes(self):
        for mi in self.program.modules.values():
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                qual = f"{mi.module}.{node.name}"
                bases = []
                for b in node.bases:
                    q = self._resolve_class_name(mi, b)
                    if q:
                        bases.append(q)
                self.class_bases.setdefault(qual, tuple(bases))
                attrs: Dict[str, object] = {}
                for child in node.body:
                    if isinstance(child, ast.Assign) \
                            and len(child.targets) == 1 \
                            and isinstance(child.targets[0], ast.Name) \
                            and isinstance(child.value, ast.Constant):
                        attrs[child.targets[0].id] = child.value.value
                    elif isinstance(child, ast.AnnAssign) \
                            and isinstance(child.target, ast.Name) \
                            and isinstance(child.value, ast.Constant):
                        attrs[child.target.id] = child.value.value
                self.class_attrs.setdefault(qual, attrs)

    def _collect_module_tuples(self):
        for mi in self.program.modules.values():
            table: Dict[str, Tuple[str, ...]] = {}
            for stmt in mi.tree.body:
                if not (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)):
                    continue
                elts = stmt.value.elts \
                    if isinstance(stmt.value, ast.Tuple) else [stmt.value]
                quals = [self._resolve_class_name(mi, e) for e in elts]
                if quals and all(q and self._is_exceptionish(q)
                                 for q in quals):
                    table[stmt.targets[0].id] = tuple(quals)
            self.module_exc_tuples[mi.module] = table

    def _resolve_class_name(self, mi, expr: ast.AST) -> Optional[str]:
        """Exception class qual an expression names: a program class's
        ``<module>.<Class>``, a builtin exception name, or a known
        external's dotted path. None when not statically nameable."""
        d = dotted_expr(mi, expr)
        if d is None:
            return None
        q = self.types.resolve_class_dotted(mi, d)
        if q in self.class_bases:
            return q
        name = q or d
        if name.startswith("builtins."):
            name = name[len("builtins."):]
        if name in EXTERNAL_EXC_BASES:
            return name
        if name in BUILTIN_EXC_BASES:
            return name
        return None

    def _is_exceptionish(self, qual: str) -> bool:
        """Does the qual (transitively) derive from BaseException — or at
        least from nothing that disproves it? Program classes with fully
        unresolved bases count (single-file fixtures)."""
        seen: Set[str] = set()
        stack = [qual]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            if q in BUILTIN_EXC_BASES:
                return True
            if q in EXTERNAL_EXC_BASES:
                stack.append(EXTERNAL_EXC_BASES[q])
            stack.extend(self.class_bases.get(q, ()))
        return bool(self.class_bases.get(qual) is not None
                    and not self.class_bases.get(qual))

    def is_subtype(self, qual: str, base: str) -> bool:
        """Subclass-aware handler matching: would ``except <base>`` catch
        an instance of ``qual``?"""
        if base in ("BaseException", "?"):
            return True
        seen: Set[str] = set()
        stack = [qual]
        while stack:
            q = stack.pop()
            if q == base:
                return True
            if q in seen:
                continue
            seen.add(q)
            b = BUILTIN_EXC_BASES.get(q)
            if b:
                stack.append(b)
            b = EXTERNAL_EXC_BASES.get(q)
            if b:
                stack.append(b)
            stack.extend(self.class_bases.get(q, ()))
        return False

    def is_serve_error(self, qual: str) -> bool:
        return any(self.is_subtype(qual, r) for r in self.serve_error_roots)

    def is_shed_error(self, qual: str) -> bool:
        return any(self.is_subtype(qual, r) for r in self.shed_error_roots)

    def is_client_gone(self, qual: str) -> bool:
        return any(self.is_subtype(qual, b) for b in CLIENT_GONE)

    def class_attr(self, qual: str, name: str):
        """Class-body constant resolved through the base chain
        (``http_status`` / ``cause`` on the typed error hierarchy)."""
        seen: Set[str] = set()
        stack = [qual]
        while stack:
            q = stack.pop(0)
            if q in seen:
                continue
            seen.add(q)
            attrs = self.class_attrs.get(q)
            if attrs and name in attrs:
                return attrs[name]
            stack.extend(self.class_bases.get(q, ()))
        return None

    # -- sanctions --------------------------------------------------------
    def _collect_sanctions(self):
        for mi in self.program.modules.values():
            lines = mi.source.splitlines()
            for fi in mi.all_funcs:
                start = min([fi.node.lineno]
                            + [d.lineno for d in fi.node.decorator_list])
                rules: Set[str] = set()
                for ln in range(start, fi.node.lineno + 1):
                    if 0 < ln <= len(lines):
                        m = _SANCTION_RE.search(lines[ln - 1])
                        if m:
                            rules.update(r.strip()
                                         for r in m.group(1).split(",")
                                         if r.strip())
                if rules:
                    self.sanctions[fi] = rules

    def sanctioned(self, fi, rule: str) -> bool:
        return rule in self.sanctions.get(fi, ())

    def flow_sanctioned(self, flow_or_escape, boundary_fi, rule: str) -> bool:
        """A finding is muted when either end of its witness chain — the
        boundary/raising function or the origin of the raise — carries the
        rule's sanction."""
        esc = flow_or_escape.escape \
            if isinstance(flow_or_escape, Flow) else flow_or_escape
        return (self.sanctioned(boundary_fi, rule)
                or self.sanctioned(esc.origin, rule))

    # -- per-function event streams ---------------------------------------
    def clause_types(self, mi, handler: ast.excepthandler
                     ) -> Optional[Tuple[str, ...]]:
        """Resolved type quals one ``except`` clause catches. None = bare
        ``except:``; unresolvable entries become '?' (treated catch-all —
        the model never claims an escape it cannot prove)."""
        t = handler.type
        if t is None:
            return None
        exprs = list(t.elts) if isinstance(t, ast.Tuple) else [t]
        out: List[str] = []
        for e in exprs:
            q = self._resolve_class_name(mi, e)
            if q is not None:
                out.append(q)
                continue
            quals = self._exc_tuple(mi, e)
            if quals:
                out.extend(quals)
            else:
                out.append("?")
        return tuple(out)

    def _exc_tuple(self, mi, expr: ast.AST) -> Optional[Tuple[str, ...]]:
        """Resolve a Name/Attribute naming a module-level tuple constant
        of exception classes (the ``_BAD_REQUEST`` idiom)."""
        d = dotted_expr(mi, expr)
        if d is None:
            return None
        head, _, name = d.rpartition(".")
        if not head:
            return self.module_exc_tuples.get(mi.module, {}).get(d)
        mod = self.program.lookup_module(head)
        if mod is None:
            return None
        return self.module_exc_tuples.get(mod.module, {}).get(name)

    def events(self, fi) -> list:
        """Structural event stream for ``fi``:

        - ``("raise", (quals,), node, frames)`` — a ``raise`` whose
          exception class(es) are statically nameable;
        - ``("call", node, callee, frames)`` — a resolvable call.

        ``frames`` is the tuple of enclosing try-ladders (outermost
        first), each a tuple of :class:`Clause`. Handler bodies run under
        the *outer* frames (their own try no longer catches); bare
        ``raise`` re-raises the handling clause's types; ``raise e`` of
        the bound name resolves to the clause's types."""
        cached = self._events.get(fi)
        if cached is not None:
            return cached
        mi = fi.module
        out: list = []

        def expr_calls(e: Optional[ast.AST], frames):
            if e is None:
                return
            for n in ast.walk(e):
                if isinstance(n, ast.Call):
                    callee = self.types.method_callee(fi, n)
                    if callee is not None and callee is not fi:
                        out.append(("call", n, callee, frames))

        def isinstance_narrow(test, bindings):
            """``if isinstance(e, (A, B)): raise`` — the guarded branch
            narrows the bound exception's types (the router's
            client-gone re-raise idiom)."""
            if isinstance(test, ast.Call) \
                    and isinstance(test.func, ast.Name) \
                    and test.func.id == "isinstance" \
                    and len(test.args) == 2 \
                    and isinstance(test.args[0], ast.Name) \
                    and test.args[0].id in bindings:
                t = test.args[1]
                exprs = list(t.elts) if isinstance(t, ast.Tuple) else [t]
                quals = [self._resolve_class_name(mi, e) for e in exprs]
                if quals and all(quals):
                    return test.args[0].id, tuple(quals)
            return None

        def do_raise(st: ast.Raise, frames, bindings, clause_ctx):
            if st.exc is None:
                quals = clause_ctx or ()
            else:
                target = st.exc.func if isinstance(st.exc, ast.Call) \
                    else st.exc
                # nested calls building the message still run
                if isinstance(st.exc, ast.Call):
                    for a in list(st.exc.args) + [k.value for k
                                                  in st.exc.keywords]:
                        expr_calls(a, frames)
                if isinstance(target, ast.Name) and target.id in bindings:
                    quals = bindings[target.id]
                else:
                    q = self._resolve_class_name(mi, target)
                    quals = (q,) if q else ()
            quals = tuple(q for q in quals
                          if q not in _UNTRACKED and q != "?")
            if quals:
                out.append(("raise", quals, st, frames))

        def walk(stmts, frames, bindings, clause_ctx):
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue  # separate scope
                if isinstance(st, ast.Raise):
                    do_raise(st, frames, bindings, clause_ctx)
                elif isinstance(st, ast.Try):
                    frame = tuple(Clause(self.clause_types(mi, h), h)
                                  for h in st.handlers)
                    walk(st.body, frames + (frame,), bindings, clause_ctx)
                    for clause in frame:
                        b2 = bindings
                        if clause.node.name:
                            b2 = dict(bindings)
                            b2[clause.node.name] = \
                                clause.types or ("Exception",)
                        walk(clause.node.body, frames, b2,
                             clause.types or ("Exception",))
                    # orelse/finally exceptions are NOT caught by this try
                    walk(st.orelse, frames, bindings, clause_ctx)
                    walk(st.finalbody, frames, bindings, clause_ctx)
                elif isinstance(st, (ast.With, ast.AsyncWith)):
                    for item in st.items:
                        expr_calls(item.context_expr, frames)
                    walk(st.body, frames, bindings, clause_ctx)
                elif isinstance(st, ast.If):
                    expr_calls(st.test, frames)
                    narrowed = isinstance_narrow(st.test, bindings)
                    if narrowed is not None:
                        name, quals = narrowed
                        b2 = dict(bindings)
                        cc2 = quals if bindings.get(name) == clause_ctx \
                            else clause_ctx
                        b2[name] = quals
                        walk(st.body, frames, b2, cc2)
                    else:
                        walk(st.body, frames, bindings, clause_ctx)
                    walk(st.orelse, frames, bindings, clause_ctx)
                elif isinstance(st, (ast.For, ast.AsyncFor)):
                    expr_calls(st.iter, frames)
                    walk(st.body, frames, bindings, clause_ctx)
                    walk(st.orelse, frames, bindings, clause_ctx)
                elif isinstance(st, ast.While):
                    expr_calls(st.test, frames)
                    walk(st.body, frames, bindings, clause_ctx)
                    walk(st.orelse, frames, bindings, clause_ctx)
                else:
                    for e in ast.iter_child_nodes(st):
                        if isinstance(e, ast.expr):
                            expr_calls(e, frames)

        walk(fi.node.body, (), {}, None)
        self._events[fi] = out
        return out

    # -- escape fixpoint ---------------------------------------------------
    def _catches(self, clause: Clause, qual: str) -> bool:
        if clause.types is None:
            return True
        key = (clause.types, qual)
        hit = self._catch_cache.get(key)
        if hit is None:
            hit = any(self.is_subtype(qual, t) for t in clause.types)
            self._catch_cache[key] = hit
        return hit

    def land(self, qual: str, frames) -> Optional[Clause]:
        """First clause that catches ``qual`` (innermost try first, clause
        order within a ladder respected). None = escapes every frame."""
        for frame in reversed(frames):
            for clause in frame:
                if self._catches(clause, qual):
                    return clause
        return None

    def _escapes_once(self, fi) -> Dict[str, Escape]:
        mi = fi.module
        out: Dict[str, Escape] = {}
        for ev in self.events(fi):
            if ev[0] == "raise":
                _, quals, node, frames = ev
                for q in quals:
                    if self.land(q, frames) is None:
                        out.setdefault(q, Escape(
                            (f"{fi.qual} raises {short(q)} "
                             f"({mi.path}:{node.lineno})",), fi))
            else:
                _, node, callee, frames = ev
                for q, esc in self.escapes.get(callee, {}).items():
                    if len(esc.chain) >= _MAX_CHAIN:
                        continue
                    if self.land(q, frames) is None:
                        out.setdefault(q, Escape(
                            (f"{fi.qual} calls {callee.qual} "
                             f"(line {node.lineno})",) + esc.chain,
                            esc.origin))
        return out

    def _fixpoint(self):
        for fi in self._all_funcs:
            self.escapes[fi] = {}
        changed = True
        while changed:
            changed = False
            for fi in self._all_funcs:
                new = self._escapes_once(fi)
                if set(new) != set(self.escapes[fi]):
                    self.escapes[fi] = new
                    changed = True

    # -- boundary queries --------------------------------------------------
    def boundaries(self) -> List[object]:
        """Every HTTP handler entry: a ``do_*`` method of any class."""
        return [fi for fi in self._all_funcs
                if fi.cls and fi.name.startswith("do_")]

    def boundary_flows(self, fi) -> List[Flow]:
        """Every tracked exception reaching boundary ``fi``, with the
        clause it lands in (None = escapes the boundary)."""
        mi = fi.module
        flows: Dict[str, Flow] = {}
        for ev in self.events(fi):
            if ev[0] == "raise":
                _, quals, node, frames = ev
                for q in quals:
                    if q in flows:
                        continue
                    esc = Escape((f"{fi.qual} raises {short(q)} "
                                  f"({mi.path}:{node.lineno})",), fi)
                    flows[q] = Flow(q, esc, self.land(q, frames), fi)
            else:
                _, node, callee, frames = ev
                for q, esc in self.escapes.get(callee, {}).items():
                    if q in flows or len(esc.chain) >= _MAX_CHAIN:
                        continue
                    chain = (f"{fi.qual} calls {callee.qual} "
                             f"(line {node.lineno})",) + esc.chain
                    flows[q] = Flow(q, Escape(chain, esc.origin),
                                    self.land(q, frames), fi)
        return [flows[q] for q in sorted(flows)]

    def clause_arrivals(self, fi) -> List[Tuple[Clause, str, Escape]]:
        """(clause, exception qual, escape) for every tracked exception
        that lands in an ``except`` clause *inside* ``fi`` — the swallow
        rule's input."""
        mi = fi.module
        out: List[Tuple[Clause, str, Escape]] = []
        seen: Set[Tuple[int, str]] = set()
        for ev in self.events(fi):
            if ev[0] == "raise":
                _, quals, node, frames = ev
                pairs = [(q, Escape((f"{fi.qual} raises {short(q)} "
                                     f"({mi.path}:{node.lineno})",), fi))
                         for q in quals]
            else:
                _, node, callee, frames = ev
                pairs = [(q, Escape((f"{fi.qual} calls {callee.qual} "
                                     f"(line {node.lineno})",) + esc.chain,
                                    esc.origin))
                         for q, esc in self.escapes.get(callee, {}).items()
                         if len(esc.chain) < _MAX_CHAIN]
            for q, esc in pairs:
                clause = self.land(q, frames)
                if clause is None:
                    continue
                key = (id(clause.node), q)
                if key not in seen:
                    seen.add(key)
                    out.append((clause, q, esc))
        return out

    # -- clause/function helpers for rules & the surface -------------------
    def commit_line(self, fi) -> Optional[int]:
        """Line of the SSE streaming commit point — the first
        ``<receiver>.send_response(200)`` call — or None."""
        best: Optional[int] = None
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "send_response" \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and node.args[0].value == 200:
                if best is None or node.lineno < best:
                    best = node.lineno
        return best

    def metric_families(self, fi, hops: int = 1) -> Set[str]:
        """Metric family literals a function touches —
        ``*.counter("family", ...)`` calls — following resolvable call
        edges ``hops`` levels deep (counters often live one helper away:
        ``self._err(...)`` / ``route_err(...)``)."""
        fams = self._families.get(fi)
        if fams is None:
            fams = set()
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("counter", "histogram",
                                               "gauge") \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    fams.add(node.args[0].value)
            self._families[fi] = fams
        if hops <= 0:
            return fams
        out = set(fams)
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                callee = self.types.method_callee(fi, node)
                if callee is not None and callee is not fi:
                    out |= self.metric_families(callee, hops - 1)
        return out

    def node_metric_families(self, fi, root: ast.AST) -> Set[str]:
        """Metric families touched within one subtree (an ``except``
        clause body), resolving one helper hop."""
        out: Set[str] = set()
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("counter", "histogram", "gauge") \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                out.add(node.args[0].value)
                continue
            callee = self.types.method_callee(fi, node)
            if callee is not None and callee is not fi:
                out |= self.metric_families(callee, hops=0)
        return out

    def clause_statuses(self, fi, clause: Clause) -> Set[object]:
        """Literal HTTP statuses a clause body answers with (first int
        argument of reply/_err/route_err/send_error/send_response), plus
        the marker ``"dynamic"`` when it defers to ``e.http_status``."""
        out: Set[object] = set()
        for node in ast.walk(clause.node):
            if not isinstance(node, ast.Call):
                continue
            name = node.func.attr if isinstance(node.func, ast.Attribute) \
                else (node.func.id if isinstance(node.func, ast.Name)
                      else None)
            if name not in ("reply", "_err", "route_err", "send_error",
                            "send_response", "err"):
                continue
            if not node.args:
                continue
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, int):
                out.add(a0.value)
            elif isinstance(a0, ast.Attribute) \
                    and a0.attr == "http_status":
                out.add("dynamic")
        return out

    def clause_retry_after(self, fi, clause: Clause) -> bool:
        """Does the clause body witness a Retry-After header — the string
        literal or one of the jitter helpers?"""
        for node in ast.walk(clause.node):
            if isinstance(node, ast.Constant) \
                    and node.value == "Retry-After":
                return True
            if isinstance(node, ast.Call):
                name = node.func.attr \
                    if isinstance(node.func, ast.Attribute) \
                    else (node.func.id if isinstance(node.func, ast.Name)
                          else None)
                if name in ("jitter_retry_after", "retry_after_s",
                            "_retry_after"):
                    return True
        return False


def get_error_model(program) -> ErrorModel:
    m = program.cache.get(_ERRORS_CACHE)
    if m is None:
        m = ErrorModel(program)
        program.cache[_ERRORS_CACHE] = m
    return m
