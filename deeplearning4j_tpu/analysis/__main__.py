"""CLI: ``python -m deeplearning4j_tpu.analysis <paths> [--json] [--select ...]``.

Exit status: 0 when clean, 1 when any finding survives suppression, 2 on
usage errors — so CI can gate on it directly (scripts/ci.sh).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .engine import analyze_paths, render_json, render_text
from .rules import ALL_RULES, rules_by_name


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis",
        description="jaxlint: JAX/TPU-correctness static analysis")
    ap.add_argument("paths", nargs="*", help=".py files or directories")
    ap.add_argument("--json", action="store_true", help="machine-readable report")
    ap.add_argument("--select", metavar="RULES",
                    help="comma-separated rule names to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.name:20s} {r.description}")
        return 0
    if not args.paths:
        ap.error("no paths given (try: deeplearning4j_tpu/)")

    rules = ALL_RULES
    if args.select:
        table = rules_by_name()
        names = [n.strip() for n in args.select.split(",") if n.strip()]
        unknown = [n for n in names if n not in table]
        if unknown:
            ap.error(f"unknown rule(s) {unknown}; known: {sorted(table)}")
        rules = [table[n] for n in names]

    findings = analyze_paths(args.paths, rules)
    print(render_json(findings) if args.json else render_text(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
