"""CLI: ``python -m deeplearning4j_tpu.analysis <paths> [options]``.

Options: ``--json`` (machine-readable report), ``--sarif FILE`` (SARIF 2.1.0
for GitHub code scanning), ``--baseline FILE`` (record-then-ratchet: first
run writes the current findings, later runs fail only on *new* ones),
``--select RULES``, ``--exclude GLOB`` (adds to the default excludes:
``tests``, ``__pycache__``), ``--list-rules``.

Exit status: 0 when clean (or no finding is new vs. the baseline), 1 when
any new finding survives suppression, 2 on usage errors — so CI can gate on
it directly (scripts/ci.sh).

Compile-surface mode (v4): ``--compile-surface FILE`` skips the rule
pass and instead writes the static executable-cardinality report (one
entry per jit site, see :mod:`.compilesurface`) to FILE; with
``--budget FILE`` the report is checked against the committed budget
and any regression exits 1.

Enumeration mode (the prebuild bridge): adding ``--enumerate-manifest
OUT --serve-config CONFIG`` to a ``--compile-surface --budget`` run
expands every budgeted site's symbolic bound against CONFIG's concrete
bucket tables (see :mod:`.enumerate`) and writes the
``prebuild_manifest.json`` that ``python -m deeplearning4j_tpu.aot
prebuild --from-surface`` compiles into the store.

Error-surface mode (v5): ``--error-surface FILE`` writes the static
per-endpoint error report (exception -> status/Retry-After/counter per
``do_*`` boundary, see :mod:`.errorsurface`) to FILE; with
``--error-budget FILE`` the report is checked against the committed
budget and any new untyped escape, mapping drift, or stale endpoint
exits 1.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .engine import analyze_paths, render_json, render_text
from .rules import ALL_RULES, rules_by_name
from .sarif import load_baseline, new_findings, render_sarif, write_baseline

#: always-on walk excludes; --exclude adds to these
DEFAULT_EXCLUDES = ["tests", "__pycache__"]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis",
        description="jaxlint: JAX/TPU-correctness static analysis "
                    "(whole-program since v2)")
    ap.add_argument("paths", nargs="*", help=".py files or directories")
    ap.add_argument("--json", action="store_true", help="machine-readable report")
    ap.add_argument("--sarif", metavar="FILE",
                    help="also write a SARIF 2.1.0 report to FILE")
    ap.add_argument("--baseline", metavar="FILE",
                    help="missing FILE: record current findings and exit 0; "
                         "existing FILE: fail only on findings not recorded")
    ap.add_argument("--select", metavar="RULES",
                    help="comma-separated rule names to run (default: all)")
    ap.add_argument("--exclude", metavar="GLOB", action="append", default=[],
                    help="glob matched against paths or single components; "
                         "repeatable; adds to defaults "
                         f"({', '.join(DEFAULT_EXCLUDES)})")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--compile-surface", metavar="FILE",
                    help="write the static compile-surface report "
                         "(executable-cardinality bound per jit site) to "
                         "FILE instead of running rules")
    ap.add_argument("--budget", metavar="FILE",
                    help="with --compile-surface: check the report "
                         "against this committed budget; regressions "
                         "exit 1")
    ap.add_argument("--enumerate-manifest", metavar="FILE",
                    help="with --compile-surface and --budget: expand the "
                         "budgeted bounds against --serve-config's bucket "
                         "tables and write the prebuild manifest to FILE")
    ap.add_argument("--serve-config", metavar="FILE",
                    help="concrete serving config (engine/gen knob groups) "
                         "the enumeration resolves bucket tables from")
    ap.add_argument("--error-surface", metavar="FILE",
                    help="write the static per-endpoint error-surface "
                         "report (exception -> status/Retry-After/counter "
                         "per do_* boundary) to FILE instead of running "
                         "rules")
    ap.add_argument("--error-budget", metavar="FILE",
                    help="with --error-surface: check the report against "
                         "this committed budget; any untyped escape, "
                         "mapping drift or stale endpoint exits 1")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.name:22s} {r.description}")
        return 0
    if not args.paths:
        ap.error("no paths given (try: deeplearning4j_tpu/)")

    if args.budget and not args.compile_surface:
        ap.error("--budget requires --compile-surface")
    if args.error_budget and not args.error_surface:
        ap.error("--error-budget requires --error-surface")
    if args.error_surface:
        import json as _json

        from .errorsurface import check_budget as _eb_check
        from .errorsurface import load_budget as _eb_load
        from .errorsurface import run as _es_run

        exclude = DEFAULT_EXCLUDES + args.exclude
        report, _ = _es_run(args.paths, exclude=exclude)
        with open(args.error_surface, "w", encoding="utf-8") as fh:
            _json.dump(report, fh, indent=2)
            fh.write("\n")
        n = len(report["endpoints"])
        e = sum(len(ep["errors"]) for ep in report["endpoints"])
        print(f"jaxlint: error surface — {n} endpoint(s), {e} "
              f"(endpoint, exception) pair(s) -> {args.error_surface}")
        if args.error_budget:
            try:
                budget = _eb_load(args.error_budget)
            except (ValueError, OSError) as e:
                ap.error(f"cannot read error budget "
                         f"{args.error_budget}: {e}")
            violations = _eb_check(report, budget)
            for v in violations:
                print(f"error-budget: {v}")
            if violations:
                print(f"{len(violations)} budget violation(s)")
                return 1
            print("error budget: ok")
        return 0
    if args.enumerate_manifest and not (args.budget and args.serve_config):
        ap.error("--enumerate-manifest requires --compile-surface, "
                 "--budget and --serve-config")
    if args.compile_surface:
        import json as _json

        from .compilesurface import check_budget, load_budget, run

        exclude = DEFAULT_EXCLUDES + args.exclude
        report, _ = run(args.paths, exclude=exclude)
        with open(args.compile_surface, "w", encoding="utf-8") as fh:
            _json.dump(report, fh, indent=2)
            fh.write("\n")
        n = len(report["sites"])
        print(f"jaxlint: compile surface — {n} jit site(s) "
              f"-> {args.compile_surface}")
        if args.budget:
            try:
                budget = load_budget(args.budget)
            except (ValueError, OSError) as e:
                ap.error(f"cannot read budget {args.budget}: {e}")
            violations = check_budget(report, budget)
            for v in violations:
                print(f"compile-budget: {v}")
            if violations:
                print(f"{len(violations)} budget violation(s)")
                return 1
            print("compile budget: ok")
            if args.enumerate_manifest:
                from .enumerate import (enumerate_surface,
                                        load_serve_config, write_manifest)

                try:
                    config = load_serve_config(args.serve_config)
                except (ValueError, OSError) as e:
                    ap.error(f"cannot read serve config "
                             f"{args.serve_config}: {e}")
                try:
                    manifest = enumerate_surface(report, budget, config)
                except ValueError as e:
                    print(f"enumerate: {e}")
                    return 1
                write_manifest(manifest, args.enumerate_manifest)
                print(f"jaxlint: enumerate — "
                      f"{len(manifest['sites'])} site(s), "
                      f"{manifest['total_signatures']} signature(s), "
                      f"hash {manifest['hash']} "
                      f"-> {args.enumerate_manifest}")
        return 0

    rules = ALL_RULES
    if args.select:
        table = rules_by_name()
        names = [n.strip() for n in args.select.split(",") if n.strip()]
        unknown = [n for n in names if n not in table]
        if unknown:
            ap.error(f"unknown rule(s) {unknown}; known: {sorted(table)}")
        rules = [table[n] for n in names]

    exclude = DEFAULT_EXCLUDES + args.exclude
    findings = analyze_paths(args.paths, rules, exclude=exclude)

    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as fh:
            fh.write(render_sarif(findings) + "\n")

    if args.baseline:
        if not os.path.exists(args.baseline):
            write_baseline(args.baseline, findings)
            print(f"jaxlint: baseline recorded ({len(findings)} finding(s) "
                  f"-> {args.baseline})")
            return 0
        try:
            baseline = load_baseline(args.baseline)
        except (ValueError, OSError, KeyError) as e:
            ap.error(f"cannot read baseline {args.baseline}: {e}")
        fresh = new_findings(findings, baseline)
        known = len(findings) - len(fresh)
        print(render_json(fresh) if args.json else render_text(fresh))
        if known:
            print(f"({known} baselined finding(s) suppressed)",
                  file=sys.stderr)
        return 1 if fresh else 0

    print(render_json(findings) if args.json else render_text(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
