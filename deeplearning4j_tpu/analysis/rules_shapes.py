"""jaxlint v4 rules — shape/dtype interpreter + compile-surface family.

These rules ride the abstract interpreter (:mod:`.shapes`) and the
compile-surface model (:mod:`.compilesurface`). All of them are
*provable-only*: they fire when the interpreter can prove the hazard
from literals, config knobs, bucket tables, and request-payload
provenance — never on mere uncertainty — so the serving tree stays at
zero findings with no baseline.

Why these patterns hurt on TPU: every distinct traced signature is a
full XLA compile (seconds to minutes) and a new executable in HBM. A
dimension that tracks request payload turns the compile cache into an
unbounded leak and the p99 into a compile queue; a Python scalar whose
weak dtype flips between calls silently doubles the executable set; a
donated buffer whose shape drifts between calls aliases freed memory.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Tuple

from . import compilesurface as CS
from . import shapes as S
from .engine import FileContext, Finding, Rule
from .rules import register

_FLOATS = ("float", "f16", "bf16", "f32", "f64")
_INTS = ("int", "i8", "i16", "i32", "i64", "u8", "u16", "u32", "u64")


def _dt_kind(dt: str) -> str:
    if dt in _FLOATS:
        return "float"
    if dt in _INTS:
        return "int"
    return "?"


def _fis_in_file(ctx: FileContext):
    """Every FuncInfo defined in this file, deduped."""
    seen = set()
    for fi in ctx.module_info.functions.values():
        if id(fi) not in seen:
            seen.add(id(fi))
            yield fi


def _surface(ctx: FileContext) -> List[CS.JitSite]:
    return CS.compute_surface(ctx.program)


@register
class ShapeMismatchRule(Rule):
    """Provable shape errors at jnp call sites.

    A broadcast of two literal dims that are unequal (and neither 1), a
    matmul whose contraction dims provably differ, or a concatenate
    whose non-concat dims provably differ will raise at trace time — in
    serving, that trace happens on the first unlucky request, inside
    the tick thread, long after CI went green. The interpreter proves
    these from literal shapes and reports the inferred operand shapes.
    """

    name = "shape-mismatch"
    description = ("provable broadcast/matmul/concat shape error, with "
                   "the inferred shapes in the message")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fi in _fis_in_file(ctx):
            fs = S.function_shapes(ctx.program, fi)
            for node, kind, msg in fs.issues:
                yield self.finding(ctx, node, f"{msg} (in {fi.qual})")


@register
class UnboundedCompileSignatureRule(Rule):
    """Request-derived dimension reaches a jit boundary.

    A traced argument whose dim provably tracks request payload —
    ``len()`` of a runtime list, a ``json.loads``/``os.environ`` read,
    boolean-mask indexing — keys a fresh XLA compile per distinct
    value: the recompile storm the bucket tables exist to prevent. The
    fix is to pad to a bucket (``engine.py``/``continuous.py`` idiom)
    before the jit call, or teach the interpreter the bound with a
    ``# jaxlint: dim=`` annotation when the bucketing is real but
    invisible.
    """

    name = "unbounded-compile-signature"
    description = ("traced argument reaches a jit call with a "
                   "request-derived (unbounded) dimension")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for site in _surface(ctx):
            for cs in site.callsites:
                if cs.mi is not ctx.module_info or not cs.unbounded_traced:
                    continue
                dims = ", ".join(cs.unbounded_traced)
                yield self.finding(
                    ctx, cs.call,
                    f"call into jit site {site.site_id} traces "
                    f"request-derived dimension(s): {dims} — every "
                    "distinct value compiles a new executable; pad to a "
                    "bucket table first")


@register
class StaticArgnumUnboundedRule(Rule):
    """static_argnums fed a request-derived value.

    ``static_argnums`` keys the compile cache on the argument's
    *value*, not its shape — feeding it anything request-derived is an
    unbounded executable set with no padding escape at all. Static
    arguments must come from config knobs or bucket tables.
    """

    name = "static-argnum-unbounded"
    description = ("static_argnums position fed a request-derived value "
                   "— each distinct value is a silent recompile")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for site in _surface(ctx):
            for cs in site.callsites:
                if cs.mi is not ctx.module_info or not cs.unbounded_static:
                    continue
                vals = ", ".join(cs.unbounded_static)
                yield self.finding(
                    ctx, cs.call,
                    f"jit site {site.site_id} keys its compile cache on "
                    f"the VALUE of static argument(s) {vals}; route the "
                    "value through a config knob or bucket table")


@register
class WeakTypePromotionRule(Rule):
    """Python-scalar weak-type mixing that flips a traced dtype.

    Python scalars trace as weak-typed 0-d arrays: the signature keys
    on dtype, not value, so a scalar that is sometimes ``int`` and
    sometimes ``float`` (or whose dtype follows the request payload)
    silently doubles the executable set and can flip downstream
    promotion from f32 to f64. Cast at the boundary
    (``np.float32(x)``) so the traced dtype is pinned.
    """

    name = "weak-type-promotion"
    description = ("weak Python scalar whose dtype can flip between jit "
                   "calls (int vs float, or payload-derived)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for site in _surface(ctx):
            # (a) payload-derived weak scalar: dtype follows the request
            for cs in site.callsites:
                if cs.mi is not ctx.module_info:
                    continue
                for row in cs.args:
                    if row.get("kind") == "scalar" and row.get("weak") \
                            and str(row.get("value", "")).startswith("unbounded"):
                        yield self.finding(
                            ctx, cs.call,
                            f"weak scalar {row['param']} passed to jit "
                            f"site {site.site_id} is request-derived "
                            f"({row['value']}): its traced dtype follows "
                            "the payload — pin it with an explicit "
                            "np.int32/np.float32 cast")
            # (b) the same param is weak-int at one call site and
            # weak-float at another: two executables where one was meant
            kinds: Dict[str, List[Tuple[str, CS.CallSite]]] = {}
            for cs in site.callsites:
                for row in cs.args:
                    if row.get("kind") == "scalar" and row.get("weak"):
                        k = _dt_kind(str(row.get("dtype", "?")))
                        if k != "?":
                            kinds.setdefault(row["param"], []).append((k, cs))
            for param, seen in kinds.items():
                if len({k for k, _ in seen}) < 2:
                    continue
                for k, cs in seen:
                    if cs.mi is ctx.module_info:
                        yield self.finding(
                            ctx, cs.call,
                            f"weak scalar {param} of jit site "
                            f"{site.site_id} is traced as {k} here but as "
                            "a different scalar kind at another call site "
                            "— the dtype flip keys a second executable; "
                            "pin the dtype at every call site")
                        break


@register
class DonatedShapeDriftRule(Rule):
    """Donated buffer whose shape is not call-invariant.

    ``donate_argnums`` lets XLA reuse the argument's buffer for the
    output — sound only while every call donates the same shape. A
    donated arg with a request-derived dim, or donated with two
    provably different literal shapes from different call sites, is the
    exact setup for aliasing a freed buffer (and for a recompile that
    silently un-donates). Donated buffers must be boot-sized.
    """

    name = "donated-shape-drift"
    description = ("donate_argnums argument whose shape provably varies "
                   "across calls (or tracks request payload)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for site in _surface(ctx):
            if not site.donate_idx:
                continue
            for p in sorted(site.donate_idx):
                pname = site.param_name(p)
                lits: List[Tuple[Tuple[str, ...], CS.CallSite]] = []
                for cs in site.callsites:
                    row = cs.args[p] if p < len(cs.args) else None
                    if row is None or row.get("param") != pname:
                        continue
                    shape = row.get("shape")
                    if shape is None:
                        continue
                    if any(d.startswith("unbounded") for d in shape):
                        if cs.mi is ctx.module_info:
                            yield self.finding(
                                ctx, cs.call,
                                f"donated argument {pname} of jit site "
                                f"{site.site_id} has request-derived "
                                f"shape ({', '.join(shape)}) — donation "
                                "requires a call-invariant, boot-sized "
                                "buffer")
                        continue
                    if all(d.isdigit() for d in shape):
                        lits.append((tuple(shape), cs))
                distinct = {sh for sh, _ in lits}
                if len(distinct) > 1:
                    for sh, cs in lits:
                        if cs.mi is ctx.module_info:
                            yield self.finding(
                                ctx, cs.call,
                                f"donated argument {pname} of jit site "
                                f"{site.site_id} is donated with shape "
                                f"({', '.join(sh)}) here but other call "
                                f"sites donate "
                                f"{sorted('(%s)' % ', '.join(s) for s in distinct - {sh})}"
                                " — shape drift across donations aliases "
                                "a freed buffer")
                            break
