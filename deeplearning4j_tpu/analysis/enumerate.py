"""Enumeration pass: compile-surface bounds -> concrete prebuild manifest.

The compile-surface pass (:mod:`.compilesurface`) proves each jit site's
executable cardinality as a *symbolic* product over bucket tables
(``|prompt_buckets|``, ``|batch_buckets|*|length_buckets|``, …). This
pass closes the loop to deployment: given one concrete serving config
(the same knobs a replica boots with), it resolves every symbolic factor
to its actual bucket table and expands each budgeted site into the
explicit list of ``(site, bucket-signature)`` pairs — the machine-readable
``prebuild_manifest.json`` that ``python -m deeplearning4j_tpu.aot
prebuild --from-surface`` compiles into the store and strict-mode replicas
verify against at boot.

Like the rest of ``analysis/``, this module is pure stdlib — it never
imports jax, numpy, or the serving code. The bucket-table derivations
(default prompt buckets, chunk buckets) are therefore *replicated* here
from ``serve/continuous.py``; ``tests/test_prebuild.py`` holds the two
implementations bit-identical so the manifest can never drift from what a
booted batcher actually warms.

Site -> AOT tag mapping lives in :data:`SITE_TAGS`: a budgeted serving
site the table does not name fails enumeration loudly (the manifest would
otherwise silently under-cover the surface), while non-serving sites
(training-side ``?`` bounds, helper jits with no store tag) are listed
under ``excluded`` with a reason, for human review.
"""

from __future__ import annotations

import hashlib
import itertools
import json
from typing import Dict, List, Optional, Tuple

from .compilesurface import _parse_bound

MANIFEST_VERSION = 1

#: site id -> (AotFunction tag, gate). The gate names which boot paths
#: build the executable: ``engine`` (always), ``gen`` (any batcher),
#: ``paged`` / ``dense`` (only that KV mode's batcher).
SITE_TAGS: Dict[str, Tuple[str, str]] = {
    "deeplearning4j_tpu.serve.engine:fwd":
        ("engine_forward", "engine"),
    "deeplearning4j_tpu.serve.continuous:_sample_dynamic":
        ("gen_sample", "gen"),
    "deeplearning4j_tpu.serve.continuous:_decode_paged_fn":
        ("gen_decode_paged", "paged"),
    "deeplearning4j_tpu.serve.continuous:_prefill_chunk_fn":
        ("gen_prefill_chunk", "paged"),
    "deeplearning4j_tpu.serve.continuous:_decode_step":
        ("gen_decode_dense", "dense"),
    "deeplearning4j_tpu.serve.continuous:_prefill":
        ("gen_prefill_dense", "dense"),
    "deeplearning4j_tpu.serve.continuous:_slot_insert":
        ("gen_slot_insert", "dense"),
}

_DEFAULT_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32)
_DEFAULT_CAPACITY = 256
_DEFAULT_PREFILL_CHUNK = 64


def default_prompt_buckets(capacity: int) -> Tuple[int, ...]:
    """Pure replica of ``serve.continuous._default_prompt_buckets`` —
    powers of two from 8 up to (and including) the KV capacity. Held
    bit-identical to the serving code by a parity test."""
    buckets, b = [], 8
    while b < capacity:
        buckets.append(b)
        b *= 2
    buckets.append(capacity)
    return tuple(sorted(set(buckets)))


def chunk_buckets(prompt_buckets: Tuple[int, ...],
                  prefill_chunk: Optional[int]) -> Tuple[int, ...]:
    """Pure replica of the batcher's ``_chunk_buckets`` derivation: the
    prompt buckets a single prefill chunk can cover, plus the chunk width
    itself; ``prefill_chunk=None`` means whole-prompt prefill over the
    prompt buckets. Parity-tested against ``serve/continuous.py``."""
    if prefill_chunk is None:
        return tuple(prompt_buckets)
    return tuple(sorted(set(
        [b for b in prompt_buckets if b <= prefill_chunk]
        + [int(prefill_chunk)])))


def resolve_tables(config: dict) -> Dict[str, list]:
    """The concrete bucket tables one serving config boots with.

    ``config`` mirrors the knobs a replica passes to ``ServeEngine`` /
    ``ContinuousBatcher`` (``engine`` and ``gen`` groups, same key names
    as the tuned-config schema). ``length_buckets`` unset resolves to the
    one-entry table ``[None]`` — the model's native input shape — so the
    ``|batch_buckets|*|length_buckets|`` product stays well defined.
    """
    engine = dict(config.get("engine") or {})
    gen = dict(config.get("gen") or {})
    batch = [int(b) for b in sorted(set(
        engine.get("batch_buckets") or _DEFAULT_BATCH_BUCKETS))]
    length = engine.get("length_buckets")
    length = ([int(b) for b in sorted(set(length))] if length
              else [None])
    capacity = int(gen.get("capacity") or _DEFAULT_CAPACITY)
    prompt = gen.get("prompt_buckets") or default_prompt_buckets(capacity)
    # the constructor's normalization: ints, deduped, capped at capacity
    prompt = tuple(sorted(set(
        int(b) for b in prompt if int(b) <= capacity))) or (capacity,)
    kv = str(gen.get("kv") or "paged")
    prefill_chunk = gen.get("prefill_chunk", _DEFAULT_PREFILL_CHUNK)
    if kv == "paged":
        chunks = chunk_buckets(
            prompt, int(prefill_chunk) if prefill_chunk is not None
            else None)
    else:
        chunks = prompt
    return {"batch_buckets": batch, "length_buckets": length,
            "prompt_buckets": list(prompt), "_chunk_buckets": list(chunks)}


def _gate_open(gate: str, kv: str, predict_only: bool) -> Optional[str]:
    """None when this boot builds the executable, else the skip reason."""
    if gate == "engine":
        return None
    if predict_only:
        return "predict-only config: no generation stack is built"
    if gate == "gen":
        return None
    if gate != kv:
        return (f"kv={kv!r} boot never builds this executable "
                f"({gate}-path only)")
    return None


def enumerate_surface(report: dict, budget: dict, config: dict) -> dict:
    """Expand the computed compile-surface ``report`` against one concrete
    serving ``config`` into a prebuild manifest.

    Every budgeted site is either *enumerated* — its symbolic factors
    resolved against the config's bucket tables, signatures = the cross
    product — or *excluded* with a machine-checkable reason (statically
    unknown bound, no call sites, not a serving executable, wrong KV
    mode). A serving-tagged site whose bound carries a factor the tables
    cannot resolve raises ``ValueError``: an unresolvable factor means the
    manifest would under-cover the surface, which is exactly the silent
    hole strict mode exists to forbid.
    """
    tables = resolve_tables(config)
    gen = dict(config.get("gen") or {})
    kv = str(gen.get("kv") or "paged")
    predict_only = bool(config.get("predict_only"))
    budgeted = budget.get("sites", {})
    sites_out: List[dict] = []
    excluded: List[dict] = []
    for row in sorted(report.get("sites", []), key=lambda r: r["site"]):
        site = row["site"]
        bound = row["bound"]
        reason = None
        tag = gate = ""
        factors: set = set()
        if budgeted.get(site) is None:
            reason = "no budget entry (the budget gate fails separately)"
        elif SITE_TAGS.get(site) is None:
            reason = "not a serving executable (no AOT store tag)"
        else:
            tag, gate = SITE_TAGS[site]
            unb, unk, factors, _numeric = _parse_bound(bound)
            if unb or unk:
                reason = f"bound {bound!r} is not statically enumerable"
            else:
                reason = _gate_open(gate, kv, predict_only)
        if reason is not None:
            excluded.append({"site": site, "bound": bound,
                             "reason": reason})
            continue
        axes: List[Tuple[str, list]] = []
        for factor in sorted(factors):
            table_name = factor.strip("|")
            table = tables.get(table_name)
            if table is None:
                raise ValueError(
                    f"{site}: factor {factor} has no resolvable bucket "
                    f"table in the config (known: {sorted(tables)}) — "
                    "the manifest would under-cover the surface")
            axes.append((table_name, list(table)))
        signatures = [dict(zip([n for n, _ in axes], combo))
                      for combo in itertools.product(
                          *[vals for _, vals in axes])]
        sites_out.append({
            "site": site, "tag": tag, "path": row.get("path"),
            "line": row.get("line"), "bound": bound,
            "cardinality": len(signatures), "signatures": signatures,
        })
    manifest = {
        "version": MANIFEST_VERSION,
        "tool": "jaxlint-enumerate",
        "config": config,
        "tables": tables,
        "sites": sites_out,
        "excluded": excluded,
        "total_signatures": sum(s["cardinality"] for s in sites_out),
    }
    manifest["hash"] = manifest_hash(manifest)
    return manifest


def manifest_hash(manifest: dict) -> str:
    """Stable 16-hex digest over the manifest's canonical JSON (the
    ``hash`` field itself excluded) — one half of the coverage-record key
    ``(runtime fingerprint, manifest hash)``."""
    body = {k: v for k, v in manifest.items() if k != "hash"}
    canon = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def load_serve_config(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        config = json.load(fh)
    if not isinstance(config, dict):
        raise ValueError("serve config must be a JSON object")
    return config


def write_manifest(manifest: dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(manifest, fh, indent=2)
        fh.write("\n")
