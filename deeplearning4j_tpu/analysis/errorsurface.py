"""Static per-endpoint error surface + committed budget (jaxlint v5).

The v4 compile surface proved a runtime property of the serving tier —
"how many executables can this jit site ever produce" — can be computed
statically, committed as a budget, and diffed in CI. This module makes
the same move for the *error* surface: for every HTTP handler entry
(``do_*`` method) the :mod:`.errorflow` fixpoint yields the set of
exception classes that can reach the boundary, and this walker resolves
where each one lands:

- a **typed** :class:`ServeError` caught by an explicitly-typed
  ``except`` entry answers with its class-attribute ``http_status``;
- an untyped exception caught by a *specific* clause (the
  ``_BAD_REQUEST`` ladder) answers with that clause's literal status —
  a deliberate mapping;
- anything landing in the generic catch-all is an untyped 500;
- anything landing nowhere **escapes** — the client gets a connection
  reset instead of an answer.

Each (endpoint, exception) pair carries the status, whether the landing
clause witnesses a ``Retry-After`` header, and which metric families the
clause counts. The report is written to ``error_surface.json`` and
checked against the committed budget (``scripts/error_budget.json``)
exactly like the compile budget: a new endpoint, a new untyped escape,
a typed error losing its status mapping, a lost Retry-After/counter, or
a stale budget endpoint fails CI; tightening always passes.
"""

from __future__ import annotations

import ast
import json
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import Program
from .errorflow import Clause, ErrorModel, Flow, get_error_model, short

GENERIC_STATUS = 500


def typed_entry(model: ErrorModel, clause: Clause, qual: str) -> bool:
    """Did the clause catch ``qual`` via an explicitly-typed entry (not
    the bare/Exception catch-all)? Only then does the typed error keep
    its own ``http_status`` mapping."""
    if clause.types is None:
        return False
    return any(t not in ("Exception", "BaseException", "?")
               and model.is_subtype(qual, t)
               for t in clause.types)


def flow_status(model: ErrorModel, fi, flow: Flow):
    """HTTP status a flow actually answers with: an int, ``"escape"``
    (no answer at all), or ``"mapped"`` (a specific clause with no
    literal status the model can read)."""
    clause = flow.clause
    if clause is None:
        return "escape"
    if model.is_serve_error(flow.qual) and typed_entry(model, clause,
                                                      flow.qual):
        st = model.class_attr(flow.qual, "http_status")
        return int(st) if isinstance(st, int) else GENERIC_STATUS
    if clause.generic and not typed_entry(model, clause, flow.qual):
        return GENERIC_STATUS
    lits = sorted(s for s in model.clause_statuses(fi, clause)
                  if isinstance(s, int) and s >= 400)
    if lits:
        return lits[0]
    return "mapped"


def _routes(fi) -> List[str]:
    """Route literals the handler compares ``self.path`` against —
    informational only; the budget keys on the boundary method."""
    out: Set[str] = set()
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and node.value.startswith("/") and len(node.value) > 1 \
                and " " not in node.value:
            out.add(node.value)
    return sorted(out)


def _via(model: ErrorModel, flow: Flow) -> str:
    if flow.clause is None:
        return "escapes the handler"
    node = flow.clause.node
    if flow.clause.types is None:
        return f"bare except (line {node.lineno})"
    names = ", ".join(short(t) for t in flow.clause.types)
    return f"except ({names}) (line {node.lineno})"


def compute_surface(program: Program) -> dict:
    """The error-surface report: one entry per ``do_*`` boundary, one row
    per exception class reachable at it."""
    model = get_error_model(program)
    endpoints = []
    for fi in model.boundaries():
        mi = fi.module
        rows = []
        for flow in model.boundary_flows(fi):
            clause = flow.clause
            status = flow_status(model, fi, flow)
            counted = sorted(model.node_metric_families(fi, clause.node)) \
                if clause is not None else []
            rows.append({
                "exception": flow.qual,
                "class": short(flow.qual),
                "typed": model.is_serve_error(flow.qual),
                "status": status,
                "retry_after": bool(
                    clause is not None
                    and model.clause_retry_after(fi, clause)),
                "counted": counted,
                "via": _via(model, flow),
                "chain": list(flow.escape.chain),
            })
        endpoints.append({
            "endpoint": f"{mi.module}:{fi.qual}",
            "path": mi.path,
            "line": fi.node.lineno,
            "routes": _routes(fi),
            "errors": sorted(rows, key=lambda r: r["exception"]),
        })
    endpoints.sort(key=lambda e: e["endpoint"])
    return {"version": 1, "tool": "jaxlint-error-surface",
            "endpoints": endpoints}


# ------------------------------------------------------------- budget

def check_budget(report: dict, budget: dict) -> List[str]:
    """Violations of the committed error budget; empty = gate passes.

    Fails on: a new endpoint the budget does not know; a new exception
    at a budgeted endpoint (worded as an *untyped escape* when it is
    one); a status mapping drifting from the budget — including a typed
    error degrading to the generic 500 or to a boundary escape; a
    Retry-After witness or a budgeted counter family going missing; and
    a stale budget endpoint (the boundary no longer exists — a stale
    entry guards nothing; delete it, that is tightening). An error class
    the budget allows but the tree no longer raises passes: tightening
    is always allowed.
    """
    allowed: Dict[str, dict] = budget.get("endpoints", {})
    out: List[str] = []
    seen: Set[str] = set()
    for ep in report.get("endpoints", []):
        eid = ep["endpoint"]
        seen.add(eid)
        entry = allowed.get(eid)
        if entry is None:
            out.append(f"{eid}: new HTTP endpoint with no budget entry "
                       f"({len(ep['errors'])} reachable error class(es)) "
                       "— add it to the budget with a why:")
            continue
        b_errors: Dict[str, dict] = entry.get("errors", {})
        for row in ep["errors"]:
            q = row["exception"]
            b = b_errors.get(q)
            if b is None:
                if not row["typed"] and row["status"] in ("escape",
                                                          GENERIC_STATUS):
                    out.append(
                        f"{eid}: new untyped escape {row['class']} "
                        f"({'no answer' if row['status'] == 'escape' else 'generic 500'}) "
                        f"— {' ; '.join(row['chain'][:3])}")
                else:
                    out.append(f"{eid}: new error class {row['class']} "
                               f"(status {row['status']}) with no budget "
                               "entry — add it with a why:")
                continue
            if row["status"] != b.get("status"):
                out.append(f"{eid}: {row['class']} status mapping drifted "
                           f"— computed {row['status']!r}, budget "
                           f"{b.get('status')!r}")
            if b.get("retry_after") and not row["retry_after"]:
                out.append(f"{eid}: {row['class']} lost its Retry-After "
                           "witness (budget requires one)")
            missing = sorted(set(b.get("counted", []))
                             - set(row["counted"]))
            if missing:
                out.append(f"{eid}: {row['class']} no longer counts "
                           f"{missing} (budget requires them)")
    for eid in sorted(set(allowed) - seen):
        out.append(f"{eid}: stale budget endpoint — no such handler in "
                   "the analyzed tree; delete the entry (tightening) or "
                   "fix the endpoint id")
    return out


def run(paths: Sequence[str], exclude: Sequence[str] = ()
        ) -> Tuple[dict, Program]:
    """Analyze ``paths`` and return (error-surface report, program)."""
    from .engine import read_sources

    sources = read_sources(paths, exclude)
    program = Program(sources)
    return compute_surface(program), program


def load_budget(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or "endpoints" not in data:
        raise ValueError("error budget file must be {'endpoints': {...}}")
    return data
