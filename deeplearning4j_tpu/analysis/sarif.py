"""Machine-readable CI output for jaxlint: SARIF 2.1.0 + finding baselines.

SARIF (Static Analysis Results Interchange Format) is what GitHub code
scanning ingests — emitting it makes jaxlint findings appear as inline PR
annotations with zero glue code. The baseline mechanism lets a *stricter*
rule land before the tree is fully clean: record today's findings once,
then fail CI only on findings that are not in the recorded set, so new
regressions are caught while the documented backlog burns down.

Baseline fingerprints are deliberately line-number-free —
``sha1(rule | normalized path | message)`` plus an occurrence index for
duplicates — so unrelated edits that shift code downward do not invalidate
the baseline, while a genuinely new instance of a known finding kind in the
same file still counts as new once it outnumbers the recorded occurrences.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Sequence, Set

from .engine import Finding

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")
SARIF_VERSION = "2.1.0"
BASELINE_VERSION = 1


def _uri(path: str) -> str:
    return os.path.normpath(path).replace(os.sep, "/")


def to_sarif(findings: Sequence[Finding]) -> dict:
    """One-run SARIF document in the GitHub code-scanning dialect."""
    from .rules import ALL_RULES

    known = {r.name: r for r in ALL_RULES}
    used = sorted({f.rule for f in findings})
    rules = []
    for name in used:
        r = known.get(name)
        desc = r.description if r is not None else name
        rules.append({
            "id": name,
            "shortDescription": {"text": desc},
            "helpUri": ("https://github.com/deeplearning4j-tpu/"
                        "deeplearning4j-tpu/blob/main/deeplearning4j_tpu/"
                        "analysis/README.md"),
        })
    index = {name: i for i, name in enumerate(used)}
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": _uri(f.path),
                                         "uriBaseId": "%SRCROOT%"},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": f.col + 1},
                },
            }],
        })
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "jaxlint",
                "informationUri": ("https://github.com/deeplearning4j-tpu/"
                                   "deeplearning4j-tpu"),
                "rules": rules,
            }},
            "results": results,
            "columnKind": "utf16CodeUnits",
        }],
    }


def render_sarif(findings: Sequence[Finding]) -> str:
    return json.dumps(to_sarif(findings), indent=2, sort_keys=True)


# -- baselines --------------------------------------------------------------

def fingerprints(findings: Sequence[Finding]) -> List[str]:
    """Stable per-finding fingerprints, order-aligned with ``findings``.
    Identical (rule, path, message) triples get an occurrence suffix so a
    *second* instance of a baselined finding still reads as new."""
    counts: Dict[str, int] = {}
    out = []
    for f in findings:
        h = hashlib.sha1(
            f"{f.rule}|{_uri(f.path)}|{f.message}".encode()).hexdigest()[:16]
        n = counts.get(h, 0)
        counts[h] = n + 1
        out.append(f"{h}:{n}")
    return out


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    doc = {"version": BASELINE_VERSION,
           "count": len(findings),
           "fingerprints": sorted(fingerprints(findings))}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")


def load_baseline(path: str) -> Set[str]:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(f"unsupported baseline version in {path}: "
                         f"{doc.get('version')!r}")
    return set(doc.get("fingerprints", ()))


def new_findings(findings: Sequence[Finding],
                 baseline: Set[str]) -> List[Finding]:
    """Findings whose fingerprint is not in the recorded baseline."""
    return [f for f, fp in zip(findings, fingerprints(findings))
            if fp not in baseline]
