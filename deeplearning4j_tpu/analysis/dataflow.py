"""Intraprocedural forward dataflow for jaxlint rules.

A small abstract-interpretation framework over one function body: statements
are visited in program order, branch states are forked and re-joined, and a
per-variable state dict flows forward. "SSA-ish" in the pragmatic sense —
every assignment kills the tracked fact for its targets, so a fact always
describes the *current* binding of a name, never a shadowed one.

Two layers:

- :class:`ForwardScan` — the walker. Subclasses observe expressions
  (:meth:`visit_expr`), define how facts merge at join points
  (:meth:`join_value`) and die at assignments (:meth:`kill`). The walker
  handles If/For/While/With/Try structure, exclusive early-return branches,
  walrus targets, and maintains :attr:`with_stack` so rules can ask "what
  context managers are held here?" (the lock rule).
- :class:`ReachingDefs` — a ready-made analysis on top of it: for every
  ``Name`` load in the function, the set of assignment lines that may reach
  it. Used by tests as the framework's reference client; rules build their
  own subclasses (key consumption, donation liveness) the same way.

The branch semantics intentionally mirror the original prng-key-reuse
walker (jaxlint v1), whose approximations were tuned on this repo: loop
bodies are scanned once, exclusive ``if/else`` branches are forked and
joined with :meth:`join_value`, and a branch ending in
``return``/``raise``/``break``/``continue`` does not contribute to the join
(its facts cannot flow into the code after the statement).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Tuple


def assign_names(target: ast.AST) -> Iterator[str]:
    """Bare names bound by an assignment target (tuples/stars unpacked)."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            yield from assign_names(e)
    elif isinstance(target, ast.Starred):
        yield from assign_names(target.value)


def walrus_targets(expr: ast.AST) -> Iterator[str]:
    for node in ast.walk(expr):
        if isinstance(node, ast.NamedExpr) and isinstance(node.target, ast.Name):
            yield node.target.id


def terminates(stmts: List[ast.stmt]) -> bool:
    """Block ends by leaving the enclosing scope — its facts never flow into
    the code after the branch statement."""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


class ForwardScan:
    """Forward scan of one function body with a per-name fact dict.

    Subclass hooks:

    - ``visit_expr(expr, state)`` — yield findings, update facts. Called for
      every expression in evaluation-ish order.
    - ``kill(name, state)`` — an assignment rebinds ``name`` (default: drop
      the fact).
    - ``join_value(a, b)`` — merge one name's facts from two branches
      (default: ``max``, matching counting analyses).
    - ``bottom`` — the implicit fact for names a branch never touched.
    """

    bottom = 0

    def __init__(self):
        self.with_stack: List[ast.withitem] = []

    # -- hooks ------------------------------------------------------------
    def visit_expr(self, expr: ast.expr, state: Dict) -> Iterator:
        return iter(())

    def kill(self, name: str, state: Dict) -> None:
        state.pop(name, None)

    def join_value(self, a, b):
        return max(a, b)

    # -- driver -----------------------------------------------------------
    def run(self, fn: ast.AST) -> Iterator:
        yield from self.scan(fn.body, {})

    def _expr(self, expr, state) -> Iterator:
        if expr is None:
            return
        yield from self.visit_expr(expr, state)
        for t in walrus_targets(expr):
            self.kill(t, state)

    def _branch(self, stmts, state) -> Tuple[list, Dict]:
        c = dict(state)
        return list(self.scan(stmts, c)), c

    def _join(self, state, branch_states) -> None:
        if not branch_states:
            return
        keys = set()
        for c in branch_states:
            keys.update(c)
        for k in keys:
            vals = [c.get(k, self.bottom) for c in branch_states]
            v = vals[0]
            for x in vals[1:]:
                v = self.join_value(v, x)
            state[k] = v

    def scan(self, stmts, state: Dict) -> Iterator:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope, scanned on its own
            if isinstance(stmt, ast.Assign):
                yield from self._expr(stmt.value, state)
                for t in stmt.targets:
                    for n in assign_names(t):
                        self.kill(n, state)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                yield from self._expr(stmt.value, state)
                for n in assign_names(stmt.target):
                    self.kill(n, state)
            elif isinstance(stmt, ast.If):
                yield from self._expr(stmt.test, state)
                f1, c1 = self._branch(stmt.body, state)
                f2, c2 = self._branch(stmt.orelse, state)
                yield from f1
                yield from f2
                self._join(state, [c for c, block in
                                   ((c1, stmt.body), (c2, stmt.orelse))
                                   if not terminates(block)])
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                yield from self._expr(stmt.iter, state)
                for n in assign_names(stmt.target):
                    self.kill(n, state)
                f1, c1 = self._branch(stmt.body + stmt.orelse, state)
                yield from f1
                self._join(state, [state, c1])
            elif isinstance(stmt, ast.While):
                yield from self._expr(stmt.test, state)
                f1, c1 = self._branch(stmt.body + stmt.orelse, state)
                yield from f1
                self._join(state, [state, c1])
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    yield from self._expr(item.context_expr, state)
                    if item.optional_vars is not None:
                        for n in assign_names(item.optional_vars):
                            self.kill(n, state)
                self.with_stack.extend(stmt.items)
                yield from self.scan(stmt.body, state)
                del self.with_stack[-len(stmt.items):]
            elif isinstance(stmt, ast.Try):
                yield from self.scan(stmt.body, state)
                handler_states = []
                for h in stmt.handlers:
                    fh, ch = self._branch(h.body, state)
                    yield from fh
                    handler_states.append(ch)
                self._join(state, [state] + handler_states)
                yield from self.scan(stmt.orelse + stmt.finalbody, state)
            else:
                for expr in ast.iter_child_nodes(stmt):
                    if isinstance(expr, ast.expr):
                        yield from self._expr(expr, state)


class ReachingDefs(ForwardScan):
    """Reaching definitions per name: for every ``Name`` load, which
    assignment lines may have produced the current binding.

    ``defs_at(name_node)`` answers for a specific load;
    ``uses_of(name)`` lists ``(load node, frozenset of def lines)``.
    Parameters count as definitions at the ``def`` line.
    """

    bottom = frozenset()

    def __init__(self, fn: ast.AST):
        super().__init__()
        self._fn = fn
        self._uses: List[Tuple[ast.Name, frozenset]] = []
        state: Dict[str, frozenset] = {}
        args = fn.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)
                  + [x for x in (args.vararg, args.kwarg) if x]):
            state[a.arg] = frozenset([fn.lineno])
        self._pending_line: int = fn.lineno
        for _ in self.scan(fn.body, state):
            pass

    def join_value(self, a, b):
        return a | b

    def kill(self, name, state):
        state[name] = frozenset([self._pending_line])

    def visit_expr(self, expr, state):
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                self._uses.append((node, state.get(node.id, frozenset())))
        return iter(())

    def scan(self, stmts, state):
        # one statement at a time so kill() knows which line redefined a name
        for stmt in stmts:
            self._pending_line = getattr(stmt, "lineno", self._pending_line)
            yield from super().scan([stmt], state)

    def uses_of(self, name: str) -> List[Tuple[ast.Name, frozenset]]:
        return [(n, d) for n, d in self._uses if n.id == name]

    def defs_at(self, name_node: ast.Name) -> frozenset:
        for n, d in self._uses:
            if n is name_node:
                return d
        return frozenset()
