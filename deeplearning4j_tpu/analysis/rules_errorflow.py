"""jaxlint error-flow rules (v5).

The serving contract — every client-visible failure is a typed
``ServeError`` with exactly one status, a counted ``{cause}``, and an
in-band error event after the SSE commit — is enforced statically over
the :mod:`.errorflow` fixpoint:

- ``untyped-escape-to-http`` — a non-``ServeError`` exception reaches a
  ``do_*`` boundary and either escapes it (connection reset, no answer)
  or lands in the generic catch-all (an anonymous 500);
- ``swallowed-typed-error`` — an ``except`` clause that receives a typed
  ``ServeError`` re-raises an untyped exception, destroying the
  status/cause mapping (the PR 16 dispatcher bug, found statically);
- ``error-status-drift`` — a typed error class mapped to a literal
  status that contradicts its ``http_status`` attribute or another
  tier's mapping, or a handler clause answering 503 with no
  ``Retry-After`` witness;
- ``uncounted-shed`` — a shed-class raise (``ShedError`` subtree) in a
  function with no ``serve_shed_total``/``fleet_*``/``cluster_*``
  counter witness nearby (itself, a helper one hop down, or a direct
  caller);
- ``sse-post-commit-error`` — an exception that can escape a streaming
  function *after* its ``send_response(200)`` commit point, where the
  only correct channel left is the in-band error event.

Findings ride the normal engine (suppressible, SARIF'd, baselined). A
function whose escape/raise is a designed contract opts out with
``# jaxlint: sanction=<rule>`` on its ``def`` line plus a written
justification — same grammar as the v3 lock model; sanctions mute the
rule at either end of the witness chain, never the error-surface budget.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .engine import FileContext, Finding, Rule
from .errorflow import Clause, get_error_model, short
from .errorsurface import typed_entry
from .rules import register

_CALLERS_CACHE = "errorflow:callers"

#: counter families that witness a counted shed
_SHED_FAMILY_EXACT = {"serve_shed_total"}
_SHED_FAMILY_PREFIX = ("fleet_", "cluster_")


def _boundaries_in_file(ctx: FileContext, model) -> list:
    return [fi for fi in ctx.module_info.all_funcs
            if fi.cls and fi.name.startswith("do_")]


def _chain_text(chain) -> str:
    return "; ".join(chain)


@register
class UntypedEscapeToHttpRule(Rule):
    """An untyped exception reaching an HTTP handler boundary.

    Whatever is not a ``ServeError`` caught by a *specific* clause has no
    contract: if it lands in the generic catch-all the client gets an
    anonymous 500 with no machine-readable cause; if it escapes the
    ``do_*`` method entirely the socket server eats it and the client
    gets a connection reset instead of an answer. Both shapes are
    invisible to per-file lint — the raise is usually modules away — so
    the check runs over the interprocedural raise-set fixpoint and
    reports the witness chain. Fix by mapping the exception to a typed
    ``ServeError`` (or an explicit except clause); a deliberate
    programming-error-to-500 path opts out with
    ``# jaxlint: sanction=untyped-escape-to-http`` + a justification.
    """

    name = "untyped-escape-to-http"
    description = ("non-ServeError exception reachable uncaught (or "
                   "catch-all-only) at an HTTP handler boundary")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        model = get_error_model(ctx.program)
        for fi in _boundaries_in_file(ctx, model):
            for flow in model.boundary_flows(fi):
                if model.is_serve_error(flow.qual):
                    continue
                if model.flow_sanctioned(flow, fi, self.name):
                    continue
                if flow.clause is None:
                    how = ("ESCAPES the boundary — the client gets a "
                           "connection reset, not an HTTP answer")
                elif flow.clause.generic \
                        and not typed_entry(model, flow.clause, flow.qual):
                    how = (f"only the generic catch-all (line "
                           f"{flow.clause.node.lineno}) stops it — an "
                           f"anonymous 500 with no typed cause")
                else:
                    continue  # a specific clause: deliberate mapping
                yield self.finding(
                    ctx, fi.node,
                    f"untyped {short(flow.qual)} reaches handler "
                    f"{fi.qual} and {how}. Witness: "
                    f"{_chain_text(flow.escape.chain)}. Map it to a "
                    f"typed ServeError or a specific except clause")


@register
class SwallowedTypedErrorRule(Rule):
    """A typed ``ServeError`` re-wrapped into an untyped exception.

    The PR 16 dispatcher bug: a broad handler caught typed
    ``AotTraceError``s and re-raised them as generic failures, so the
    front door answered 500/"internal" instead of 503/"aot_trace" and
    the shed counters lost the cause. The check finds every ``except``
    clause that *receives* a ServeError — either named in the clause or
    proven to arrive by the fixpoint — and raises a non-ServeError from
    its body. Re-raising (bare ``raise``/``raise e``) and wrapping into
    another ServeError are fine.
    """

    name = "swallowed-typed-error"
    description = ("except clause receives a typed ServeError but "
                   "re-raises an untyped exception (mapping lost)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        model = get_error_model(ctx.program)
        mi = ctx.module_info
        for fi in mi.all_funcs:
            if model.sanctioned(fi, self.name):
                continue
            arrivals: Dict[int, List[str]] = {}
            for clause, q, esc in model.clause_arrivals(fi):
                if model.is_serve_error(q):
                    arrivals.setdefault(id(clause.node), []).append(q)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Try):
                    continue
                if mi.enclosing_function(node) is not fi.node:
                    continue
                for h in node.handlers:
                    clause = Clause(model.clause_types(mi, h), h)
                    typed = sorted(set(arrivals.get(id(h), [])))
                    if not typed and clause.types:
                        typed = sorted(
                            t for t in clause.types
                            if t not in ("?",) and model.is_serve_error(t))
                    if not typed:
                        continue
                    yield from self._wraps(ctx, model, mi, h,
                                           clause, typed)

    def _wraps(self, ctx, model, mi, handler, clause, typed):
        bound = handler.name
        for node in ast.walk(handler):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            target = node.exc.func if isinstance(node.exc, ast.Call) \
                else node.exc
            if isinstance(target, ast.Name) and target.id == bound:
                continue  # re-raising the caught exception: fine
            q = model._resolve_class_name(mi, target)
            if q is None or model.is_serve_error(q):
                continue
            names = ", ".join(short(t) for t in typed)
            yield self.finding(
                ctx, node,
                f"typed {names} caught at line {handler.lineno} is "
                f"re-wrapped into untyped {short(q)} — the "
                f"status/cause mapping is destroyed and the front door "
                f"answers an anonymous 500 (the PR 16 dispatcher bug "
                f"shape). Re-raise it, or wrap into a ServeError")


@register
class ErrorStatusDriftRule(Rule):
    """One typed error class, two different HTTP statuses — or a 503
    with no ``Retry-After``.

    The three HTTP tiers (serve, fleet, cluster router) answer typed
    errors via ``e.http_status``; a clause that hard-codes a literal for
    a typed class can silently drift from the class attribute (or from
    another tier). And every 503 is a retry invitation: a clause that
    answers 503 without a ``Retry-After`` witness (the header literal or
    a ``jitter_retry_after``-family helper) invites synchronized retry
    storms — the jittered header is the contract everywhere else.
    """

    name = "error-status-drift"
    description = ("typed error mapped to a status contradicting its "
                   "http_status (or another tier), or a 503 clause "
                   "without Retry-After")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        model = get_error_model(ctx.program)
        mi = ctx.module_info
        handler_classes = {(fi.module, fi.cls)
                           for fi in model.boundaries()}
        for fi in mi.all_funcs:
            if model.sanctioned(fi, self.name):
                continue
            in_handler = (fi.module, fi.cls) in handler_classes \
                or (fi.cls and fi.name.startswith("do_"))
            arrives_503: Dict[int, List[str]] = {}
            for clause, q, esc in model.clause_arrivals(fi):
                if model.is_serve_error(q) \
                        and model.class_attr(q, "http_status") == 503:
                    arrives_503.setdefault(id(clause.node), []).append(q)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Try):
                    continue
                if mi.enclosing_function(node) is not fi.node:
                    continue
                for h in node.handlers:
                    clause = Clause(model.clause_types(mi, h), h)
                    statuses = model.clause_statuses(fi, clause)
                    lits = sorted(s for s in statuses
                                  if isinstance(s, int) and s >= 400)
                    # (a) literal contradicts the class's http_status
                    for t in (clause.types or ()):
                        if t == "?" or not model.is_serve_error(t):
                            continue
                        attr = model.class_attr(t, "http_status")
                        for s in lits:
                            if isinstance(attr, int) and s != attr:
                                yield self.finding(
                                    ctx, h,
                                    f"{short(t)} is answered with "
                                    f"literal {s} here but declares "
                                    f"http_status={attr} — one typed "
                                    f"error class must map to one "
                                    f"status on every tier; use "
                                    f"e.http_status or fix the class")
                    # (b) a 503 answer with no Retry-After witness
                    if not in_handler:
                        continue
                    answers_503 = 503 in lits or (
                        "dynamic" in statuses
                        and arrives_503.get(id(h)))
                    if answers_503 \
                            and not model.clause_retry_after(fi, clause):
                        via = sorted(short(q) for q in
                                     arrives_503.get(id(h), [])) or ["503"]
                        yield self.finding(
                            ctx, h,
                            f"this clause answers 503 "
                            f"({', '.join(via)}) without a Retry-After "
                            f"witness — a 503 with no backoff hint "
                            f"invites synchronized retry storms; add "
                            f"the jittered Retry-After header like the "
                            f"other tiers")


@register
class UncountedShedRule(Rule):
    """A shed-class raise with no counter witness.

    Every admission refusal (the ``ShedError`` subtree: queue_full,
    shutting_down, quota, breaker_open, no_replica…) must land on a
    ``serve_shed_total{cause=...}`` / ``fleet_*`` / ``cluster_*``
    counter — sheds that are invisible to the burn-rate pipeline are how
    overload turns into a silent SLO breach. The witness may live in the
    raising function itself, a helper one call away, or a direct caller
    (the count-then-raise split); beyond that, the raise is reported.
    """

    name = "uncounted-shed"
    description = ("raise of a shed-class (ShedError subtree) error on "
                   "a path with no shed/fleet/cluster counter witness")

    @staticmethod
    def _has_family(fams: Set[str]) -> bool:
        return bool(fams & _SHED_FAMILY_EXACT
                    or any(f.startswith(_SHED_FAMILY_PREFIX)
                           for f in fams))

    def _callers(self, model) -> Dict[object, List[object]]:
        rev = model.program.cache.get(_CALLERS_CACHE)
        if rev is None:
            rev = {}
            for fi in model._all_funcs:
                for ev in model.events(fi):
                    if ev[0] == "call":
                        rev.setdefault(ev[2], []).append(fi)
            model.program.cache[_CALLERS_CACHE] = rev
        return rev

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        model = get_error_model(ctx.program)
        callers = self._callers(model)
        for fi in ctx.module_info.all_funcs:
            if model.sanctioned(fi, self.name):
                continue
            sheds = [(ev[1], ev[2]) for ev in model.events(fi)
                     if ev[0] == "raise"
                     and any(model.is_shed_error(q) for q in ev[1])]
            if not sheds:
                continue
            if self._has_family(model.metric_families(fi, hops=1)):
                continue
            if any(self._has_family(model.metric_families(c, hops=1))
                   for c in callers.get(fi, ())):
                continue
            for quals, node in sheds:
                names = ", ".join(short(q) for q in quals
                                  if model.is_shed_error(q))
                yield self.finding(
                    ctx, node,
                    f"{names} raised here but neither {fi.qual}, its "
                    f"helpers, nor any direct caller touches a "
                    f"serve_shed_total/fleet_*/cluster_* counter — an "
                    f"uncounted shed is invisible to the burn-rate "
                    f"pipeline; count the cause where it is decided")


@register
class SsePostCommitErrorRule(Rule):
    """An exception escaping a streaming function after the SSE commit.

    Once ``send_response(200)`` + headers are on the wire, the HTTP
    status is spent: an exception that escapes the function after that
    point makes the outer handler write a second status line into a
    committed stream (garbage mid-stream) — or kills the socket with no
    in-band signal. Everything raisable past the commit point must be
    caught locally and routed through the in-band error event
    (``data: {"error": ..., "cause": ...}``); only the client-gone
    family (``BrokenPipeError``/``ConnectionResetError``) may escape, as
    there is no client left to tell.
    """

    name = "sse-post-commit-error"
    description = ("exception raisable after the send_response(200) "
                   "commit point escapes the streaming function")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        model = get_error_model(ctx.program)
        for fi in ctx.module_info.all_funcs:
            if model.sanctioned(fi, self.name):
                continue
            commit = model.commit_line(fi)
            if commit is None:
                continue
            seen: Set[str] = set()
            for ev in model.events(fi):
                node = ev[2] if ev[0] == "raise" else ev[1]
                if getattr(node, "lineno", 0) <= commit:
                    continue
                if ev[0] == "raise":
                    _, quals, node, frames = ev
                    pairs = [(q, None) for q in quals]
                else:
                    _, node, callee, frames = ev
                    pairs = list(model.escapes.get(callee, {}).items())
                for q, esc in pairs:
                    if q in seen or model.is_client_gone(q):
                        continue
                    if esc is not None \
                            and model.sanctioned(esc.origin, self.name):
                        continue
                    if model.land(q, frames) is not None:
                        continue
                    seen.add(q)
                    chain = esc.chain if esc is not None else (
                        f"{fi.qual} raises {short(q)} "
                        f"(line {node.lineno})",)
                    yield self.finding(
                        ctx, node,
                        f"{short(q)} can escape {fi.qual} after the SSE "
                        f"commit point (send_response(200) at line "
                        f"{commit}) — the outer handler would write a "
                        f"second status line into a committed stream. "
                        f"Witness: {_chain_text(chain)}. Catch it and "
                        f"emit the in-band error event instead")
