"""Abstract shape/dtype interpretation for jaxlint v4.

The serving stack's compile-cardinality contract ("ONE decode executable
for the server lifetime", prefill bounded by the bucket tables) is a
statement about *shapes*: a jit site recompiles exactly when a traced
argument's shape/dtype signature changes. This module gives the linter
eyes for that — a flow-sensitive abstract interpreter over a small
shape/dtype lattice, pure stdlib ``ast`` like everything else in
``analysis/`` (it never imports jax or numpy).

Every dimension carries a *provenance* classification, because what the
compile-surface analysis needs is not the number but where it came from:

- ``literal`` — a source-literal int (``np.zeros((1, 8))``);
- ``config`` — a constructor knob / ``self.`` attribute fixed at boot
  (``self.slots``), cardinality 1 over a server lifetime;
- ``bucket`` — drawn from a bucket table (``self.prompt_buckets``) via
  the tree's bucketing idioms (``next((b for b in T if b >= n), T[-1])``,
  ``for b in T: ... return b``, ``T[i]``) — cardinality ``|T|``;
- ``sym`` — inherited from the enclosing function's inputs (a parameter
  value or ``x.shape[i]``), the normal shape-polymorphic jit contract;
- ``unbounded`` — provably request/runtime-derived: ``len()`` of a
  runtime list, a read from ``os.environ``/``json.loads`` payloads;
- ``top`` — unknown, which is *not* the same as unbounded: rules only
  fire on provable facts, the compile-surface report renders it ``?``.

Interprocedural pieces ride the v2 :class:`~.callgraph.Program`: calls
to resolvable functions are summarized by evaluating the callee's body
with the caller's abstract arguments (depth-limited, cycle-guarded), and
``self.X`` reads go through a per-class attribute model built by
abstract-executing ``__init__`` with constructor parameters bound as
``config``.

Where the interpreter needs help (heap-carried values like a prefill
job's chunk plan), a *teaching annotation* on the binding line or the
line above pins a name::

    off, true_len, bucket = job.chunks[job.idx]  # jaxlint: dim=bucket:bucket(_chunk_buckets)
    # jaxlint: shape=x:(bucket(batch_buckets), config)
    x = np.concatenate([r.x for r in live])

``dim=`` binds a host scalar's provenance; ``shape=`` binds a full array
shape. Dim tokens: an int literal, ``?``, ``config``/``config(name)``,
``bucket(table)``, ``sym(name)``, ``unbounded``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .dataflow import ForwardScan, assign_names

# ---------------------------------------------------------------- dims

LITERAL = "literal"
CONFIG = "config"
BUCKET = "bucket"
SYM = "sym"
UNBOUNDED = "unbounded"
TOP = "top"

#: lattice severity used by joins (higher = less known / worse)
_SEV = {LITERAL: 0, CONFIG: 1, BUCKET: 2, SYM: 3, TOP: 4, UNBOUNDED: 5}


class Dim:
    """One abstract dimension: a kind plus provenance payload."""

    __slots__ = ("kind", "value", "name", "table", "size", "origin")

    def __init__(self, kind: str, value: Optional[int] = None, name: str = "",
                 table: Optional[str] = None, size: Optional[int] = None,
                 origin: str = ""):
        self.kind = kind
        self.value = value          # literal extent
        self.name = name            # display / provenance ("self.slots")
        self.table = table          # bucket table attr ("prompt_buckets")
        self.size = size            # |table| when statically known
        self.origin = origin        # dedup key for cardinality products

    def render(self) -> str:
        if self.kind == LITERAL:
            return str(self.value)
        if self.kind == CONFIG:
            return f"config({self.name})" if self.name else "config"
        if self.kind == BUCKET:
            return f"bucket({self.table})"
        if self.kind == SYM:
            return f"sym({self.name})" if self.name else "sym"
        if self.kind == UNBOUNDED:
            return f"unbounded({self.name})" if self.name else "unbounded"
        return "?"

    def same(self, other: "Dim") -> bool:
        return (self.kind == other.kind and self.value == other.value
                and self.name == other.name and self.table == other.table)

    def __repr__(self):
        return f"<Dim {self.render()}>"


def lit(n: int) -> Dim:
    return Dim(LITERAL, value=int(n))


def config_dim(name: str = "") -> Dim:
    return Dim(CONFIG, name=name, origin=name)


def bucket_dim(table: str, size: Optional[int] = None,
               origin: str = "") -> Dim:
    return Dim(BUCKET, table=table, size=size, origin=origin or table)


def sym_dim(name: str = "") -> Dim:
    return Dim(SYM, name=name, origin=name)


def unbounded_dim(name: str = "") -> Dim:
    return Dim(UNBOUNDED, name=name, origin=name)


def top_dim() -> Dim:
    return Dim(TOP)


def join_dims(a: Dim, b: Dim) -> Dim:
    if a.same(b):
        return a
    if UNBOUNDED in (a.kind, b.kind):
        which = a if a.kind == UNBOUNDED else b
        return unbounded_dim(which.name)
    if a.kind == b.kind:
        if a.kind == BUCKET and a.table == b.table:
            return a
        if a.kind == SYM and a.name == b.name:
            return a
    return top_dim()


def render_shape(dims: Sequence[Dim]) -> str:
    return "(" + ", ".join(d.render() for d in dims) + ")"


# ------------------------------------------------------------- dtypes

_DTYPE_CANON = {
    "float32": "f32", "float64": "f64", "float16": "f16",
    "bfloat16": "bf16", "int32": "i32", "int64": "i64", "int16": "i16",
    "int8": "i8", "uint8": "u8", "uint32": "u32", "bool_": "bool",
    "bool": "bool", "float": "f64", "int": "i64", "complex64": "c64",
}

#: dtype kind + width for promotion ("?" stays "?")
_DT_KIND = {"bool": ("b", 1), "i8": ("i", 8), "u8": ("i", 8),
            "i16": ("i", 16), "u32": ("i", 32), "i32": ("i", 32),
            "i64": ("i", 64), "f16": ("f", 16), "bf16": ("f", 16),
            "f32": ("f", 32), "f64": ("f", 64), "c64": ("c", 64),
            "int": ("i", 0), "float": ("f", 0)}


def canon_dtype(name: Optional[str]) -> str:
    if not name:
        return "?"
    return _DTYPE_CANON.get(name.rsplit(".", 1)[-1], "?")


def promote_dtypes(a: str, b: str, b_weak: bool = False) -> str:
    """Rough model of jax promotion; weak (python-scalar) operands never
    promote a strong operand's kind width, matching weak-type semantics."""
    if a == "?" or b == "?":
        return "?"
    if a == b:
        return a
    ka, kb = _DT_KIND.get(a), _DT_KIND.get(b)
    if ka is None or kb is None:
        return "?"
    if b_weak:
        if kb[0] == "f" and ka[0] in ("b", "i"):
            return "f32"
        return a
    order = {"b": 0, "i": 1, "f": 2, "c": 3}
    if order[ka[0]] != order[kb[0]]:
        hi = a if order[ka[0]] > order[kb[0]] else b
        if _DT_KIND[hi][0] == "f" and _DT_KIND[hi][1] == 0:
            return "f32"
        return hi
    return a if ka[1] >= kb[1] else b


# ----------------------------------------------------- abstract values

class AV:
    """Base abstract value."""


class OpaqueVal(AV):
    __slots__ = ("why",)

    def __init__(self, why: str = ""):
        self.why = why

    def __repr__(self):
        return f"<Opaque {self.why}>" if self.why else "<Opaque>"


OPAQUE = OpaqueVal()


class ArrayVal(AV):
    __slots__ = ("shape", "dtype", "weak")

    def __init__(self, shape: Sequence[Dim], dtype: str = "?",
                 weak: bool = False):
        self.shape: Tuple[Dim, ...] = tuple(shape)
        self.dtype = dtype
        self.weak = weak

    def __repr__(self):
        return f"<Array {render_shape(self.shape)} {self.dtype}>"


class ScalarVal(AV):
    """A host Python number; ``dim`` is its provenance when used as an
    extent, ``weak`` means a bare Python scalar (weak-typed under jit)."""

    __slots__ = ("dim", "dtype", "weak")

    def __init__(self, dim: Dim, dtype: str = "int", weak: bool = True):
        self.dim = dim
        self.dtype = dtype
        self.weak = weak

    def __repr__(self):
        return f"<Scalar {self.dim.render()} {self.dtype}>"


class TupleVal(AV):
    __slots__ = ("items",)

    def __init__(self, items: Sequence[AV]):
        self.items: Tuple[AV, ...] = tuple(items)


class ListVal(AV):
    """Homogeneous runtime list: element value + length dimension."""

    __slots__ = ("elem", "length")

    def __init__(self, elem: AV, length: Dim):
        self.elem = elem
        self.length = length


class TableVal(AV):
    """A bucket table: tuple of host ints fixed at boot. Drawing an
    element (iteration, subscript, ``next``/``min``/``max``) yields a
    ``bucket``-classified scalar."""

    __slots__ = ("name", "size", "values")

    def __init__(self, name: str = "", size: Optional[int] = None,
                 values: Optional[Tuple[int, ...]] = None):
        self.name = name
        self.size = size
        # the member ints, when the table is a source literal — lets a
        # tuple that doubled as a table still be read as a shape
        self.values = values

    def element(self, origin: str = "") -> ScalarVal:
        return ScalarVal(bucket_dim(self.name or "table", self.size,
                                    origin=origin), "int")


class DictVal(AV):
    """``runtime=True`` marks payload-shaped dicts (``json.loads``,
    ``os.environ``): reads used as extents are *unbounded*."""

    __slots__ = ("runtime", "source")

    def __init__(self, runtime: bool = False, source: str = ""):
        self.runtime = runtime
        self.source = source


class ParamVal(AV):
    """An unannotated parameter: opaque, but with provenance — used as an
    extent it is ``config`` in a constructor, ``sym`` elsewhere."""

    __slots__ = ("name", "config")

    def __init__(self, name: str, config: bool = False):
        self.name = name
        self.config = config


class SelfVal(AV):
    """``self`` inside a method; attribute reads go through the class
    attribute model."""

    __slots__ = ("mi", "cls")

    def __init__(self, mi, cls: str):
        self.mi = mi
        self.cls = cls


def as_dim(av: AV, fallback_name: str = "") -> Dim:
    """Interpret an abstract value used as a dimension extent."""
    if isinstance(av, ScalarVal):
        return av.dim
    if isinstance(av, ParamVal):
        return config_dim(av.name) if av.config else sym_dim(av.name)
    if isinstance(av, ArrayVal) and not av.shape:
        return top_dim()
    return Dim(TOP, name=fallback_name)


def join_avs(a: Optional[AV], b: Optional[AV]) -> AV:
    if a is None or b is None:
        return a or b or OPAQUE
    if a is b:
        return a
    if isinstance(a, ParamVal) and isinstance(b, ParamVal) \
            and a.name == b.name and a.config == b.config:
        return a
    if isinstance(a, ListVal) and isinstance(b, ListVal):
        return ListVal(join_avs(a.elem, b.elem), join_dims(a.length, b.length))
    if isinstance(a, DictVal) and isinstance(b, DictVal):
        if a.runtime == b.runtime:
            return a
        return DictVal(True, a.source or b.source)
    if isinstance(a, ArrayVal) and isinstance(b, ArrayVal):
        if len(a.shape) != len(b.shape):
            return OPAQUE
        return ArrayVal([join_dims(x, y) for x, y in zip(a.shape, b.shape)],
                        a.dtype if a.dtype == b.dtype else "?",
                        a.weak or b.weak)
    if isinstance(a, ScalarVal) and isinstance(b, ScalarVal):
        return ScalarVal(join_dims(a.dim, b.dim),
                         a.dtype if a.dtype == b.dtype else "?",
                         a.weak or b.weak)
    if isinstance(a, TableVal) and isinstance(b, TableVal):
        if a.name == b.name:
            return a
        return TableVal("", None)
    if isinstance(a, TableVal) and isinstance(b, TupleVal):
        return a
    if isinstance(a, TupleVal) and isinstance(b, TableVal):
        return b
    if isinstance(a, TupleVal) and isinstance(b, TupleVal) \
            and len(a.items) == len(b.items):
        return TupleVal([join_avs(x, y) for x, y in zip(a.items, b.items)])
    if isinstance(a, SelfVal) and isinstance(b, SelfVal):
        return a
    return OPAQUE


# ------------------------------------------------- teaching annotations

_TEACH_RE = re.compile(
    r"#\s*jaxlint:\s*(shape|dim)=([A-Za-z_][\w.]*):(\(.*\)|[^\s#]+)")

_DIM_TOKEN_RE = re.compile(
    r"^\s*(?:(\d+)|(\?)|config(?:\(([\w.]+)\))?|bucket\(([\w.]+)\)"
    r"|sym\(([\w.]+)\)|unbounded)\s*$")


def _parse_dim_token(tok: str) -> Optional[Dim]:
    m = _DIM_TOKEN_RE.match(tok)
    if not m:
        return None
    if m.group(1) is not None:
        return lit(int(m.group(1)))
    if m.group(2) is not None:
        return top_dim()
    if m.group(4) is not None:
        return bucket_dim(m.group(4))
    if m.group(5) is not None:
        return sym_dim(m.group(5))
    if "unbounded" in tok:
        return unbounded_dim("annotated")
    return config_dim(m.group(3) or "")


def parse_teachings(line: str) -> Dict[str, AV]:
    """Teaching annotations on one physical line -> name (possibly
    ``self.``-dotted) to abstract value."""
    out: Dict[str, AV] = {}
    for kind, name, spec in _TEACH_RE.findall(line or ""):
        if kind == "dim":
            d = _parse_dim_token(spec)
            if d is not None:
                out[name] = ScalarVal(d, "int")
        else:
            if not (spec.startswith("(") and spec.endswith(")")):
                continue
            dims = []
            body = spec[1:-1].strip()
            toks = [t for t in body.split(",") if t.strip()] if body else []
            ok = True
            for tok in toks:
                d = _parse_dim_token(tok)
                if d is None:
                    ok = False
                    break
                dims.append(d)
            if ok:
                out[name] = ArrayVal(dims)
    return out


# ------------------------------------------------------------ the eval

_NUMPY_PREFIXES = ("numpy.", "jax.numpy.")

#: unary elementwise ops: result has operand 0's shape
_UNARY_OPS = {
    "exp", "log", "log1p", "expm1", "sqrt", "square", "abs", "absolute",
    "tanh", "sin", "cos", "sign", "negative", "floor", "ceil", "clip",
    "nan_to_num", "logical_not", "copy", "round", "isnan", "isfinite",
    "cumsum", "cumprod", "sort", "tril", "triu", "relu", "gelu",
    "softmax", "log_softmax", "sigmoid", "stop_gradient",
}

_BINARY_OPS = {"maximum", "minimum", "add", "subtract", "multiply",
               "divide", "true_divide", "power", "mod", "equal",
               "not_equal", "greater", "greater_equal", "less",
               "less_equal", "logical_and", "logical_or", "arctan2"}

_REDUCTIONS = {"sum", "mean", "max", "min", "prod", "any", "all", "var",
               "std", "argmax", "argmin", "count_nonzero", "nanmean",
               "amax", "amin", "median"}

_SCALAR_CTORS = {"float32", "float64", "float16", "bfloat16", "int32",
                 "int64", "int16", "int8", "uint8", "uint32", "bool_"}


class FnShapes:
    """The result of abstractly executing one function body."""

    def __init__(self, types: Dict[int, AV], issues: List[Tuple[ast.AST, str, str]],
                 returns: List[AV]):
        self._types = types
        self.issues = issues
        self.returns = returns

    def at(self, node: ast.AST) -> AV:
        return self._types.get(id(node), OPAQUE)

    @property
    def return_value(self) -> AV:
        out: Optional[AV] = None
        for r in self.returns:
            out = r if out is None else join_avs(out, r)
        return out if out is not None else OPAQUE


class Interp:
    """Program-wide interpreter façade with the caches rules share."""

    MAX_DEPTH = 4

    def __init__(self, program):
        self.program = program
        self._in_progress: Set[int] = set()
        self._depth = 0
        self._module_envs: Dict[int, Dict[str, AV]] = {}
        self._node2fi: Dict[int, Dict[int, object]] = {}

    @classmethod
    def get(cls, program) -> "Interp":
        interp = program.cache.get("shapes:interp")
        if interp is None:
            interp = cls(program)
            program.cache["shapes:interp"] = interp
        return interp

    # -- module-level constants ------------------------------------
    def module_env(self, mi) -> Dict[str, AV]:
        env = self._module_envs.get(id(mi))
        if env is not None:
            return env
        env = {}
        for stmt in mi.tree.body:
            if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                continue
            name, v = stmt.targets[0].id, stmt.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int) \
                    and not isinstance(v.value, bool):
                env[name] = ScalarVal(lit(v.value), "int")
            elif isinstance(v, (ast.Tuple, ast.List)) and v.elts and all(
                    isinstance(e, ast.Constant) and isinstance(e.value, int)
                    and not isinstance(e.value, bool) for e in v.elts):
                env[name] = TableVal(name, len(v.elts),
                                     tuple(e.value for e in v.elts))
        self._module_envs[id(mi)] = env
        return env

    def _lookup_alias_const(self, mi, name: str) -> Optional[AV]:
        tgt = mi.aliases.get(name)
        if not tgt:
            return None
        parts = tgt.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mi2 = self.program.lookup_module(".".join(parts[:cut]))
            if mi2 is None:
                continue
            rest = parts[cut:]
            if len(rest) == 1:
                return self.module_env(mi2).get(rest[0])
            return None
        return None

    # -- class attribute models ------------------------------------
    def class_model(self, mi, cls: str) -> Dict[str, AV]:
        key = f"shapes:cls:{mi.module}:{cls}"
        model = self.program.cache.get(key)
        if model is not None:
            return model
        model = {}
        # in-progress-visible so recursive self.method() calls during
        # __init__ see the attrs bound so far instead of looping
        self.program.cache[key] = model
        fi = mi.functions.get(f"{cls}.__init__")
        if fi is not None:
            env: Dict[str, AV] = {"self": SelfVal(mi, cls)}
            args = fi.node.args
            for a in list(args.posonlyargs) + list(args.args) \
                    + list(args.kwonlyargs):
                if a.arg not in ("self", "cls"):
                    env[a.arg] = ParamVal(a.arg, config=True)
            _Eval(self, fi, env, attr_sink=model)
        return model

    # -- function evaluation ---------------------------------------
    def function_shapes(self, fi) -> FnShapes:
        key = f"shapes:fn:{id(fi)}"
        fs = self.program.cache.get(key)
        if fs is not None:
            return fs
        env: Dict[str, AV] = {}
        if fi.cls:
            env["self"] = SelfVal(fi.module, fi.cls)
        args = fi.node.args
        for a in list(args.posonlyargs) + list(args.args) \
                + list(args.kwonlyargs):
            if a.arg not in ("self", "cls"):
                env[a.arg] = ParamVal(a.arg)
        ev = _Eval(self, fi, env)
        fs = FnShapes(ev.types, ev.issues, ev.returns)
        self.program.cache[key] = fs
        return fs

    def call_summary(self, fi, bound: Dict[str, AV]) -> AV:
        """Abstract return value of calling ``fi`` with ``bound`` args."""
        if id(fi) in self._in_progress or self._depth >= self.MAX_DEPTH:
            return OPAQUE
        env: Dict[str, AV] = {}
        if fi.cls:
            env["self"] = bound.get("self", SelfVal(fi.module, fi.cls))
        args = fi.node.args
        for a in list(args.posonlyargs) + list(args.args) \
                + list(args.kwonlyargs):
            if a.arg in ("self", "cls"):
                continue
            env[a.arg] = bound.get(a.arg, ParamVal(a.arg))
        self._in_progress.add(id(fi))
        self._depth += 1
        try:
            ev = _Eval(self, fi, env)
        finally:
            self._depth -= 1
            self._in_progress.discard(id(fi))
        out: Optional[AV] = None
        for r in ev.returns:
            out = r if out is None else join_avs(out, r)
        return out if out is not None else OPAQUE

    def node_to_fi(self, mi) -> Dict[int, object]:
        m = self._node2fi.get(id(mi))
        if m is None:
            m = {id(f.node): f for f in mi.all_funcs}
            self._node2fi[id(mi)] = m
        return m


def function_shapes(program, fi) -> FnShapes:
    """Public entry: memoized abstract execution of one function."""
    return Interp.get(program).function_shapes(fi)


class _Eval(ForwardScan):
    """One function body, executed abstractly. Captures a type per
    expression node, provable shape issues, and return values."""

    bottom = None

    def __init__(self, interp: Interp, fi, env: Dict[str, AV],
                 attr_sink: Optional[Dict[str, AV]] = None):
        super().__init__()
        self.interp = interp
        self.program = interp.program
        self.fi = fi
        self.mi = fi.module
        self.resolve = self.mi.imports.resolve
        self.types: Dict[int, AV] = {}
        self.issues: List[Tuple[ast.AST, str, str]] = []
        self.returns: List[AV] = []
        self.attr_sink = attr_sink
        self._pending: Dict[str, AV] = {}
        self._stmt: Optional[ast.stmt] = None
        self._lines = self.mi.source.splitlines()
        for _ in self.scan(fi.node.body, env):
            pass

    # -- driver hooks ----------------------------------------------
    def scan(self, stmts, state):
        for stmt in stmts:
            self._stmt = stmt
            self._pending = {}
            yield from super().scan([stmt], state)
            self._finish(stmt, state)

    def _teachings(self, stmt) -> Dict[str, AV]:
        out: Dict[str, AV] = {}
        ln = getattr(stmt, "lineno", 0)
        for i in (ln - 1, ln):
            if 1 <= i <= len(self._lines):
                out.update(parse_teachings(self._lines[i - 1]))
        return out

    def _finish(self, stmt, state):
        if isinstance(stmt, ast.Return):
            self.returns.append(
                self.types.get(id(stmt.value), OPAQUE)
                if stmt.value is not None else OPAQUE)
        if isinstance(stmt, ast.Assign) and self.attr_sink is not None:
            v = self.types.get(id(stmt.value), OPAQUE)
            for t in stmt.targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    av = v
                    if isinstance(av, TableVal):
                        av = TableVal(t.attr, av.size)
                    prev = self.attr_sink.get(t.attr)
                    self.attr_sink[t.attr] = av if prev is None \
                        else join_avs(prev, av)
        for name, av in self._teachings(stmt).items():
            state[name] = av

    def kill(self, name, state):
        if name in self._pending:
            state[name] = self._pending[name]
        else:
            state.pop(name, None)

    def join_value(self, a, b):
        if a is None or b is None:
            return OPAQUE
        return join_avs(a, b)

    def visit_expr(self, expr, state):
        v = self.eval(expr, state)
        stmt = self._stmt
        if isinstance(stmt, ast.Assign) and expr is stmt.value:
            for t in stmt.targets:
                self._destructure(t, v)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)) and expr is stmt.iter:
            self._destructure(stmt.target, self._element_of(v, stmt))
        elif isinstance(stmt, ast.AnnAssign) and expr is stmt.value:
            self._destructure(stmt.target, v)
        return iter(())

    def _destructure(self, target, v: AV):
        if isinstance(target, ast.Name):
            self._pending[target.id] = v
        elif isinstance(target, (ast.Tuple, ast.List)):
            n = len(target.elts)
            items: List[AV] = [OPAQUE] * n
            if isinstance(v, TupleVal) and len(v.items) == n:
                items = list(v.items)
            elif isinstance(v, ListVal):
                items = [v.elem] * n
            elif isinstance(v, ArrayVal) and len(v.shape) == 1:
                items = [ScalarVal(top_dim(), "?")] * n
            for t, it in zip(target.elts, items):
                if isinstance(t, ast.Starred):
                    continue
                self._destructure(t, it)

    # -- expression evaluation -------------------------------------
    def eval(self, expr: Optional[ast.AST], state) -> AV:
        if expr is None:
            return OPAQUE
        v = self._eval_inner(expr, state)
        self.types[id(expr)] = v
        return v

    def _eval_inner(self, expr, state) -> AV:
        if isinstance(expr, ast.Constant):
            return self._const(expr.value)
        if isinstance(expr, ast.Name):
            return self._name(expr, state)
        if isinstance(expr, ast.Attribute):
            return self._attribute(expr, state)
        if isinstance(expr, ast.Subscript):
            return self._subscript(expr, state)
        if isinstance(expr, ast.Call):
            return self._call(expr, state)
        if isinstance(expr, ast.BinOp):
            return self._binop(expr, state)
        if isinstance(expr, ast.UnaryOp):
            v = self.eval(expr.operand, state)
            if isinstance(expr.op, ast.USub) and isinstance(v, ScalarVal) \
                    and v.dim.kind == LITERAL and v.dim.value is not None:
                return ScalarVal(lit(-v.dim.value), v.dtype, v.weak)
            if isinstance(expr.op, ast.Not):
                return ScalarVal(top_dim(), "bool")
            return v
        if isinstance(expr, ast.BoolOp):
            vals = [self.eval(v, state) for v in expr.values]
            out = vals[0]
            for v in vals[1:]:
                out = join_avs(out, v)
            # `x or default`: a table on either side keeps table-ness
            for v in vals:
                if isinstance(v, TableVal):
                    return TableVal(v.name, None)
            return out
        if isinstance(expr, ast.Compare):
            left = self.eval(expr.left, state)
            rights = [self.eval(c, state) for c in expr.comparators]
            for other in rights:
                if isinstance(left, ArrayVal) and isinstance(other, ArrayVal):
                    return ArrayVal(self._broadcast(left.shape, other.shape,
                                                    expr), "bool")
            if isinstance(left, ArrayVal):
                return ArrayVal(left.shape, "bool")
            for other in rights:
                if isinstance(other, ArrayVal):
                    return ArrayVal(other.shape, "bool")
            return ScalarVal(top_dim(), "bool")
        if isinstance(expr, (ast.Tuple, ast.List)):
            items = [self.eval(e, state) for e in expr.elts]
            if isinstance(expr, ast.Tuple) and len(items) > 1 \
                    and all(isinstance(e, ast.Constant)
                            and isinstance(e.value, int)
                            and not isinstance(e.value, bool)
                            for e in expr.elts) \
                    and self._looks_like_table(expr):
                return TableVal("", len(items),
                                tuple(e.value for e in expr.elts))
            return TupleVal(items)
        if isinstance(expr, ast.Dict):
            for k in expr.keys:
                if k is not None:
                    self.eval(k, state)
            for v in expr.values:
                self.eval(v, state)
            return DictVal(runtime=False)
        if isinstance(expr, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            return self._comprehension(expr, state)
        if isinstance(expr, ast.IfExp):
            self.eval(expr.test, state)
            return join_avs(self.eval(expr.body, state),
                            self.eval(expr.orelse, state))
        if isinstance(expr, ast.Starred):
            return self.eval(expr.value, state)
        if isinstance(expr, ast.NamedExpr):
            v = self.eval(expr.value, state)
            if isinstance(expr.target, ast.Name):
                self._pending[expr.target.id] = v
            return v
        if isinstance(expr, ast.JoinedStr):
            return OPAQUE
        if isinstance(expr, ast.Lambda):
            return OPAQUE
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self.eval(child, state)
        return OPAQUE

    @staticmethod
    def _looks_like_table(expr: ast.Tuple) -> bool:
        """A literal int tuple reads as a bucket table only when it is
        plausibly one: ≥2 distinct positive ints."""
        vals = [e.value for e in expr.elts]
        return len(set(vals)) >= 2 and all(v > 0 for v in vals)

    def _const(self, v) -> AV:
        if isinstance(v, bool):
            return ScalarVal(top_dim(), "bool")
        if isinstance(v, int):
            return ScalarVal(lit(v), "int")
        if isinstance(v, float):
            return ScalarVal(Dim(LITERAL, value=None, name=repr(v)), "float")
        return OPAQUE

    def _name(self, expr: ast.Name, state) -> AV:
        v = state.get(expr.id)
        if v is not None:
            return v
        if expr.id == "self" and self.fi.cls:
            return SelfVal(self.mi, self.fi.cls)
        v = self.interp.module_env(self.mi).get(expr.id)
        if v is not None:
            return v
        v = self.interp._lookup_alias_const(self.mi, expr.id)
        if v is not None:
            return v
        return OPAQUE

    def _attribute(self, expr: ast.Attribute, state) -> AV:
        # flow-sensitive self.X overrides (teaching annotations)
        if isinstance(expr.value, ast.Name) and expr.value.id == "self":
            dotted = f"self.{expr.attr}"
            if dotted in state:
                return state[dotted]
        base = self.eval(expr.value, state)
        if isinstance(base, SelfVal):
            model = self.interp.class_model(base.mi, base.cls)
            av = model.get(expr.attr)
            if av is not None:
                if isinstance(av, ParamVal):
                    return ScalarVal(config_dim(f"self.{expr.attr}"), "?")
                return av
            return OpaqueVal(f"self.{expr.attr}")
        if isinstance(base, ArrayVal):
            if expr.attr == "shape":
                return TupleVal([ScalarVal(d, "int") for d in base.shape])
            if expr.attr == "T":
                return ArrayVal(tuple(reversed(base.shape)), base.dtype)
            if expr.attr == "ndim":
                return ScalarVal(lit(len(base.shape)), "int")
            if expr.attr == "size":
                return ScalarVal(top_dim(), "int")
            if expr.attr == "dtype":
                return OpaqueVal(base.dtype)
        if isinstance(base, ParamVal):
            if expr.attr == "shape":
                return TupleVal([])  # rank unknown: handled by subscript
            return OpaqueVal(f"{base.name}.{expr.attr}")
        q = self.resolve(expr)
        if q == "os.environ":
            return DictVal(runtime=True, source="os.environ")
        return OPAQUE

    def _subscript(self, expr: ast.Subscript, state) -> AV:
        base = self.eval(expr.value, state)
        sl = expr.slice
        # x.shape[i] on a rank-unknown value -> sym
        if isinstance(expr.value, ast.Attribute) \
                and expr.value.attr == "shape" \
                and isinstance(base, TupleVal) and not base.items:
            src = ast.unparse(expr.value.value) if hasattr(ast, "unparse") \
                else "x"
            idx = self.eval(sl, state)
            i = idx.dim.value if isinstance(idx, ScalarVal) \
                and idx.dim.kind == LITERAL else "?"
            return ScalarVal(sym_dim(f"{src}.shape[{i}]"), "int")
        if isinstance(base, TupleVal):
            idx = self.eval(sl, state)
            if isinstance(idx, ScalarVal) and idx.dim.kind == LITERAL \
                    and idx.dim.value is not None \
                    and -len(base.items) <= idx.dim.value < len(base.items):
                return base.items[idx.dim.value]
            return OPAQUE
        if isinstance(base, TableVal):
            if isinstance(sl, ast.Slice):
                self.eval(sl.lower, state)
                self.eval(sl.upper, state)
                return TableVal(base.name, None)
            self.eval(sl, state)
            return base.element(origin=f"{base.name}[]")
        if isinstance(base, ListVal):
            if isinstance(sl, ast.Slice):
                return ListVal(base.elem, top_dim())
            self.eval(sl, state)
            return base.elem
        if isinstance(base, DictVal):
            self.eval(sl, state) if not isinstance(sl, ast.Slice) else None
            if base.runtime:
                src = base.source or "payload"
                return ScalarVal(unbounded_dim(f"{src}[...]"), "?")
            return OPAQUE
        if isinstance(base, ArrayVal):
            return self._index_array(base, sl, state)
        if not isinstance(sl, ast.Slice):
            self.eval(sl, state)
        return OPAQUE

    def _slice_dim(self, d: Dim, sl: ast.Slice, state) -> Dim:
        if sl.step is not None:
            self.eval(sl.step, state)
            return top_dim()
        lo, hi = sl.lower, sl.upper
        if lo is None and hi is None:
            return d
        lo_v = self.eval(lo, state) if lo is not None else None
        hi_v = self.eval(hi, state) if hi is not None else None
        if lo is not None and hi is not None:
            if isinstance(lo_v, ScalarVal) and isinstance(hi_v, ScalarVal) \
                    and lo_v.dim.kind == LITERAL and hi_v.dim.kind == LITERAL:
                return lit(max(hi_v.dim.value - lo_v.dim.value, 0))
            # the x[i:i+k] idiom: extent k regardless of i
            if isinstance(hi, ast.BinOp) and isinstance(hi.op, ast.Add) \
                    and isinstance(hi.right, ast.Constant) \
                    and isinstance(hi.right.value, int) \
                    and ast.dump(hi.left) == ast.dump(lo):
                return lit(hi.right.value)
            return top_dim()
        if lo is None and isinstance(hi_v, ScalarVal):
            return hi_v.dim if hi_v.dim.kind != LITERAL else \
                lit(hi_v.dim.value)
        return top_dim()

    def _index_array(self, base: ArrayVal, sl, state) -> AV:
        items = list(sl.elts) if isinstance(sl, ast.Tuple) else [sl]
        dims = list(base.shape)
        out: List[Dim] = []
        i = 0
        for it in items:
            if isinstance(it, ast.Constant) and it.value is None:
                out.append(lit(1))
                continue
            if isinstance(it, ast.Constant) and it.value is Ellipsis:
                return OPAQUE
            if i >= len(dims):
                return OPAQUE
            if isinstance(it, ast.Slice):
                out.append(self._slice_dim(dims[i], it, state))
                i += 1
                continue
            v = self.eval(it, state)
            if isinstance(v, ArrayVal):
                if v.dtype == "bool":
                    out.append(unbounded_dim("boolean mask"))
                else:
                    out.extend(v.shape)
            i += 1
        out.extend(dims[i:])
        return ArrayVal(out, base.dtype, base.weak)

    # -- broadcasting ----------------------------------------------
    def _broadcast(self, sa: Sequence[Dim], sb: Sequence[Dim],
                   node: ast.AST) -> Tuple[Dim, ...]:
        la, lb = list(sa), list(sb)
        n = max(len(la), len(lb))
        la = [lit(1)] * (n - len(la)) + la
        lb = [lit(1)] * (n - len(lb)) + lb
        out: List[Dim] = []
        for a, b in zip(la, lb):
            if a.kind == LITERAL and a.value == 1:
                out.append(b)
            elif b.kind == LITERAL and b.value == 1:
                out.append(a)
            elif a.kind == LITERAL and b.kind == LITERAL:
                if a.value != b.value:
                    self.issues.append((
                        node, "broadcast",
                        f"provable broadcast mismatch: {render_shape(sa)} vs "
                        f"{render_shape(sb)} (dim {a.value} != {b.value}, "
                        "neither is 1)"))
                    out.append(top_dim())
                else:
                    out.append(a)
            elif a.same(b):
                out.append(a)
            else:
                out.append(join_dims(a, b))
        return tuple(out)

    def _binop(self, expr: ast.BinOp, state) -> AV:
        a = self.eval(expr.left, state)
        b = self.eval(expr.right, state)
        if isinstance(expr.op, ast.MatMult):
            return self._matmul(a, b, True, expr)
        if isinstance(a, ArrayVal) and isinstance(b, ArrayVal):
            shape = self._broadcast(a.shape, b.shape, expr)
            return ArrayVal(shape, promote_dtypes(a.dtype, b.dtype, b.weak))
        if isinstance(a, ArrayVal) and isinstance(b, ScalarVal):
            return ArrayVal(a.shape, promote_dtypes(a.dtype, b.dtype, b.weak))
        if isinstance(b, ArrayVal) and isinstance(a, ScalarVal):
            return ArrayVal(b.shape, promote_dtypes(b.dtype, a.dtype, a.weak))
        if isinstance(a, ScalarVal) and isinstance(b, ScalarVal):
            return self._scalar_binop(a, b, expr.op)
        # list/tuple concatenation feeds bucket-table construction
        if isinstance(expr.op, ast.Add):
            for x, y in ((a, b), (b, a)):
                if isinstance(x, (ListVal, TableVal)) \
                        and isinstance(y, (ListVal, TupleVal, TableVal)):
                    return ListVal(
                        x.elem if isinstance(x, ListVal)
                        else ScalarVal(top_dim(), "int"), top_dim())
        return OPAQUE

    @staticmethod
    def _scalar_binop(a: ScalarVal, b: ScalarVal, op) -> ScalarVal:
        dtype = "float" if (a.dtype == "float" or b.dtype == "float"
                            or isinstance(op, ast.Div)) else \
            (a.dtype if a.dtype == b.dtype else "?")
        da, db = a.dim, b.dim
        if da.kind == LITERAL and db.kind == LITERAL \
                and da.value is not None and db.value is not None:
            try:
                fn = {ast.Add: lambda x, y: x + y,
                      ast.Sub: lambda x, y: x - y,
                      ast.Mult: lambda x, y: x * y,
                      ast.FloorDiv: lambda x, y: x // y,
                      ast.Mod: lambda x, y: x % y,
                      ast.Pow: lambda x, y: x ** y}.get(type(op))
                if fn is not None:
                    return ScalarVal(lit(fn(da.value, db.value)), dtype,
                                     a.weak and b.weak)
            except (ZeroDivisionError, OverflowError, ValueError):
                pass
        for d, other in ((da, db), (db, da)):
            if d.kind == UNBOUNDED:
                return ScalarVal(unbounded_dim(d.name), dtype)
            if d.kind == BUCKET and other.kind in (LITERAL, CONFIG):
                # arithmetic on a bucket value stays |table|-valued
                return ScalarVal(Dim(BUCKET, table=d.table, size=d.size,
                                     origin=d.origin), dtype)
        for d, other in ((da, db), (db, da)):
            if d.kind == CONFIG and other.kind in (LITERAL, CONFIG):
                return ScalarVal(config_dim(d.name), dtype)
            if d.kind == SYM and other.kind in (LITERAL, CONFIG, SYM):
                return ScalarVal(sym_dim(d.name), dtype)
        return ScalarVal(top_dim(), dtype)

    # -- comprehensions --------------------------------------------
    def _comprehension(self, expr, state) -> AV:
        inner = dict(state)
        length: Dim = top_dim()
        for k, gen in enumerate(expr.generators):
            it = self.eval(gen.iter, inner)
            elem = self._element_of(it, expr)
            if k == 0:
                length = self._len_dim(it)
                if gen.ifs:
                    length = top_dim()
            self._pending = {}
            self._destructure(gen.target, elem)
            for name, v in self._pending.items():
                inner[name] = v
            for cond in gen.ifs:
                self.eval(cond, inner)
        self._pending = {}
        elt = self.eval(expr.elt, inner)
        return ListVal(elt, length)

    def _element_of(self, av: AV, node) -> AV:
        if isinstance(av, TableVal):
            return av.element(origin=f"{av.name}@{getattr(node, 'lineno', 0)}")
        if isinstance(av, ListVal):
            return av.elem
        if isinstance(av, TupleVal):
            out: Optional[AV] = None
            for it in av.items:
                out = it if out is None else join_avs(out, it)
            return out if out is not None else OPAQUE
        if isinstance(av, ArrayVal) and av.shape:
            return ArrayVal(av.shape[1:], av.dtype)
        if isinstance(av, DictVal) and av.runtime:
            return ScalarVal(unbounded_dim(av.source or "payload"), "?")
        return OPAQUE

    def _len_dim(self, av: AV) -> Dim:
        if isinstance(av, ArrayVal) and av.shape:
            return av.shape[0]
        if isinstance(av, TableVal):
            return lit(av.size) if av.size is not None else \
                config_dim(f"|{av.name}|")
        if isinstance(av, ListVal):
            return av.length
        if isinstance(av, TupleVal):
            return lit(len(av.items))
        if isinstance(av, ParamVal):
            return config_dim(av.name) if av.config else sym_dim(
                f"len({av.name})")
        if isinstance(av, DictVal) and av.runtime:
            return unbounded_dim(f"len({av.source or 'payload'})")
        return top_dim()

    # -- calls ------------------------------------------------------
    def _eval_args(self, node: ast.Call, state) -> Tuple[List[AV],
                                                         Dict[str, AV]]:
        pos = [self.eval(a, state) for a in node.args]
        kw = {k.arg: self.eval(k.value, state) for k in node.keywords
              if k.arg is not None}
        for k in node.keywords:
            if k.arg is None:
                self.eval(k.value, state)
        return pos, kw

    def _dtype_from(self, node: Optional[ast.AST],
                    av: Optional[AV]) -> str:
        if node is None:
            return "?"
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return canon_dtype(node.value)
        q = self.resolve(node)
        if q:
            leaf = q.rsplit(".", 1)[-1]
            if leaf in _DTYPE_CANON:
                return canon_dtype(leaf)
        if isinstance(node, ast.Name) and node.id in ("float", "int", "bool"):
            return canon_dtype(node.id)
        return "?"

    def _shape_from(self, av: AV) -> Optional[List[Dim]]:
        if isinstance(av, TupleVal):
            return [as_dim(it) for it in av.items]
        if isinstance(av, (ScalarVal, ParamVal)):
            return [as_dim(av)]
        if isinstance(av, TableVal):
            # a literal int tuple doubles as a table; in shape position
            # its members ARE the literal dims
            if av.values is not None:
                return [lit(v) for v in av.values]
            return None
        if isinstance(av, ListVal):
            return None
        return None

    def _as_array(self, av: AV, jnp: bool) -> AV:
        if isinstance(av, ArrayVal):
            return av
        if isinstance(av, ScalarVal):
            dt = av.dtype
            if dt == "int":
                dt = "i64" if not jnp else "i32"
            elif dt == "float":
                dt = "f64" if not jnp else "f32"
            return ArrayVal([], dt, weak=av.weak)
        if isinstance(av, ListVal):
            if isinstance(av.elem, ArrayVal):
                return ArrayVal([av.length] + list(av.elem.shape),
                                av.elem.dtype)
            if isinstance(av.elem, ScalarVal):
                return ArrayVal([av.length], "?")
            return OPAQUE
        if isinstance(av, TupleVal):
            if av.items and all(isinstance(i, ScalarVal) for i in av.items):
                return ArrayVal([lit(len(av.items))], "?")
            if av.items and all(isinstance(i, ArrayVal) for i in av.items):
                first = av.items[0]
                return ArrayVal([lit(len(av.items))] + list(first.shape),
                                first.dtype)
        if isinstance(av, TableVal):
            return ArrayVal([self._len_dim(av)], "i64")
        return OPAQUE

    def _call(self, node: ast.Call, state) -> AV:
        fn = node.func
        q = self.resolve(fn) or ""
        pos, kw = self._eval_args(node, state)

        if isinstance(fn, ast.Name):
            v = self._builtin_call(fn.id, node, pos, kw, state)
            if v is not None:
                return v

        # numpy / jax.numpy / jax.lax / jax.nn namespaces
        op = None
        jnp = False
        for pref in _NUMPY_PREFIXES:
            if q.startswith(pref):
                op = q[len(pref):]
                jnp = pref != "numpy."
                break
        if op is None and q.startswith("jax.lax."):
            op = q[len("jax.lax."):]
            jnp = True
        if op is None and q.startswith("jax.nn."):
            op = q[len("jax.nn."):]
            jnp = True
        if op is not None:
            v = self._numpy_call(op, jnp, node, pos, kw, state)
            if v is not None:
                return v

        if q in ("json.loads", "json.load"):
            return DictVal(runtime=True, source="json.loads")
        if q in ("os.getenv", "os.environ.get"):
            return ScalarVal(unbounded_dim("os.environ"), "?")

        # method calls on evaluated receivers
        if isinstance(fn, ast.Attribute):
            recv = self.types.get(id(fn.value))
            if recv is None:
                recv = self.eval(fn.value, state)
            v = self._method_call(recv, fn.attr, node, pos, kw)
            if v is not None:
                return v

        # user functions through the program call graph
        callee = self.program.resolve_call(
            self.mi, fn, self.mi.enclosing_class(node))
        if callee is not None and callee.node is not self.fi.node:
            bound: Dict[str, AV] = {}
            for i, a in enumerate(node.args):
                if isinstance(a, ast.Starred):
                    break
                if i < len(callee.params):
                    bound[callee.params[i]] = pos[i]
            for k in node.keywords:
                if k.arg and k.arg in kw:
                    bound[k.arg] = kw[k.arg]
            if isinstance(fn, ast.Attribute) and isinstance(
                    fn.value, ast.Name) and fn.value.id == "self":
                recv = state.get("self")
                if isinstance(recv, SelfVal):
                    bound["self"] = recv
            return self.interp.call_summary(callee, bound)
        return OPAQUE

    def _builtin_call(self, name: str, node: ast.Call, pos: List[AV],
                      kw: Dict[str, AV], state) -> Optional[AV]:
        if name == "len" and pos:
            return ScalarVal(self._len_dim(pos[0]), "int")
        if name in ("int", "float", "bool") and pos:
            a = pos[0]
            d = a.dim if isinstance(a, ScalarVal) else as_dim(a)
            return ScalarVal(d, "int" if name == "int" else name, weak=True)
        if name in ("min", "max"):
            if len(pos) == 1:
                a = pos[0]
                if isinstance(a, TableVal):
                    return a.element(origin=f"{name}({a.name})")
                if isinstance(a, ListVal):
                    return a.elem
                return OPAQUE
            out: Optional[AV] = None
            for a in pos:
                out = a if out is None else join_avs(out, a)
            return out or OPAQUE
        if name == "next" and node.args:
            first = pos[0]
            out = first.elem if isinstance(first, ListVal) else OPAQUE
            if len(pos) > 1:
                out = join_avs(out, pos[1])
            return out
        if name == "range" and pos:
            n = pos[-1] if len(pos) <= 1 else pos[1]
            return ListVal(ScalarVal(top_dim(), "int"),
                           as_dim(n) if len(pos) == 1 else top_dim())
        if name in ("tuple", "sorted", "list", "set", "frozenset") and pos:
            a = pos[0]
            if isinstance(a, TableVal):
                return a
            if isinstance(a, ListVal):
                if isinstance(a.elem, ScalarVal) and a.elem.dtype == "int":
                    return TableVal("", a.length.value
                                    if a.length.kind == LITERAL else None)
                return a
            if isinstance(a, TupleVal):
                return a
            return OPAQUE
        if name in ("sum", "abs", "round") and pos:
            a = pos[0]
            if isinstance(a, (ScalarVal, ArrayVal)):
                return a
            return ScalarVal(top_dim(), "?")
        if name == "enumerate" and pos:
            return ListVal(TupleVal([ScalarVal(top_dim(), "int"),
                                     self._element_of(pos[0], node)]),
                           self._len_dim(pos[0]))
        if name == "zip" and pos:
            return ListVal(TupleVal([self._element_of(a, node) for a in pos]),
                           self._len_dim(pos[0]))
        if name in ("print", "isinstance", "hasattr", "getattr", "repr",
                    "str", "format", "id", "iter", "callable", "setattr",
                    "vars", "dir", "type", "super", "open", "input",
                    "divmod", "hash", "map", "filter", "all", "any"):
            return OPAQUE
        return None

    def _numpy_call(self, op: str, jnp: bool, node: ast.Call,
                    pos: List[AV], kw: Dict[str, AV],
                    state) -> Optional[AV]:
        def dtype_arg(idx: int, kwname: str = "dtype") -> str:
            for k in node.keywords:
                if k.arg == kwname:
                    return self._dtype_from(k.value, kw.get(kwname))
            if idx < len(node.args):
                return self._dtype_from(node.args[idx], pos[idx])
            return "?"

        def axis_arg(idx: int) -> Optional[int]:
            for k in node.keywords:
                if k.arg == "axis" and isinstance(k.value, ast.Constant) \
                        and isinstance(k.value.value, int):
                    return k.value.value
            if idx < len(node.args):
                a = node.args[idx]
                if isinstance(a, ast.Constant) and isinstance(a.value, int):
                    return a.value
            return None

        def keepdims() -> bool:
            for k in node.keywords:
                if k.arg == "keepdims" and isinstance(k.value, ast.Constant):
                    return bool(k.value.value)
            return False

        if op in ("zeros", "ones", "empty") and pos:
            shape = self._shape_from(pos[0])
            if shape is None:
                return OPAQUE
            dt = dtype_arg(1)
            return ArrayVal(shape, dt if dt != "?" else
                            ("f32" if jnp else "f64"))
        if op == "full" and pos:
            shape = self._shape_from(pos[0])
            if shape is None:
                return OPAQUE
            return ArrayVal(shape, dtype_arg(2))
        if op in ("zeros_like", "ones_like", "full_like",
                  "empty_like") and pos:
            a = self._as_array(pos[0], jnp)
            if isinstance(a, ArrayVal):
                dt = dtype_arg(2 if op == "full_like" else 1)
                return ArrayVal(a.shape, dt if dt != "?" else a.dtype)
            return OPAQUE
        if op in ("asarray", "array", "ascontiguousarray") and pos:
            a = self._as_array(pos[0], jnp)
            if isinstance(a, ArrayVal):
                dt = dtype_arg(1)
                return ArrayVal(a.shape, dt if dt != "?" else a.dtype,
                                a.weak)
            return OPAQUE
        if op in _SCALAR_CTORS and pos:
            a = pos[0]
            dt = canon_dtype(op)
            if isinstance(a, ArrayVal):
                return ArrayVal(a.shape, dt)
            if isinstance(a, ScalarVal):
                return ScalarVal(a.dim, dt, weak=False)
            return ScalarVal(as_dim(a), dt, weak=False)
        if op == "arange":
            if len(pos) == 1:
                return ArrayVal([as_dim(pos[0])],
                                "i32" if jnp else "i64")
            return ArrayVal([top_dim()], "?")
        if op == "linspace" and len(pos) >= 3:
            return ArrayVal([as_dim(pos[2])], "f32" if jnp else "f64")
        if op in ("concatenate", "concat", "vstack", "hstack") and pos:
            return self._concat(pos[0], axis_arg(1) or 0, node)
        if op == "stack" and pos:
            a = pos[0]
            axis = axis_arg(1) or 0
            if isinstance(a, (TupleVal, ListVal)):
                elem = self._element_of(a, node)
                if isinstance(elem, ArrayVal):
                    dims = list(elem.shape)
                    if 0 <= axis <= len(dims):
                        dims.insert(axis, self._len_dim(a))
                        return ArrayVal(dims, elem.dtype)
            return OPAQUE
        if op == "where" and len(pos) == 3:
            c = self._as_array(pos[0], jnp)
            a = self._as_array(pos[1], jnp)
            b = self._as_array(pos[2], jnp)
            arrs = [x for x in (c, a, b) if isinstance(x, ArrayVal)]
            if not arrs:
                return OPAQUE
            shape = arrs[0].shape
            for x in arrs[1:]:
                shape = self._broadcast(shape, x.shape, node)
            dt = "?"
            if isinstance(a, ArrayVal) and isinstance(b, ArrayVal):
                dt = promote_dtypes(a.dtype, b.dtype, b.weak)
            elif isinstance(a, ArrayVal):
                dt = a.dtype
            return ArrayVal(shape, dt)
        if op == "broadcast_to" and len(pos) >= 2:
            shape = self._shape_from(pos[1])
            if shape is None:
                return OPAQUE
            a = self._as_array(pos[0], jnp)
            return ArrayVal(shape,
                            a.dtype if isinstance(a, ArrayVal) else "?")
        if op == "reshape" and len(pos) >= 2:
            shape = self._shape_from(pos[1])
            a = self._as_array(pos[0], jnp)
            if shape is None:
                return OPAQUE
            shape = [top_dim() if (d.kind == LITERAL and d.value == -1)
                     else d for d in shape]
            return ArrayVal(shape,
                            a.dtype if isinstance(a, ArrayVal) else "?")
        if op == "pad" and len(pos) >= 2:
            return self._pad(pos[0], pos[1], jnp)
        if op == "transpose" and pos:
            a = self._as_array(pos[0], jnp)
            if isinstance(a, ArrayVal):
                return ArrayVal(tuple(reversed(a.shape)), a.dtype)
            return OPAQUE
        if op == "swapaxes" and len(pos) == 3:
            a = self._as_array(pos[0], jnp)
            i, j = pos[1], pos[2]
            if isinstance(a, ArrayVal) and isinstance(i, ScalarVal) \
                    and isinstance(j, ScalarVal) \
                    and i.dim.kind == LITERAL and j.dim.kind == LITERAL:
                dims = list(a.shape)
                try:
                    dims[i.dim.value], dims[j.dim.value] = \
                        dims[j.dim.value], dims[i.dim.value]
                    return ArrayVal(dims, a.dtype)
                except IndexError:
                    return OPAQUE
            return OPAQUE
        if op == "expand_dims" and len(pos) >= 2:
            a = self._as_array(pos[0], jnp)
            ax = axis_arg(1)
            if isinstance(a, ArrayVal) and ax is not None \
                    and -len(a.shape) - 1 <= ax <= len(a.shape):
                dims = list(a.shape)
                dims.insert(ax if ax >= 0 else len(dims) + 1 + ax, lit(1))
                return ArrayVal(dims, a.dtype)
            return OPAQUE
        if op == "squeeze" and pos:
            a = self._as_array(pos[0], jnp)
            ax = axis_arg(1)
            if isinstance(a, ArrayVal):
                if ax is not None and -len(a.shape) <= ax < len(a.shape):
                    dims = list(a.shape)
                    dims.pop(ax)
                    return ArrayVal(dims, a.dtype)
                return ArrayVal([d for d in a.shape
                                 if not (d.kind == LITERAL and d.value == 1)],
                                a.dtype)
            return OPAQUE
        if op in _REDUCTIONS and pos:
            a = self._as_array(pos[0], jnp)
            if not isinstance(a, ArrayVal):
                return OPAQUE
            dt = "i32" if op in ("argmax", "argmin", "count_nonzero") \
                else a.dtype
            ax = axis_arg(1)
            if ax is None and not any(k.arg == "axis"
                                      for k in node.keywords):
                return ArrayVal([], dt)
            if ax is not None and -len(a.shape) <= ax < len(a.shape):
                dims = list(a.shape)
                if keepdims():
                    dims[ax] = lit(1)
                else:
                    dims.pop(ax)
                return ArrayVal(dims, dt)
            return OPAQUE
        if op in _BINARY_OPS and len(pos) >= 2:
            a = self._as_array(pos[0], jnp)
            b = self._as_array(pos[1], jnp)
            if isinstance(a, ArrayVal) and isinstance(b, ArrayVal):
                boolish = op in ("equal", "not_equal", "greater", "less",
                                 "greater_equal", "less_equal",
                                 "logical_and", "logical_or")
                return ArrayVal(self._broadcast(a.shape, b.shape, node),
                                "bool" if boolish
                                else promote_dtypes(a.dtype, b.dtype,
                                                    b.weak))
            return OPAQUE
        if op in _UNARY_OPS and pos:
            a = self._as_array(pos[0], jnp)
            if isinstance(a, ArrayVal):
                dt = "bool" if op in ("isnan", "isfinite") else a.dtype
                return ArrayVal(a.shape, dt)
            if isinstance(pos[0], ScalarVal):
                return pos[0]
            return OPAQUE
        if op in ("matmul", "dot") and len(pos) >= 2:
            return self._matmul(pos[0], pos[1], jnp, node)
        if op == "einsum":
            return OPAQUE
        if op in ("take",) and len(pos) >= 2:
            a = self._as_array(pos[0], jnp)
            idx = self._as_array(pos[1], jnp)
            ax = axis_arg(2)
            if isinstance(a, ArrayVal) and isinstance(idx, ArrayVal) \
                    and ax is not None and -len(a.shape) <= ax < len(a.shape):
                dims = list(a.shape)
                dims[ax:ax + 1] = list(idx.shape)
                return ArrayVal(dims, a.dtype)
            return OPAQUE
        if op == "take_along_axis" and len(pos) >= 2:
            idx = self._as_array(pos[1], jnp)
            a = self._as_array(pos[0], jnp)
            if isinstance(idx, ArrayVal):
                return ArrayVal(idx.shape,
                                a.dtype if isinstance(a, ArrayVal) else "?")
            return OPAQUE
        if op == "repeat" and len(pos) >= 2:
            a = self._as_array(pos[0], jnp)
            n = pos[1]
            ax = axis_arg(2)
            if isinstance(a, ArrayVal) and ax is not None \
                    and isinstance(n, ScalarVal) \
                    and -len(a.shape) <= ax < len(a.shape):
                dims = list(a.shape)
                d = dims[ax]
                if d.kind == LITERAL and n.dim.kind == LITERAL:
                    dims[ax] = lit(d.value * n.dim.value)
                else:
                    dims[ax] = top_dim()
                return ArrayVal(dims, a.dtype)
            return OPAQUE
        if op == "split" and len(pos) >= 2:
            a = self._as_array(pos[0], jnp)
            if isinstance(a, ArrayVal):
                ax = axis_arg(2) or 0
                dims = list(a.shape)
                if -len(dims) <= ax < len(dims):
                    dims[ax] = top_dim()
                return ListVal(ArrayVal(dims, a.dtype), as_dim(pos[1]))
            return OPAQUE
        if op == "dynamic_update_slice" and pos:
            return self._as_array(pos[0], jnp)
        if op == "dynamic_slice" and len(pos) >= 3:
            shape = self._shape_from(pos[2])
            if shape is not None:
                a = self._as_array(pos[0], jnp)
                return ArrayVal(shape, a.dtype
                                if isinstance(a, ArrayVal) else "?")
            return OPAQUE
        if op == "top_k" and len(pos) >= 2:
            a = self._as_array(pos[0], jnp)
            if isinstance(a, ArrayVal) and a.shape:
                dims = list(a.shape)
                dims[-1] = as_dim(pos[1])
                return TupleVal([ArrayVal(dims, a.dtype),
                                 ArrayVal(dims, "i32")])
            return OPAQUE
        if op == "one_hot" and len(pos) >= 2:
            a = self._as_array(pos[0], jnp)
            if isinstance(a, ArrayVal):
                return ArrayVal(list(a.shape) + [as_dim(pos[1])], "f32")
            return OPAQUE
        if op in ("scan", "while_loop", "cond", "fori_loop", "dot_general",
                  "conv_general_dilated", "reduce_window", "switch",
                  "associative_scan", "map"):
            return OPAQUE
        return None

    def _concat(self, seq: AV, axis: int, node) -> AV:
        if isinstance(seq, TupleVal) and seq.items and all(
                isinstance(i, ArrayVal) for i in seq.items):
            arrs: List[ArrayVal] = list(seq.items)  # type: ignore
            rank = len(arrs[0].shape)
            if any(len(a.shape) != rank for a in arrs) or rank == 0 \
                    or not (-rank <= axis < rank):
                return OPAQUE
            ax = axis if axis >= 0 else rank + axis
            out: List[Dim] = []
            for i in range(rank):
                ds = [a.shape[i] for a in arrs]
                if i == ax:
                    if all(d.kind == LITERAL for d in ds):
                        out.append(lit(sum(d.value for d in ds)))
                    elif any(d.kind == UNBOUNDED for d in ds):
                        out.append(unbounded_dim("concat"))
                    elif len(ds) == 1:
                        out.append(ds[0])
                    else:
                        out.append(top_dim())
                else:
                    d0 = ds[0]
                    for d in ds[1:]:
                        if d0.kind == LITERAL and d.kind == LITERAL \
                                and d0.value != d.value:
                            self.issues.append((
                                node, "concat-axis",
                                f"concatenate along axis {ax}: non-concat "
                                f"dim {i} provably differs "
                                f"({d0.value} vs {d.value})"))
                        d0 = join_dims(d0, d)
                    out.append(d0)
            return ArrayVal(out, arrs[0].dtype)
        if isinstance(seq, ListVal):
            if isinstance(seq.elem, ArrayVal) and seq.elem.shape:
                dims = list(seq.elem.shape)
                L, d0 = seq.length, dims[axis] if -len(dims) <= axis \
                    < len(dims) else top_dim()
                if L.kind == LITERAL and d0.kind == LITERAL:
                    dims[axis] = lit(L.value * d0.value)
                elif L.kind == UNBOUNDED:
                    dims[axis] = unbounded_dim(L.name or "concat")
                else:
                    dims[axis] = top_dim()
                return ArrayVal(dims, seq.elem.dtype)
            return OPAQUE
        return OPAQUE

    def _pad(self, a_av: AV, widths: AV, jnp: bool) -> AV:
        a = self._as_array(a_av, jnp)
        if not isinstance(a, ArrayVal):
            return OPAQUE
        if isinstance(widths, TupleVal) and len(widths.items) == \
                len(a.shape):
            dims: List[Dim] = []
            for d, w in zip(a.shape, widths.items):
                total: Optional[int] = None
                if isinstance(w, TupleVal) and len(w.items) == 2 and all(
                        isinstance(x, ScalarVal)
                        and x.dim.kind == LITERAL for x in w.items):
                    total = sum(x.dim.value for x in w.items)  # type: ignore
                if total == 0:
                    dims.append(d)
                elif total is not None and d.kind == LITERAL:
                    dims.append(lit(d.value + total))
                else:
                    dims.append(top_dim())
            return ArrayVal(dims, a.dtype)
        return ArrayVal([top_dim()] * len(a.shape), a.dtype)

    def _matmul(self, a_av: AV, b_av: AV, jnp: bool, node) -> AV:
        a = self._as_array(a_av, jnp)
        b = self._as_array(b_av, jnp)
        if not (isinstance(a, ArrayVal) and isinstance(b, ArrayVal)):
            return OPAQUE
        if len(a.shape) < 1 or len(b.shape) < 1:
            return OPAQUE
        ka = a.shape[-1]
        kb = b.shape[-2] if len(b.shape) >= 2 else b.shape[0]
        if ka.kind == LITERAL and kb.kind == LITERAL and ka.value != kb.value:
            self.issues.append((
                node, "dot",
                f"matmul contraction mismatch: {render_shape(a.shape)} @ "
                f"{render_shape(b.shape)} (inner {ka.value} != {kb.value})"))
        if len(a.shape) == 1 and len(b.shape) == 1:
            return ArrayVal([], promote_dtypes(a.dtype, b.dtype))
        lead = list(a.shape[:-1]) if len(a.shape) > 1 else []
        tail = list(b.shape[-1:]) if len(b.shape) > 1 else []
        return ArrayVal(lead + tail, promote_dtypes(a.dtype, b.dtype))

    def _method_call(self, recv: AV, name: str, node: ast.Call,
                     pos: List[AV], kw: Dict[str, AV]) -> Optional[AV]:
        if isinstance(recv, ArrayVal):
            if name == "astype" and pos:
                return ArrayVal(recv.shape,
                                self._dtype_from(node.args[0], pos[0]))
            if name == "reshape":
                if len(pos) == 1:
                    shape = self._shape_from(pos[0])
                else:
                    shape = [as_dim(p) for p in pos]
                if shape is None:
                    return OPAQUE
                return ArrayVal([top_dim() if (d.kind == LITERAL
                                               and d.value == -1) else d
                                 for d in shape], recv.dtype)
            if name == "copy":
                return recv
            if name in ("item",):
                return ScalarVal(top_dim(), recv.dtype, weak=True)
            if name == "tolist":
                return ListVal(ScalarVal(top_dim(), "?"),
                               recv.shape[0] if recv.shape else top_dim())
            if name in ("flatten", "ravel"):
                return ArrayVal([top_dim()], recv.dtype)
            if name in ("squeeze", "transpose", "sum", "mean", "max", "min",
                        "prod", "any", "all", "argmax", "argmin", "clip"):
                # reuse the function-form transfer
                return self._numpy_call(
                    name if name != "clip" else "clip", True, node,
                    [recv] + pos, kw, None)
        if isinstance(recv, DictVal) and name == "get":
            if recv.runtime:
                return ScalarVal(
                    unbounded_dim(f"{recv.source or 'payload'}.get"), "?")
            return OPAQUE
        if isinstance(recv, TableVal) and name == "index":
            return ScalarVal(top_dim(), "int")
        if isinstance(recv, ListVal) and name in ("pop",):
            return recv.elem
        return None
