"""Lock model for jaxlint's concurrency rules.

Classic static deadlock/blocking analysis in the Eraser / lock-order-graph
tradition, adapted to this codebase's idiom: every lock is an attribute
bound once in ``__init__`` (``self._lock = threading.Lock()``) or a
module-level constant, and every acquisition is a ``with`` block (plus the
occasional explicit ``.acquire()``). Lock *identity* is therefore nominal:

- ``<module>.<Class>.<attr>`` for instance locks — one identity per
  (class, attribute), not per object. Two instances of the same class
  share an identity, so self-edges are never reported (an RLock re-enter
  and a two-instance ABBA look identical at this resolution);
- ``<module>.<NAME>`` for module-level locks.

On top of identity the model computes, to a fixpoint over the typed call
graph (:mod:`.typeinfo` resolves ``self._pager.ensure(...)``-style edges
the core resolver cannot):

- ``acquires``: every lock a function may take, directly or transitively;
- ``block_chain``: a witness chain ("f calls g (line n); g: time.sleep")
  when a function may block — socket/HTTP I/O, ``time.sleep``,
  ``block_until_ready``, device transfers, ``subprocess``,
  ``Event.wait``/``Thread.join``, ``Condition.wait``;
- the **lock-order graph**: an edge A -> B with a witness site whenever a
  function holding A acquires B (directly or through a callee). Cycles in
  this graph are potential ABBA deadlocks.

``Condition.wait`` releases the condition it waits on, so waiting on the
*held* condition is the sanctioned wait-loop idiom and is exempt at the
direct site — but the function still blocks its callers, so the fact
propagates. A helper that deliberately blocks under its own discipline
(the pager's reserve-under-lock / transfer-outside-it pattern) opts out
with a sanction comment on its ``def`` line::

    def ensure(self, name):  # jaxlint: sanction=blocking-call-under-lock

Sanctioning clears the helper's blocking summary for callers *and* skips
its body — unlike ``disable=``, which only mutes one report line. Use it
for helpers whose blocking is a designed contract, with a justification
comment alongside.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .typeinfo import LOCK_CTORS, dotted_expr, get_types

_LOCKS_CACHE = "locks:model"

_SANCTION_RE = re.compile(r"#\s*jaxlint:\s*sanction=([A-Za-z0-9_\-, ]+)")

#: dotted-path prefixes that block the calling thread on I/O or a child
_BLOCKING_PREFIXES = ("socket.", "urllib.request.", "http.client.",
                      "requests.", "subprocess.")

#: exact dotted paths that block
_BLOCKING_CALLS = {"time.sleep", "jax.device_put", "jax.device_get",
                   "subprocess.run", "subprocess.check_output"}


class BlockSite:
    """One direct blocking operation inside a function."""

    __slots__ = ("node", "desc", "exempt_lock")

    def __init__(self, node: ast.AST, desc: str,
                 exempt_lock: Optional[str] = None):
        self.node = node
        self.desc = desc
        #: lock id whose *being held* makes this site sanctioned —
        #: Condition.wait on the held condition (the wait releases it)
        self.exempt_lock = exempt_lock


class LockModel:
    """Program-wide lock facts. Build via :func:`get_lock_model`."""

    def __init__(self, program):
        self.program = program
        self.types = get_types(program)
        #: module qual -> {NAME: ctor qual} for module-level locks
        self.module_locks: Dict[str, Dict[str, str]] = {}
        #: FuncInfo -> rule names sanctioned on its def line
        self.sanctions: Dict[object, Set[str]] = {}
        #: FuncInfo -> [(call node, callee FuncInfo)]
        self.call_edges: Dict[object, List[Tuple[ast.Call, object]]] = {}
        #: FuncInfo -> transitive set of lock ids it may acquire
        self.acquires: Dict[object, Set[str]] = {}
        #: FuncInfo -> witness chain (list of strings) if it may block
        self.block_chain: Dict[object, List[str]] = {}
        #: (lock A, lock B) -> (path, line, via-description) first witness
        self.order_edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self._events: Dict[object, list] = {}

        self._collect_module_locks()
        self._collect_sanctions()
        self._all_funcs = sorted(
            (fi for mi in program.modules.values() for fi in mi.all_funcs),
            key=lambda fi: (fi.module.module, fi.qual, fi.node.lineno))
        for fi in self._all_funcs:
            self.call_edges[fi] = self._edges_of(fi)
        self._fixpoint_acquires()
        self._fixpoint_blocking()
        self._build_order_graph()

    # -- construction -----------------------------------------------------
    def _collect_module_locks(self):
        for mi in self.program.modules.values():
            table: Dict[str, str] = {}
            for stmt in mi.tree.body:
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name) \
                        and isinstance(stmt.value, ast.Call):
                    q = dotted_expr(mi, stmt.value.func)
                    if q in LOCK_CTORS:
                        table[stmt.targets[0].id] = q
            self.module_locks[mi.module] = table

    def _collect_sanctions(self):
        for mi in self.program.modules.values():
            lines = mi.source.splitlines()
            for fi in mi.all_funcs:
                start = min([fi.node.lineno]
                            + [d.lineno for d in fi.node.decorator_list])
                rules: Set[str] = set()
                for ln in range(start, fi.node.lineno + 1):
                    if 0 < ln <= len(lines):
                        m = _SANCTION_RE.search(lines[ln - 1])
                        if m:
                            rules.update(r.strip()
                                         for r in m.group(1).split(",")
                                         if r.strip())
                if rules:
                    self.sanctions[fi] = rules

    def sanctioned(self, fi, rule: str) -> bool:
        return rule in self.sanctions.get(fi, ())

    def _edges_of(self, fi) -> List[Tuple[ast.Call, object]]:
        out = []
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                callee = self.types.method_callee(fi, node)
                if callee is not None and callee is not fi:
                    out.append((node, callee))
        return out

    # -- lock identity ----------------------------------------------------
    def lock_id(self, fi, expr: ast.AST) -> Optional[str]:
        """Nominal identity of a lock expression, or None if the
        expression is not provably a lock."""
        mi = fi.module
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks.get(mi.module, ()):
                return f"{mi.module}.{expr.id}"
            return None  # function-local locks have no nominal identity
        if isinstance(expr, ast.Attribute):
            base_t = self.types.type_of(fi, expr.value)
            ci = self.types.class_of(base_t)
            if ci is not None and expr.attr in ci.lock_attrs:
                return f"{ci.qual}.{expr.attr}"
        return None

    def lock_ctor(self, lock_id: str) -> Optional[str]:
        """The threading ctor qual behind a lock id (None if unknown)."""
        head, _, attr = lock_id.rpartition(".")
        ci = self.types.classes.get(head)
        if ci is not None:
            return ci.lock_attrs.get(attr)
        mod, _, name = lock_id.rpartition(".")
        return self.module_locks.get(mod, {}).get(name)

    # -- per-function events ----------------------------------------------
    def direct_blocks(self, fi) -> List[BlockSite]:
        """Blocking operations appearing directly in ``fi``'s body."""
        mi = fi.module
        out: List[BlockSite] = []
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            q = dotted_expr(mi, node.func)
            if q in _BLOCKING_CALLS or (
                    q and q.startswith(_BLOCKING_PREFIXES)):
                out.append(BlockSite(node, f"{q}()"))
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            if f.attr == "block_until_ready":
                out.append(BlockSite(node, ".block_until_ready()"))
            elif f.attr in ("wait", "wait_for", "join", "getresponse",
                            "communicate"):
                recv_t = self.types.type_of(fi, f.value)
                lid = self.lock_id(fi, f.value)
                ctor = self.lock_ctor(lid) if lid else None
                if ctor == "threading.Condition":
                    # waiting on the held condition releases it: exempt at
                    # the direct site, but callers still see the block
                    out.append(BlockSite(node, f"Condition.{f.attr}()",
                                         exempt_lock=lid))
                elif recv_t == "threading.Event" and f.attr == "wait":
                    out.append(BlockSite(node, "Event.wait()"))
                elif recv_t == "threading.Thread" and f.attr == "join":
                    out.append(BlockSite(node, "Thread.join()"))
                elif recv_t == "http.client.HTTPConnection" \
                        or (recv_t or "").startswith("subprocess."):
                    out.append(BlockSite(node, f".{f.attr}()"))
        return out

    def events(self, fi) -> list:
        """Structural event stream for ``fi``: ``("acquire", lock_id,
        node, held_before)`` and ``("call", node, held)`` tuples, with
        ``held`` the tuple of lock ids held at that point (innermost
        last). ``with``-acquired locks scope over their body; bare
        ``.acquire()`` holds to end of function (approximation)."""
        cached = self._events.get(fi)
        if cached is not None:
            return cached
        out: list = []
        held: List[str] = []

        def expr_calls(e: Optional[ast.AST]):
            if e is None:
                return
            for n in ast.walk(e):
                if isinstance(n, ast.Call):
                    f = n.func
                    if isinstance(f, ast.Attribute) and f.attr == "acquire":
                        lid = self.lock_id(fi, f.value)
                        if lid is not None:
                            out.append(("acquire", lid, n, tuple(held)))
                            held.append(lid)
                            continue
                    out.append(("call", n, tuple(held)))

        def walk(stmts):
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue  # separate scope
                if isinstance(st, (ast.With, ast.AsyncWith)):
                    ids = []
                    for item in st.items:
                        expr_calls(item.context_expr)
                        lid = self.lock_id(fi, item.context_expr)
                        if lid is not None:
                            out.append(("acquire", lid, item.context_expr,
                                        tuple(held)))
                            held.append(lid)
                            ids.append(lid)
                    walk(st.body)
                    for _ in ids:
                        held.pop()
                elif isinstance(st, ast.If):
                    expr_calls(st.test)
                    walk(st.body)
                    walk(st.orelse)
                elif isinstance(st, (ast.For, ast.AsyncFor)):
                    expr_calls(st.iter)
                    walk(st.body)
                    walk(st.orelse)
                elif isinstance(st, ast.While):
                    expr_calls(st.test)
                    walk(st.body)
                    walk(st.orelse)
                elif isinstance(st, ast.Try):
                    walk(st.body)
                    for h in st.handlers:
                        walk(h.body)
                    walk(st.orelse)
                    walk(st.finalbody)
                else:
                    for e in ast.iter_child_nodes(st):
                        if isinstance(e, ast.expr):
                            expr_calls(e)

        walk(fi.node.body)
        self._events[fi] = out
        return out

    # -- fixpoints ---------------------------------------------------------
    def _fixpoint_acquires(self):
        for fi in self._all_funcs:
            direct = {ev[1] for ev in self.events(fi) if ev[0] == "acquire"}
            self.acquires[fi] = direct
        changed = True
        while changed:
            changed = False
            for fi in self._all_funcs:
                acc = self.acquires[fi]
                before = len(acc)
                for _, callee in self.call_edges.get(fi, ()):
                    acc |= self.acquires.get(callee, set())
                if len(acc) != before:
                    changed = True

    def _fixpoint_blocking(self):
        rule = "blocking-call-under-lock"
        for fi in self._all_funcs:
            if self.sanctioned(fi, rule):
                continue
            sites = self.direct_blocks(fi)
            if sites:
                s = sites[0]
                self.block_chain[fi] = [
                    f"{fi.qual} ({s.desc} at "
                    f"{fi.module.path}:{s.node.lineno})"]
        changed = True
        while changed:
            changed = False
            for fi in self._all_funcs:
                if fi in self.block_chain or self.sanctioned(fi, rule):
                    continue
                for call, callee in self.call_edges.get(fi, ()):
                    chain = self.block_chain.get(callee)
                    if chain and len(chain) < 6:
                        self.block_chain[fi] = [
                            f"{fi.qual} calls {callee.qual} "
                            f"(line {call.lineno})"] + chain
                        changed = True
                        break

    # -- order graph -------------------------------------------------------
    def _build_order_graph(self):
        for fi in self._all_funcs:
            callee_at = {id(call): callee
                         for call, callee in self.call_edges.get(fi, ())}
            for ev in self.events(fi):
                if ev[0] == "acquire":
                    _, lid, node, held = ev
                    for h in held:
                        self._edge(h, lid, fi, node, f"{fi.qual} acquires")
                else:
                    _, node, held = ev
                    if not held:
                        continue
                    callee = callee_at.get(id(node))
                    if callee is None:
                        continue
                    for lid in sorted(self.acquires.get(callee, ())):
                        for h in held:
                            self._edge(h, lid, fi, node,
                                       f"{fi.qual} -> {callee.qual} "
                                       f"acquires")

    def _edge(self, a: str, b: str, fi, node, via: str):
        if a == b:
            return  # one nominal id per (class, attr): self-edges are
            # indistinguishable from RLock re-entry / two instances
        self.order_edges.setdefault(
            (a, b), (fi.module.path, getattr(node, "lineno", 0), via))

    def cycles(self) -> List[List[str]]:
        """Elementary lock-order cycles, each as the sorted list of lock
        ids in one strongly connected component of size >= 2."""
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.order_edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str):
            # iterative Tarjan: (node, child-iterator) frames
            frames = [(v, iter(sorted(graph[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            while frames:
                node, it = frames[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        frames.append((w, iter(sorted(graph[w]))))
                        advanced = True
                        break
                    elif w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                frames.pop()
                if frames:
                    p = frames[-1][0]
                    low[p] = min(low[p], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) >= 2:
                        sccs.append(sorted(comp))

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        return sorted(sccs)


def get_lock_model(program) -> LockModel:
    m = program.cache.get(_LOCKS_CACHE)
    if m is None:
        m = LockModel(program)
        program.cache[_LOCKS_CACHE] = m
    return m
