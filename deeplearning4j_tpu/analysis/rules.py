"""jaxlint rules — JAX/TPU correctness checks for this codebase's idiom.

Each rule documents *why the pattern hurts on TPU* in its class docstring;
``analysis/README.md`` has the long-form rationale and suppression guidance.

Since v2 the rules see the whole program (:class:`~.callgraph.Program` via
``ctx.program``): jit context propagates across modules, and the
interprocedural families (prng-key-escape, donation-alias,
sharding-consistency, unlocked-shared-state) query call-graph summaries.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .dataflow import (ForwardScan, assign_names, terminates,
                       walrus_targets)
from .engine import FileContext, Finding, Rule

ALL_RULES: List[Rule] = []


def register(cls):
    ALL_RULES.append(cls())
    return cls


def rules_by_name() -> Dict[str, Rule]:
    return {r.name: r for r in ALL_RULES}


def _static_shape_arg(node: ast.AST) -> bool:
    """Arguments to float()/int() that are provably host-side static values:
    literals, len(...), ``.ndim``/``.size`` attributes, ``x.shape[i]``."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.operand, ast.Constant):
        return True
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == "len"):
        return True
    if isinstance(node, ast.Attribute) and node.attr in ("ndim", "size"):
        return True
    if isinstance(node, ast.Subscript):
        v = node.value
        if isinstance(v, ast.Attribute) and v.attr == "shape":
            return True
    return False


@register
class HostSyncRule(Rule):
    """Host-device synchronization in traced code.

    ``.item()``, ``float()``/``int()`` on array values, and
    ``np.asarray``/``np.array`` on traced values either raise a
    ConcretizationTypeError under jit or — worse, outside jit but inside the
    training loop — silently block the host on the device stream, serializing
    dispatch and collapsing TPU utilization.
    """

    name = "host-sync"
    description = ("host-device sync (.item(), float()/int() on arrays, "
                   "np.asarray on traced values) inside jit-context code")

    _NP_MATERIALIZE = {"numpy.asarray", "numpy.array", "numpy.asanyarray"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not ctx.jit.in_jit(node):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "item" and not node.args:
                yield self.finding(ctx, node, ".item() forces a device->host "
                                   "transfer and blocks until the value is ready")
            elif isinstance(f, ast.Attribute) and f.attr == "block_until_ready":
                yield self.finding(ctx, node, ".block_until_ready() stalls the "
                                   "dispatch pipeline inside traced code")
            elif (isinstance(f, ast.Name) and f.id in ("float", "int")
                    and len(node.args) == 1 and not _static_shape_arg(node.args[0])):
                yield self.finding(
                    ctx, node, f"{f.id}() on a (possibly traced) array value "
                    f"is a host sync; use jnp.asarray(x, dtype=...) or keep "
                    f"the value on device")
            else:
                q = ctx.resolve(f)
                if q in self._NP_MATERIALIZE:
                    yield self.finding(
                        ctx, node, f"{q}() materializes on host; inside a "
                        f"trace use jnp.asarray instead")
                elif q == "jax.device_get":
                    yield self.finding(ctx, node, "jax.device_get inside "
                                       "traced code is a host sync")


@register
class PrngConstantKeyRule(Rule):
    """Hard-coded ``PRNGKey(<literal>)``.

    A constant key baked into library code yields the *same* "random" stream
    on every call — silently correlated dropout masks, identical sampling
    across generate() calls, and irreproducible-looking-but-actually-frozen
    experiments. Keys must flow in from the caller or from a documented
    ``seed`` argument.
    """

    name = "prng-constant-key"
    description = "hard-coded jax.random.PRNGKey(<const>) / jax.random.key(<const>)"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            q = ctx.resolve(node.func)
            if q not in ("jax.random.PRNGKey", "jax.random.key"):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, (int, float)):
                yield self.finding(
                    ctx, node, f"{q}({node.args[0].value!r}) hard-codes the "
                    f"random stream; thread a key or seed argument through "
                    f"instead")


_SAMPLER_EXEMPT = {"PRNGKey", "key", "split", "fold_in", "wrap_key_data",
                   "key_data", "clone", "key_impl", "bits"}


def _key_uses(expr: ast.AST, resolve) -> Iterator[Tuple[str, ast.AST]]:
    """(key var name, call node) for jax.random draws whose first arg is a
    bare Name. Nested lambdas are included; nested defs are not reached here
    (the rule scans each def separately)."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and node.args \
                and isinstance(node.args[0], ast.Name):
            q = resolve(node.func)
            if q and q.startswith("jax.random.") \
                    and q.rsplit(".", 1)[1] not in _SAMPLER_EXEMPT:
                yield node.args[0].id, node


# compat aliases — pre-v2 these lived here; the framework owns them now
_walrus_targets = walrus_targets
_terminates = terminates
_assign_names = assign_names


class _KeyReuseScan(ForwardScan):
    """Per-name consumption counter for local jax.random draws."""

    def __init__(self, rule: "PrngKeyReuseRule", ctx: FileContext):
        super().__init__()
        self.rule = rule
        self.ctx = ctx

    def kill(self, name, state):
        state[name] = 0

    def visit_expr(self, expr, state):
        for name, call in _key_uses(expr, self.ctx.resolve):
            state[name] = state.get(name, 0) + 1
            if state[name] == 2:
                yield self.rule.finding(
                    self.ctx, call, f"key '{name}' already consumed by an "
                    f"earlier jax.random draw; split it first (identical "
                    f"samples otherwise)")


@register
class PrngKeyReuseRule(Rule):
    """Same PRNG key consumed by more than one random draw.

    Unlike stateful RNGs, jax keys are pure values: passing one key to two
    draws gives two *identical* samples. Every consumption must be preceded
    by a ``jax.random.split`` (or ``fold_in``). The check is a linear
    per-function approximation (:class:`~.dataflow.ForwardScan`): exclusive
    branches are merged, loop bodies are scanned once. Cross-function
    consumption is the :class:`PrngKeyEscapeRule`'s job.
    """

    name = "prng-key-reuse"
    description = "PRNG key passed to multiple jax.random draws without a split"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from _KeyReuseScan(self, ctx).run(node)


class _KeyEscapeScan(ForwardScan):
    """Key consumption across call boundaries.

    State per name: (count, escape seen, already fired, first consumer
    description). Local draws weigh 1; a call forwarding the key into an
    analyzed callee weighs that callee's transitive consumption (0/1/2 from
    the program's PRNG summaries). A finding fires when the count crosses 2
    with at least one call-boundary event involved — pure-local reuse is
    :class:`PrngKeyReuseRule` territory and is not double-reported."""

    bottom = (0, False, False, None)

    def __init__(self, rule: "PrngKeyEscapeRule", ctx: FileContext):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        self.program = ctx.program
        self.mi = ctx.module_info

    def join_value(self, a, b):
        return (max(a[0], b[0]), a[1] or b[1], a[2] or b[2], a[3] or b[3])

    def visit_expr(self, expr, state):
        events = []
        for name, call in _key_uses(expr, self.ctx.resolve):
            events.append((name, 1, False, call, None))
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                for argname, callee, uses in \
                        self.program.prng_callee_uses(self.mi, node):
                    events.append((argname, uses, True, node, callee))
        events.sort(key=lambda e: (getattr(e[3], "lineno", 0),
                                   getattr(e[3], "col_offset", 0)))
        for name, weight, escape, node, callee in events:
            count, saw, fired, who = state.get(name, self.bottom)
            newc = count + weight
            saw = saw or escape
            if not fired and newc >= 2 and saw:
                if escape and weight >= 2 and count == 0:
                    msg = (f"key '{name}' is consumed by multiple jax.random "
                           f"draws inside callee '{callee.name}' without a "
                           f"split — identical samples; split the key before "
                           f"the call or inside '{callee.name}'")
                else:
                    how = (f"passing it to '{callee.name}' re-consumes it"
                           if escape else "this draw re-consumes it")
                    msg = (f"key '{name}' already consumed by {who}; {how} "
                           f"without a split — identical random streams "
                           f"across the call boundary")
                yield self.rule.finding(self.ctx, node, msg)
                fired = True
            if who is None:
                who = (f"callee '{callee.name}' (line {node.lineno})" if escape
                       else f"a jax.random draw (line {node.lineno})")
            state[name] = (newc, saw, fired, who)


@register
class PrngKeyEscapeRule(Rule):
    """PRNG key reused across a function boundary.

    The per-function reuse rule cannot see that ``b.noise(key)`` consumes the
    key inside ``b`` — each function looks innocent in isolation, yet the
    caller's next draw from the same key repeats the callee's stream exactly
    (correlated noise/dropout that no test of either function alone catches).
    This rule charges every call site with the callee's *transitive* key
    consumption from the whole-program PRNG summaries and fires when the
    combined count reaches 2 with a call boundary involved.
    """

    name = "prng-key-escape"
    description = ("PRNG key consumed again after being passed to a callee "
                   "that draws from it (cross-function key reuse)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from _KeyEscapeScan(self, ctx).run(node)


_SIDE_EFFECT_PREFIXES = ("time.", "datetime.", "random.", "numpy.random.")


@register
class JitSideEffectRule(Rule):
    """Python side effects under a trace.

    Code under ``@jax.jit`` runs once at trace time, then never again:
    ``print`` fires only on (re)compile, stdlib/``np.random`` draw a single
    value that is baked into the compiled program as a constant, and mutating
    a global both leaks tracers and desynchronizes across pjit hosts.
    """

    name = "jit-side-effect"
    description = ("print/open/global/time/datetime/stdlib-random inside "
                   "jit-context code")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not ctx.jit.in_jit(node):
                continue
            if isinstance(node, ast.Global):
                yield self.finding(ctx, node, "mutating a global under jit "
                                   "leaks tracers / bakes stale constants")
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Name) and f.id in ("print", "input", "open"):
                    yield self.finding(
                        ctx, node, f"{f.id}() under jit runs at trace time "
                        f"only; use jax.debug.print / move I/O out of the "
                        f"traced function")
                    continue
                q = ctx.resolve(f)
                if q and q.startswith(_SIDE_EFFECT_PREFIXES):
                    yield self.finding(
                        ctx, node, f"{q}() under jit is evaluated once at "
                        f"trace time and baked in as a constant")


def _step_shaped(name: str) -> bool:
    tokens = name.lower().strip("_").split("_")
    return name.lower().endswith("step") or "step" in tokens or "update" in tokens


@register
class MissingDonateRule(Rule):
    """Train-step jit without buffer donation.

    A step function that maps ``(params, opt_state, ...) -> (params,
    opt_state, ...)`` keeps *two* copies of every donated-able buffer live on
    TPU unless the inputs are donated — for large models that halves usable
    HBM and forces XLA into extra copies. Name-based heuristic: functions
    whose name contains a ``step``/``update`` token.
    """

    name = "missing-donate"
    description = ("jitted *step/update function without donate_argnums/"
                   "donate_argnames")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        from .jitgraph import jit_call_kwargs

        seen = set()
        for fn, expr in ctx.jit.jit_applications:
            fname = getattr(fn, "name", "")
            if not _step_shaped(fname) or id(fn) in seen:
                continue
            seen.add(id(fn))
            kwargs = jit_call_kwargs(expr, ctx.resolve) or []
            if "donate_argnums" not in kwargs and "donate_argnames" not in kwargs:
                yield self.finding(
                    ctx, fn, f"step-shaped function '{fname}' is jitted "
                    f"without donate_argnums — old input buffers stay live, "
                    f"doubling HBM for the state pytree")


class _DonationScan(ForwardScan):
    """Caller-side liveness of donated buffers.

    State per name: the (call node, callee FuncInfo) that donated it. A later
    ``Name`` load of a still-donated binding is a read of a deleted buffer;
    rebinding the name (the ``params, opt = step(params, opt, ...)`` idiom)
    kills the fact.
    """

    bottom = None

    def __init__(self, rule: "DonationAliasRule", ctx: FileContext):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        self.program = ctx.program
        self.mi = ctx.module_info

    def join_value(self, a, b):
        return a or b

    def visit_expr(self, expr, state):
        # reads first: the donating call's own argument expressions are
        # processed before the call marks them donated
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                rec = state.get(node.id)
                if rec:
                    call, callee = rec
                    yield self.rule.finding(
                        self.ctx, node, f"'{node.id}' was donated to jitted "
                        f"'{callee.name}' (line {call.lineno}) and its buffer "
                        f"is deleted; rebind the result "
                        f"(`{node.id}, ... = {callee.name}(...)`) or copy "
                        f"before donating")
                    state.pop(node.id, None)  # one finding per donation
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            callee = self.program.donating_callee(self.mi, node)
            if callee is None:
                continue
            donated = callee.donated_params()
            for pname, arg in self.program.map_call_args(node, callee):
                if pname in donated and isinstance(arg, ast.Name):
                    state[arg.id] = (node, callee)


@register
class DonationAliasRule(Rule):
    """Donated buffer read after the jitted call.

    ``donate_argnums`` tells XLA it may reuse the argument's HBM for the
    output — after the call the Python binding still *looks* alive but the
    buffer is deleted; touching it raises "Array has been deleted" at
    runtime, and only on the donating path (tests that skip donation pass).
    The donation table is whole-program, so calling another module's donating
    step and reading the old state is caught too. Only non-traced callers are
    scanned: inside a trace XLA ignores nested donation.
    """

    name = "donation-alias"
    description = ("argument read after being donated to a jitted call "
                   "(deleted buffer)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        jit_nodes = ctx.program.jit_func_nodes(ctx.module_info)
        for fi in ctx.module_info.all_funcs:
            if fi.node not in jit_nodes:
                yield from _DonationScan(self, ctx).run(fi.node)


@register
class Float64DtypeRule(Rule):
    """float64/int64 in op kernels.

    TPUs have no native f64 ALUs: XLA emulates double precision at a large
    multiple of the f32 cost, and a single f64 literal silently promotes a
    whole expression tree. Kernel modules (``ops/``) must stay in
    f32/bf16-land; this rule only fires there.
    """

    name = "float64-dtype"
    description = "float64/int64 dtype reference inside an ops/ kernel module"

    _BAD_ATTRS = {"numpy.float64", "jax.numpy.float64", "numpy.double",
                  "numpy.int64", "jax.numpy.int64"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.is_kernel_module:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                q = ctx.resolve(node)
                if q in self._BAD_ATTRS:
                    yield self.finding(
                        ctx, node, f"{q} in a kernel module: TPUs emulate "
                        f"64-bit at a large slowdown; use f32/bf16")
            elif isinstance(node, ast.Call):
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(arg, ast.Constant) and arg.value in ("float64", "int64"):
                        yield self.finding(
                            ctx, arg, f"dtype string '{arg.value}' in a "
                            f"kernel module: use f32/bf16 on TPU")
                for k in node.keywords:
                    if k.arg == "dtype" and isinstance(k.value, ast.Name) \
                            and k.value.id == "float":
                        yield self.finding(
                            ctx, k.value, "dtype=float means float64; "
                            "spell the 32-bit dtype explicitly")


@register
class BroadExceptRule(Rule):
    """``except Exception`` that swallows.

    Under jit, the errors worth seeing — ConcretizationTypeError from a
    leaked tracer, XlaRuntimeError from a bad donation — are generic
    ``Exception`` subclasses; a catch-all that logs-and-continues converts
    them into silent wrong results. Handlers that re-raise (bare ``raise`` or
    ``raise X from e``) preserve the failure and are allowed. A tuple
    containing ``Exception`` is as broad as ``Exception`` alone, and
    ``contextlib.suppress(Exception)`` is the same catch-all in context-
    manager clothing.
    """

    name = "broad-except"
    description = ("except Exception/BaseException (bare, in a tuple, or via "
                   "contextlib.suppress) that swallows")

    def _is_broad(self, ctx, t) -> bool:
        if t is None:
            return True
        if isinstance(t, ast.Tuple):
            return any(self._is_broad(ctx, e) for e in t.elts)
        return ctx.resolve(t) in ("Exception", "BaseException")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler):
                if not self._is_broad(ctx, node.type):
                    continue
                reraises = any(
                    isinstance(n, ast.Raise) and (n.exc is None or n.cause is not None)
                    for n in ast.walk(node))
                if not reraises:
                    yield self.finding(
                        ctx, node, "broad except swallows tracer/runtime "
                        "errors; narrow the type, re-raise with `from e`, or "
                        "suppress with a justification if the loop must "
                        "survive")
            elif isinstance(node, ast.Call) \
                    and ctx.resolve(node.func) == "contextlib.suppress" \
                    and any(ctx.resolve(a) in ("Exception", "BaseException")
                            for a in node.args):
                yield self.finding(
                    ctx, node, "contextlib.suppress(Exception) is a broad "
                    "except in disguise — it silently drops tracer/runtime "
                    "errors; narrow the exception type")


_AXES_CACHE = "sharding-consistency:axes"
_SPEC_CTORS = {"jax.sharding.PartitionSpec"}
_MESH_CTORS = {"jax.sharding.Mesh", "jax.make_mesh",
               "jax.experimental.mesh_utils.create_device_mesh"}
_MAX_SPEC_RANK = 5


def _declared_axes(program) -> Set[str]:
    """Mesh axis names declared anywhere in the program: module-level
    ``*_AXIS = "..."`` constants plus string literals in the axis-names
    argument of ``jax.sharding.Mesh`` constructor calls."""
    axes = program.cache.get(_AXES_CACHE)
    if axes is not None:
        return axes
    axes = set()
    for mi in program.modules.values():
        for name, val in mi.str_consts.items():
            if name.endswith("_AXIS"):
                axes.add(val)
        resolve = mi.imports.resolve
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Call) and len(node.args) >= 2 \
                    and resolve(node.func) in _MESH_CTORS:
                for sub in ast.walk(node.args[1]):
                    if isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, str):
                        axes.add(sub.value)
    program.cache[_AXES_CACHE] = axes
    return axes


@register
class ShardingConsistencyRule(Rule):
    """PartitionSpec axes that no mesh declares.

    GSPMD resolves ``PartitionSpec`` axis names against the mesh at dispatch
    time: a typo'd axis (``"modle"``) or one the mesh never declares fails
    only when the jitted function first runs on the real topology — often
    multi-host, where the stack trace points at XLA internals, not the spec.
    Mentioning the same axis twice in one spec is an XLA hard error
    (a dimension cannot be sharded over one axis twice), and a spec with more
    entries than any array rank used here signals a drifted refactor. Checked
    against the program-wide set of declared axes (``*_AXIS`` constants and
    ``Mesh(...)`` axis-name literals) in ``parallel/`` and ``nn/`` modules.
    """

    name = "sharding-consistency"
    description = ("PartitionSpec axis unknown to any declared mesh, "
                   "duplicated in one spec, or of implausible rank")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        parts = set(os.path.normpath(ctx.path).split(os.sep))
        if not parts & {"parallel", "nn"}:
            return
        axes = _declared_axes(ctx.program)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) \
                    or ctx.resolve(node.func) not in _SPEC_CTORS:
                continue
            if len(node.args) > _MAX_SPEC_RANK:
                yield self.finding(
                    ctx, node, f"PartitionSpec with {len(node.args)} entries "
                    f"— no array in this codebase has rank > "
                    f"{_MAX_SPEC_RANK}; stale spec?")
            seen: Dict[str, ast.AST] = {}
            for arg in node.args:
                elts = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) \
                    else [arg]
                for e in elts:
                    if isinstance(e, ast.Starred):
                        continue
                    if isinstance(e, ast.Constant):
                        if not isinstance(e.value, str):
                            continue
                        val: Optional[str] = e.value
                        if axes and val not in axes:
                            yield self.finding(
                                ctx, e, f"PartitionSpec axis '{val}' is not "
                                f"declared by any mesh in the program "
                                f"(known: {', '.join(sorted(axes))}) — "
                                f"fails at dispatch on the real topology")
                    else:
                        # Name/Attribute resolving to a module-level string
                        # constant (DATA_AXIS etc.); opaque values are skipped
                        val = ctx.program.resolve_const_str(
                            ctx.module_info, e)
                        if val is None:
                            continue
                    if val in seen:
                        yield self.finding(
                            ctx, e, f"axis '{val}' appears twice in one "
                            f"PartitionSpec — XLA rejects double sharding "
                            f"over the same mesh axis")
                    else:
                        seen[val] = e


_HANDLER_METHODS = {"do_GET", "do_POST", "do_PUT", "do_DELETE", "do_HEAD",
                    "do_PATCH"}
_MUTATORS = {"append", "add", "update", "extend", "insert", "setdefault",
             "pop", "popitem", "remove", "discard", "clear", "appendleft",
             "extendleft"}
_MUTABLE_CTORS = {"dict", "list", "set", "collections.defaultdict",
                  "collections.deque", "collections.OrderedDict",
                  "collections.Counter"}
_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
               "threading.Semaphore", "threading.BoundedSemaphore"}
_LOCK_TOKENS = ("lock", "mutex", "cond", "cv")
_REACH_CACHE = "unlocked-shared-state:reachable"


def _is_mutable_ctor(v: ast.AST, resolve) -> bool:
    if isinstance(v, (ast.Dict, ast.List, ast.Set)):
        return True
    if isinstance(v, ast.Call):
        return resolve(v.func) in _MUTABLE_CTORS
    return False


def _thread_reachable(program) -> Set:
    """FuncInfos reachable from a concurrency entry point: an httpd
    ``do_*`` handler method or a ``threading.Thread(target=...)``. Cached
    program-wide."""
    reach = program.cache.get(_REACH_CACHE)
    if reach is not None:
        return reach
    entries = set()
    for mi in program.modules.values():
        resolve = mi.imports.resolve
        for fi in mi.all_funcs:
            if fi.cls and fi.name in _HANDLER_METHODS:
                entries.add(fi)
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Call) \
                    and resolve(node.func) == "threading.Thread":
                for k in node.keywords:
                    if k.arg != "target":
                        continue
                    fi = program.resolve_call(mi, k.value,
                                              mi.enclosing_class(node))
                    if fi is not None:
                        entries.add(fi)
    reach = set(entries)
    work = list(entries)
    while work:
        fi = work.pop()
        mi = fi.module
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            callee = program.resolve_call(mi, node.func,
                                          mi.enclosing_class(node))
            if callee is not None and callee not in reach:
                reach.add(callee)
                work.append(callee)
    program.cache[_REACH_CACHE] = reach
    return reach


@register
class UnlockedSharedStateRule(Rule):
    """Shared mutable state written from concurrent code without a lock.

    The metrics/trace/KNN servers run request handlers and ``Thread``
    targets concurrently with the training loop. CPython's GIL makes single
    bytecodes atomic but not read-modify-write sequences —
    ``events.append(...)`` racing ``events.clear()`` in a flush drops
    telemetry, and dict resize during iteration raises. Any mutation of a
    module-level container or a ``self.`` container (bound in ``__init__``)
    from code reachable from a handler/Thread entry must hold a lock — a
    ``with`` whose context is lock-named, a ``threading.Lock``-typed
    attribute, or a module-level lock. Reachability is whole-program, so a
    helper in another module called from a handler is still checked.
    """

    name = "unlocked-shared-state"
    description = ("module-level or self. mutable container mutated from "
                   "Thread/handler-reachable code without a held lock")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        mi = ctx.module_info
        resolve = mi.imports.resolve
        reach = _thread_reachable(ctx.program)
        if not any(fi in reach for fi in mi.all_funcs):
            return

        module_shared: Set[str] = set()
        module_locks: Set[str] = set()
        for stmt in mi.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                if _is_mutable_ctor(stmt.value, resolve):
                    module_shared.add(stmt.targets[0].id)
                elif isinstance(stmt.value, ast.Call) \
                        and resolve(stmt.value.func) in _LOCK_CTORS:
                    module_locks.add(stmt.targets[0].id)

        class_shared: Set[Tuple[str, str]] = set()
        class_locks: Set[Tuple[str, str]] = set()
        for fi in mi.all_funcs:
            if fi.name != "__init__" or not fi.cls:
                continue
            for node in ast.walk(fi.node):
                if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                    continue
                t = node.targets[0]
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                if _is_mutable_ctor(node.value, resolve):
                    class_shared.add((fi.cls, t.attr))
                elif isinstance(node.value, ast.Call) \
                        and resolve(node.value.func) in _LOCK_CTORS:
                    class_locks.add((fi.cls, t.attr))

        def shared_base(b: ast.AST, fi) -> Optional[str]:
            if isinstance(b, ast.Name) and b.id in module_shared \
                    and b.id not in fi.params:
                return b.id
            if isinstance(b, ast.Attribute) and isinstance(b.value, ast.Name) \
                    and b.value.id == "self" and fi.cls \
                    and (fi.cls, b.attr) in class_shared:
                return f"self.{b.attr}"
            return None

        def lockish(e: ast.AST, cls: Optional[str]) -> bool:
            seg = e.attr if isinstance(e, ast.Attribute) else (
                e.id if isinstance(e, ast.Name) else None)
            if seg and any(tok in seg.lower() for tok in _LOCK_TOKENS):
                return True
            if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) \
                    and e.value.id == "self" and cls \
                    and (cls, e.attr) in class_locks:
                return True
            return isinstance(e, ast.Name) and e.id in module_locks

        def lock_held(node: ast.AST, cls: Optional[str]) -> bool:
            cur = mi.parents.get(node)
            while cur is not None and not isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if isinstance(cur, (ast.With, ast.AsyncWith)) \
                        and any(lockish(item.context_expr, cls)
                                for item in cur.items):
                    return True
                cur = mi.parents.get(cur)
            return False

        for fi in mi.all_funcs:
            if fi not in reach:
                continue
            for node in ast.walk(fi.node):
                hits: List[Tuple[str, ast.AST]] = []
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    tgts = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in tgts:
                        if isinstance(t, ast.Subscript):
                            name = shared_base(t.value, fi)
                            if name:
                                hits.append((name, t))
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _MUTATORS:
                    name = shared_base(node.func.value, fi)
                    if name:
                        hits.append((name, node))
                for name, loc in hits:
                    if not lock_held(loc, fi.cls):
                        yield self.finding(
                            ctx, loc, f"shared container '{name}' is mutated "
                            f"from Thread/handler-reachable code "
                            f"('{fi.qual}') without a held lock — concurrent "
                            f"request/flush access races; wrap in "
                            f"`with <lock>:`")


# --------------------------------------------------------------------------
# metric label cardinality

_METRIC_FACTORIES = {"counter", "gauge", "histogram"}

#: identifier segments that smell like per-request (unbounded) values.
#: Deliberately narrow: ``tenant``/``model``/``code``/``cause`` are bounded
#: by configuration or an enum and stay clean.
_UNBOUNDED_LABEL_RE = re.compile(
    r"(?:^|_)(?:id|ids|uuid|guid|path|paths|url|urls|uri|uris|prompt|"
    r"prompts|query|queries|trace|token|tokens)(?:_|$)")


def _find_unbounded(expr: ast.AST) -> Optional[str]:
    """Innermost Name/Attribute under ``expr`` whose identifier matches the
    unbounded-input pattern (``request_id``, ``self.path``, ``trace``...)."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and _UNBOUNDED_LABEL_RE.search(n.id.lower()):
            return n.id
        if isinstance(n, ast.Attribute) \
                and _UNBOUNDED_LABEL_RE.search(n.attr.lower()):
            return n.attr
    return None


def _label_value_origin(value: ast.AST) -> Optional[Tuple[str, str]]:
    """(source identifier, how it reached the label) when ``value`` is built
    from an unbounded input; None for bounded/unknown provenance.

    Only three shapes are trusted to *carry* the raw value into the label:
    f-strings, ``str()``/``repr()``/``format()``, and the bare Name/Attribute
    itself. Any other call (``_metric_route(path)``, ``_bucket(n)``) is
    assumed to collapse its input to a bounded set — that is the sanctioned
    fix for a finding from this rule.
    """
    if isinstance(value, ast.JoinedStr):
        for part in value.values:
            if isinstance(part, ast.FormattedValue):
                src = _find_unbounded(part.value)
                if src:
                    return src, "an f-string of"
    elif isinstance(value, ast.Call) and isinstance(value.func, ast.Name) \
            and value.func.id in ("str", "repr", "format"):
        for a in value.args:
            src = _find_unbounded(a)
            if src:
                return src, f"{value.func.id}() of"
    elif isinstance(value, ast.Name):
        if _UNBOUNDED_LABEL_RE.search(value.id.lower()):
            return value.id, "the raw value of"
    elif isinstance(value, ast.Attribute):
        if _UNBOUNDED_LABEL_RE.search(value.attr.lower()):
            return value.attr, "the raw value of"
    return None


@register
class MetricLabelCardinalityRule(Rule):
    """Metric label values derived from unbounded per-request inputs.

    Every distinct label value mints a new time series: a label fed from a
    request id, URL path, prompt text, or trace id grows the registry (and
    every scrape) without bound — the Prometheus cardinality explosion.
    Flags ``counter``/``gauge``/``histogram`` call sites whose label dict
    (inline, or a local ``labels = {...}`` passed by name) contains an
    f-string over, ``str()``/``repr()`` of, or the raw value of an
    identifier matching the unbounded pattern. The fix is structural: fold
    the value through a bounded mapper (``_metric_route`` collapsing unknown
    paths to ``"other"``) or attach ids as *exemplars* on histogram
    observations instead of as labels.
    """

    name = "metric-label-cardinality"
    description = ("metric label value built from an unbounded per-request "
                   "input (id/path/prompt/...) — one time series per "
                   "distinct value")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        mi = ctx.module_info
        for node in ast.walk(mi.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_FACTORIES):
                continue
            # registry factories take the metric name as a string literal;
            # this also skips look-alikes (np.histogram(data, bins=...))
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            for labels in self._label_dicts(mi, node):
                for key, value in zip(labels.keys, labels.values):
                    if key is None:  # **spread — provenance unknown
                        continue
                    hit = _label_value_origin(value)
                    if hit is None:
                        continue
                    src, how = hit
                    kname = key.value if isinstance(key, ast.Constant) \
                        else ast.dump(key)
                    yield self.finding(
                        ctx, value,
                        f"metric label {kname!r} is {how} '{src}', an "
                        f"unbounded per-request value — each distinct value "
                        f"creates a new time series; map it to a bounded set "
                        f"first (e.g. a route table with an 'other' bucket) "
                        f"or carry the id as a histogram exemplar instead")

    @staticmethod
    def _label_dicts(mi, call: ast.Call) -> List[ast.Dict]:
        """Dict literals feeding the call's label argument: inline dicts in
        any argument slot, plus a Name argument resolved to a single
        ``labels = {...}`` assignment in the enclosing function."""
        out: List[ast.Dict] = []
        names: List[str] = []
        for e in list(call.args[1:]) + [kw.value for kw in call.keywords
                                        if kw.arg != "help"]:
            if isinstance(e, ast.Dict):
                out.append(e)
            elif isinstance(e, ast.Name):
                names.append(e.id)
        if names:
            fn = mi.parents.get(call)
            while fn is not None and not isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = mi.parents.get(fn)
            if fn is not None:
                for stmt in ast.walk(fn):
                    if isinstance(stmt, ast.Assign) \
                            and len(stmt.targets) == 1 \
                            and isinstance(stmt.targets[0], ast.Name) \
                            and stmt.targets[0].id in names \
                            and isinstance(stmt.value, ast.Dict):
                        out.append(stmt.value)
        return out


# v3 concurrency & resource-discipline family registers itself on import.
# Imported last: it needs `register` and must not win name clashes above.
from . import rules_concurrency  # noqa: E402,F401  (registration side effect)

# v4 shape/dtype interpreter & compile-surface family, same contract.
from . import rules_shapes  # noqa: E402,F401  (registration side effect)

# v5 interprocedural error-flow family, same contract.
from . import rules_errorflow  # noqa: E402,F401  (registration side effect)
