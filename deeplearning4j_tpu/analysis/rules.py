"""jaxlint rules — JAX/TPU correctness checks for this codebase's idiom.

Each rule documents *why the pattern hurts on TPU* in its class docstring;
``analysis/README.md`` has the long-form rationale and suppression guidance.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Tuple

from .engine import FileContext, Finding, Rule

ALL_RULES: List[Rule] = []


def register(cls):
    ALL_RULES.append(cls())
    return cls


def rules_by_name() -> Dict[str, Rule]:
    return {r.name: r for r in ALL_RULES}


def _static_shape_arg(node: ast.AST) -> bool:
    """Arguments to float()/int() that are provably host-side static values:
    literals, len(...), ``.ndim``/``.size`` attributes, ``x.shape[i]``."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.operand, ast.Constant):
        return True
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == "len"):
        return True
    if isinstance(node, ast.Attribute) and node.attr in ("ndim", "size"):
        return True
    if isinstance(node, ast.Subscript):
        v = node.value
        if isinstance(v, ast.Attribute) and v.attr == "shape":
            return True
    return False


@register
class HostSyncRule(Rule):
    """Host-device synchronization in traced code.

    ``.item()``, ``float()``/``int()`` on array values, and
    ``np.asarray``/``np.array`` on traced values either raise a
    ConcretizationTypeError under jit or — worse, outside jit but inside the
    training loop — silently block the host on the device stream, serializing
    dispatch and collapsing TPU utilization.
    """

    name = "host-sync"
    description = ("host-device sync (.item(), float()/int() on arrays, "
                   "np.asarray on traced values) inside jit-context code")

    _NP_MATERIALIZE = {"numpy.asarray", "numpy.array", "numpy.asanyarray"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not ctx.jit.in_jit(node):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "item" and not node.args:
                yield self.finding(ctx, node, ".item() forces a device->host "
                                   "transfer and blocks until the value is ready")
            elif isinstance(f, ast.Attribute) and f.attr == "block_until_ready":
                yield self.finding(ctx, node, ".block_until_ready() stalls the "
                                   "dispatch pipeline inside traced code")
            elif (isinstance(f, ast.Name) and f.id in ("float", "int")
                    and len(node.args) == 1 and not _static_shape_arg(node.args[0])):
                yield self.finding(
                    ctx, node, f"{f.id}() on a (possibly traced) array value "
                    f"is a host sync; use jnp.asarray(x, dtype=...) or keep "
                    f"the value on device")
            else:
                q = ctx.resolve(f)
                if q in self._NP_MATERIALIZE:
                    yield self.finding(
                        ctx, node, f"{q}() materializes on host; inside a "
                        f"trace use jnp.asarray instead")
                elif q == "jax.device_get":
                    yield self.finding(ctx, node, "jax.device_get inside "
                                       "traced code is a host sync")


@register
class PrngConstantKeyRule(Rule):
    """Hard-coded ``PRNGKey(<literal>)``.

    A constant key baked into library code yields the *same* "random" stream
    on every call — silently correlated dropout masks, identical sampling
    across generate() calls, and irreproducible-looking-but-actually-frozen
    experiments. Keys must flow in from the caller or from a documented
    ``seed`` argument.
    """

    name = "prng-constant-key"
    description = "hard-coded jax.random.PRNGKey(<const>) / jax.random.key(<const>)"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            q = ctx.resolve(node.func)
            if q not in ("jax.random.PRNGKey", "jax.random.key"):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, (int, float)):
                yield self.finding(
                    ctx, node, f"{q}({node.args[0].value!r}) hard-codes the "
                    f"random stream; thread a key or seed argument through "
                    f"instead")


_SAMPLER_EXEMPT = {"PRNGKey", "key", "split", "fold_in", "wrap_key_data",
                   "key_data", "clone", "key_impl", "bits"}


def _key_uses(expr: ast.AST, resolve) -> Iterator[Tuple[str, ast.AST]]:
    """(key var name, call node) for jax.random draws whose first arg is a
    bare Name. Nested lambdas are included; nested defs are not reached here
    (the rule scans each def separately)."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and node.args \
                and isinstance(node.args[0], ast.Name):
            q = resolve(node.func)
            if q and q.startswith("jax.random.") \
                    and q.rsplit(".", 1)[1] not in _SAMPLER_EXEMPT:
                yield node.args[0].id, node


def _walrus_targets(expr: ast.AST) -> Iterator[str]:
    for node in ast.walk(expr):
        if isinstance(node, ast.NamedExpr) and isinstance(node.target, ast.Name):
            yield node.target.id


def _terminates(stmts: List[ast.stmt]) -> bool:
    """Block ends by leaving the enclosing scope — its key counts never flow
    into the code after the If (``if cond: return draw(key)`` is exclusive
    with a later ``return draw(key)``)."""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


def _assign_names(target: ast.AST) -> Iterator[str]:
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            yield from _assign_names(e)
    elif isinstance(target, ast.Starred):
        yield from _assign_names(target.value)


@register
class PrngKeyReuseRule(Rule):
    """Same PRNG key consumed by more than one random draw.

    Unlike stateful RNGs, jax keys are pure values: passing one key to two
    draws gives two *identical* samples. Every consumption must be preceded
    by a ``jax.random.split`` (or ``fold_in``). The check is a linear
    per-function approximation: exclusive branches are merged, loop bodies
    are scanned once.
    """

    name = "prng-key-reuse"
    description = "PRNG key passed to multiple jax.random draws without a split"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._scan(ctx, node.body, {})

    def _expr(self, ctx, expr, counts) -> Iterator[Finding]:
        if expr is None:
            return
        for name, call in _key_uses(expr, ctx.resolve):
            counts[name] = counts.get(name, 0) + 1
            if counts[name] == 2:
                yield self.finding(
                    ctx, call, f"key '{name}' already consumed by an earlier "
                    f"jax.random draw; split it first (identical samples "
                    f"otherwise)")
        for t in _walrus_targets(expr):
            counts[t] = 0

    def _branch(self, ctx, stmts, counts) -> Tuple[List[Finding], Dict[str, int]]:
        c = dict(counts)
        return list(self._scan(ctx, stmts, c)), c

    def _scan(self, ctx, stmts, counts) -> Iterator[Finding]:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # separate scope, scanned on its own
            if isinstance(stmt, ast.Assign):
                yield from self._expr(ctx, stmt.value, counts)
                for t in stmt.targets:
                    for n in _assign_names(t):
                        counts[n] = 0
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                yield from self._expr(ctx, stmt.value, counts)
                for n in _assign_names(stmt.target):
                    counts[n] = 0
            elif isinstance(stmt, ast.If):
                yield from self._expr(ctx, stmt.test, counts)
                f1, c1 = self._branch(ctx, stmt.body, counts)
                f2, c2 = self._branch(ctx, stmt.orelse, counts)
                yield from f1
                yield from f2
                merged = [c for c, block in ((c1, stmt.body), (c2, stmt.orelse))
                          if not _terminates(block)]
                if merged:
                    for k in set().union(*merged):
                        counts[k] = max(c.get(k, 0) for c in merged)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                yield from self._expr(ctx, stmt.iter, counts)
                for n in _assign_names(stmt.target):
                    counts[n] = 0
                f1, c1 = self._branch(ctx, stmt.body + stmt.orelse, counts)
                yield from f1
                for k in c1:
                    counts[k] = max(counts.get(k, 0), c1[k])
            elif isinstance(stmt, ast.While):
                yield from self._expr(ctx, stmt.test, counts)
                f1, c1 = self._branch(ctx, stmt.body + stmt.orelse, counts)
                yield from f1
                for k in c1:
                    counts[k] = max(counts.get(k, 0), c1[k])
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    yield from self._expr(ctx, item.context_expr, counts)
                    if item.optional_vars is not None:
                        for n in _assign_names(item.optional_vars):
                            counts[n] = 0
                yield from self._scan(ctx, stmt.body, counts)
            elif isinstance(stmt, ast.Try):
                yield from self._scan(ctx, stmt.body, counts)
                for h in stmt.handlers:
                    fh, ch = self._branch(ctx, h.body, counts)
                    yield from fh
                    for k in ch:
                        counts[k] = max(counts.get(k, 0), ch[k])
                yield from self._scan(ctx, stmt.orelse + stmt.finalbody, counts)
            else:
                for expr in ast.iter_child_nodes(stmt):
                    if isinstance(expr, ast.expr):
                        yield from self._expr(ctx, expr, counts)


_SIDE_EFFECT_PREFIXES = ("time.", "datetime.", "random.", "numpy.random.")


@register
class JitSideEffectRule(Rule):
    """Python side effects under a trace.

    Code under ``@jax.jit`` runs once at trace time, then never again:
    ``print`` fires only on (re)compile, stdlib/``np.random`` draw a single
    value that is baked into the compiled program as a constant, and mutating
    a global both leaks tracers and desynchronizes across pjit hosts.
    """

    name = "jit-side-effect"
    description = ("print/open/global/time/datetime/stdlib-random inside "
                   "jit-context code")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not ctx.jit.in_jit(node):
                continue
            if isinstance(node, ast.Global):
                yield self.finding(ctx, node, "mutating a global under jit "
                                   "leaks tracers / bakes stale constants")
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Name) and f.id in ("print", "input", "open"):
                    yield self.finding(
                        ctx, node, f"{f.id}() under jit runs at trace time "
                        f"only; use jax.debug.print / move I/O out of the "
                        f"traced function")
                    continue
                q = ctx.resolve(f)
                if q and q.startswith(_SIDE_EFFECT_PREFIXES):
                    yield self.finding(
                        ctx, node, f"{q}() under jit is evaluated once at "
                        f"trace time and baked in as a constant")


def _step_shaped(name: str) -> bool:
    tokens = name.lower().strip("_").split("_")
    return name.lower().endswith("step") or "step" in tokens or "update" in tokens


@register
class MissingDonateRule(Rule):
    """Train-step jit without buffer donation.

    A step function that maps ``(params, opt_state, ...) -> (params,
    opt_state, ...)`` keeps *two* copies of every donated-able buffer live on
    TPU unless the inputs are donated — for large models that halves usable
    HBM and forces XLA into extra copies. Name-based heuristic: functions
    whose name contains a ``step``/``update`` token.
    """

    name = "missing-donate"
    description = ("jitted *step/update function without donate_argnums/"
                   "donate_argnames")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        from .jitgraph import jit_call_kwargs

        seen = set()
        for fn, expr in ctx.jit.jit_applications:
            fname = getattr(fn, "name", "")
            if not _step_shaped(fname) or id(fn) in seen:
                continue
            seen.add(id(fn))
            kwargs = jit_call_kwargs(expr, ctx.resolve) or []
            if "donate_argnums" not in kwargs and "donate_argnames" not in kwargs:
                yield self.finding(
                    ctx, fn, f"step-shaped function '{fname}' is jitted "
                    f"without donate_argnums — old input buffers stay live, "
                    f"doubling HBM for the state pytree")


@register
class Float64DtypeRule(Rule):
    """float64/int64 in op kernels.

    TPUs have no native f64 ALUs: XLA emulates double precision at a large
    multiple of the f32 cost, and a single f64 literal silently promotes a
    whole expression tree. Kernel modules (``ops/``) must stay in
    f32/bf16-land; this rule only fires there.
    """

    name = "float64-dtype"
    description = "float64/int64 dtype reference inside an ops/ kernel module"

    _BAD_ATTRS = {"numpy.float64", "jax.numpy.float64", "numpy.double",
                  "numpy.int64", "jax.numpy.int64"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.is_kernel_module:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                q = ctx.resolve(node)
                if q in self._BAD_ATTRS:
                    yield self.finding(
                        ctx, node, f"{q} in a kernel module: TPUs emulate "
                        f"64-bit at a large slowdown; use f32/bf16")
            elif isinstance(node, ast.Call):
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(arg, ast.Constant) and arg.value in ("float64", "int64"):
                        yield self.finding(
                            ctx, arg, f"dtype string '{arg.value}' in a "
                            f"kernel module: use f32/bf16 on TPU")
                for k in node.keywords:
                    if k.arg == "dtype" and isinstance(k.value, ast.Name) \
                            and k.value.id == "float":
                        yield self.finding(
                            ctx, k.value, "dtype=float means float64; "
                            "spell the 32-bit dtype explicitly")


@register
class BroadExceptRule(Rule):
    """``except Exception`` that swallows.

    Under jit, the errors worth seeing — ConcretizationTypeError from a
    leaked tracer, XlaRuntimeError from a bad donation — are generic
    ``Exception`` subclasses; a catch-all that logs-and-continues converts
    them into silent wrong results. Handlers that re-raise (bare ``raise`` or
    ``raise X from e``) preserve the failure and are allowed.
    """

    name = "broad-except"
    description = "except Exception/BaseException (or bare except) that swallows"

    def _is_broad(self, ctx, t) -> bool:
        if t is None:
            return True
        if isinstance(t, ast.Tuple):
            return any(self._is_broad(ctx, e) for e in t.elts)
        return ctx.resolve(t) in ("Exception", "BaseException")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(ctx, node.type):
                continue
            reraises = any(
                isinstance(n, ast.Raise) and (n.exc is None or n.cause is not None)
                for n in ast.walk(node))
            if not reraises:
                yield self.finding(
                    ctx, node, "broad except swallows tracer/runtime errors; "
                    "narrow the type, re-raise with `from e`, or suppress "
                    "with a justification if the loop must survive")
