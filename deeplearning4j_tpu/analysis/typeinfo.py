"""Nominal type inference for jaxlint's concurrency rules.

The v2 call graph resolves bare names, ``self.method()`` and aliased
module attributes — enough for jit/PRNG facts, but blind to the serving
stack's dominant call shape: a method invoked through a *typed object
attribute* (``self._pager.ensure(...)``, ``entry.activate()`` where
``entry`` came from ``registry.get(name) -> FleetEntry``). The lock and
resource rules need those edges, so this module builds a small nominal
type table over the program:

- :class:`ClassInfo` per class definition: methods, ``@property``
  attributes, and the inferred type of every ``self.X`` attribute —
  from constructor calls (``self.X = Cls(...)``), annotated assignments
  (``self.X: Optional[Cls] = None``), and class-body annotations;
- a per-function local environment (:meth:`Types.local_env`): parameter
  annotations, ``x = Cls(...)`` constructor bindings, ``x = self.attr``
  reads, and return annotations of resolvable method calls;
- :meth:`Types.type_of` / :meth:`Types.method_callee` to answer "what
  class is this expression, and which FuncInfo does this attribute call
  land on".

Deliberately *nominal and flow-insensitive*: a name bound to two
different classes is dropped (no unions), unknown types resolve to
``None`` and downstream rules stay silent rather than guess. Everything
is stdlib ``ast``; nothing imports the code under analysis.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

_TYPES_CACHE = "typeinfo:types"

#: decorators that make an attribute access out of a def
_PROPERTY_DECOS = {"property", "functools.cached_property",
                   "cached_property"}

#: threading primitives the lock rules key on (qual -> kind)
LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
              "threading.Semaphore", "threading.BoundedSemaphore"}
EVENT_CTOR = "threading.Event"
THREAD_CTOR = "threading.Thread"


def dotted_expr(mi, node: ast.AST) -> Optional[str]:
    """Alias-aware dotted path of a Name/Attribute chain using the
    module's *full* alias map (every import, not just canonical ones):
    ``b.B`` after ``from pkg import b`` -> ``pkg.b.B``."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(mi.aliases.get(node.id, node.id))
    return ".".join(reversed(parts))


class ClassInfo:
    """One class definition: methods, properties, typed attributes."""

    __slots__ = ("module", "name", "qual", "node", "methods", "properties",
                 "attr_types", "lock_attrs")

    def __init__(self, mi, node: ast.ClassDef):
        self.module = mi
        self.name = node.name
        self.qual = f"{mi.module}.{node.name}"
        self.node = node
        #: method name -> FuncInfo (properties excluded)
        self.methods: Dict[str, object] = {}
        self.properties: Set[str] = set()
        #: self.X -> dotted type qual (program class or opaque stdlib path)
        self.attr_types: Dict[str, str] = {}
        #: self.X -> lock ctor qual (threading.Lock/RLock/Condition/...)
        self.lock_attrs: Dict[str, str] = {}


class Types:
    """Program-wide class table + expression typing. Build via
    :func:`get_types` so the table is computed once per program."""

    def __init__(self, program):
        self.program = program
        #: "<module>.<Class>" -> ClassInfo
        self.classes: Dict[str, ClassInfo] = {}
        #: per module: class name -> ClassInfo
        self._by_module: Dict[str, Dict[str, ClassInfo]] = {}
        self._env_cache: Dict[int, Dict[str, Optional[str]]] = {}
        for mi in program.modules.values():
            table: Dict[str, ClassInfo] = {}
            for node in ast.walk(mi.tree):
                if isinstance(node, ast.ClassDef):
                    ci = ClassInfo(mi, node)
                    self.classes.setdefault(ci.qual, ci)
                    table.setdefault(ci.name, ci)
            self._by_module[mi.module] = table
        # methods/properties and attribute types need the class table
        # complete first (annotations reference other modules' classes)
        for ci in self.classes.values():
            self._collect_members(ci)
        for ci in self.classes.values():
            self._collect_attrs(ci)

    # -- construction -----------------------------------------------------
    def _collect_members(self, ci: ClassInfo):
        mi = ci.module
        for child in ci.node.body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                decos = {dotted_expr(mi, d) for d in child.decorator_list
                         if not isinstance(d, ast.Call)}
                if decos & _PROPERTY_DECOS:
                    ci.properties.add(child.name)
                else:
                    fi = mi.functions.get(f"{ci.name}.{child.name}")
                    if fi is not None:
                        ci.methods[child.name] = fi
            elif isinstance(child, ast.AnnAssign) \
                    and isinstance(child.target, ast.Name):
                t = self.resolve_annotation(mi, child.annotation)
                if t:
                    ci.attr_types.setdefault(child.target.id, t)

    def _collect_attrs(self, ci: ClassInfo):
        mi = ci.module
        for node in ast.walk(ci.node):
            target = value = ann = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                target, value, ann = node.target, node.value, node.annotation
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            # the assignment must belong to one of *this* class's methods,
            # not a nested class's (walk() has no scope)
            if mi.enclosing_class(node) != ci.name:
                continue
            attr = target.attr
            if ann is not None:
                t = self.resolve_annotation(mi, ann)
                if t in LOCK_CTORS:
                    ci.lock_attrs.setdefault(attr, t)
                elif t:
                    ci.attr_types.setdefault(attr, t)
            if isinstance(value, ast.Call):
                q = dotted_expr(mi, value.func)
                if q in LOCK_CTORS:
                    ci.lock_attrs.setdefault(attr, q)
                    continue
                t = self.resolve_class_expr(mi, value.func)
                if t:
                    ci.attr_types.setdefault(attr, t)

    # -- class resolution -------------------------------------------------
    def resolve_class_expr(self, mi, node: ast.AST) -> Optional[str]:
        """Type qual a constructor/annotation expression names: a program
        class's ``<module>.<Class>``, or the raw dotted path for opaque
        externals (``threading.Event``)."""
        d = dotted_expr(mi, node)
        if d is None:
            return None
        return self.resolve_class_dotted(mi, d)

    def resolve_class_dotted(self, mi, dotted: str,
                             _hops: int = 0) -> Optional[str]:
        if _hops > 4:
            return None
        parts = dotted.split(".")
        if len(parts) == 1:
            ci = self._by_module.get(mi.module, {}).get(parts[0])
            return ci.qual if ci else None
        for cut in range(len(parts) - 1, 0, -1):
            mod = self.program.lookup_module(".".join(parts[:cut]))
            if mod is None:
                continue
            rest = parts[cut:]
            ci = self._by_module.get(mod.module, {}).get(rest[0])
            if ci is not None and len(rest) == 1:
                return ci.qual
            tgt = mod.aliases.get(rest[0])
            if tgt is not None:
                return self.resolve_class_dotted(
                    mi, ".".join([tgt] + rest[1:]), _hops + 1)
            return None
        # no analyzed module owns the prefix: opaque external (threading.X)
        return dotted

    def resolve_annotation(self, mi, node: ast.AST) -> Optional[str]:
        """Annotation expression -> type qual. Unwraps ``Optional[X]`` and
        string annotations; unions/generics beyond that are dropped."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            try:
                node = ast.parse(node.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(node, ast.Subscript):
            base = dotted_expr(mi, node.value)
            if base in ("Optional", "typing.Optional"):
                return self.resolve_annotation(mi, node.slice)
            return None
        if isinstance(node, (ast.Name, ast.Attribute)):
            return self.resolve_class_expr(mi, node)
        return None

    def class_of(self, qual: Optional[str]) -> Optional[ClassInfo]:
        return self.classes.get(qual) if qual else None

    # -- expression typing ------------------------------------------------
    def local_env(self, fi) -> Dict[str, Optional[str]]:
        """Flow-insensitive local name -> type qual for one function.
        A name bound to two distinct types maps to None (unknown)."""
        env = self._env_cache.get(id(fi))
        if env is not None:
            return env
        mi = fi.module
        env = {}

        def bind(name: str, t: Optional[str]):
            if t is None:
                return
            if name in env and env[name] != t:
                env[name] = None  # conflicting bindings: unknown
            else:
                env.setdefault(name, t)

        args = fi.node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            if a.annotation is not None and a.arg not in ("self", "cls"):
                bind(a.arg, self.resolve_annotation(mi, a.annotation))
        self._env_cache[id(fi)] = env  # publish early: type_of may recurse
        for node in ast.walk(fi.node):
            target = value = ann = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                target, value, ann = node.target, node.value, node.annotation
            if not isinstance(target, ast.Name):
                continue
            if ann is not None:
                bind(target.id, self.resolve_annotation(mi, ann))
            if isinstance(value, ast.Call):
                t = self.resolve_class_expr(mi, value.func)
                if t in self.classes:
                    bind(target.id, t)
                else:
                    callee = self._callee_of(fi, value, env)
                    ret = getattr(callee, "node", None)
                    ret = getattr(ret, "returns", None) if ret else None
                    if ret is not None and callee is not None:
                        bind(target.id, self.resolve_annotation(
                            callee.module, ret))
            elif isinstance(value, (ast.Name, ast.Attribute)):
                bind(target.id, self.type_of(fi, value, env))
        return env

    def type_of(self, fi, expr: ast.AST,
                env: Optional[Dict[str, Optional[str]]] = None
                ) -> Optional[str]:
        """Type qual of an expression inside ``fi``: local names via the
        inferred environment, ``self.X`` via the class table, attribute
        chains one hop at a time (``self.a.b``)."""
        if env is None:
            env = self.local_env(fi)
        if isinstance(expr, ast.Name):
            if expr.id == "self" and fi.cls:
                return f"{fi.module.module}.{fi.cls}"
            return env.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self.type_of(fi, expr.value, env)
            ci = self.class_of(base)
            if ci is not None:
                return ci.attr_types.get(expr.attr)
            return None
        if isinstance(expr, ast.Call):
            callee = self._callee_of(fi, expr, env)
            ret = getattr(getattr(callee, "node", None), "returns", None)
            if callee is not None and ret is not None:
                return self.resolve_annotation(callee.module, ret)
        return None

    def _callee_of(self, fi, call: ast.Call, env):
        f = call.func
        callee = self.program.resolve_call(
            fi.module, f, fi.cls or fi.module.enclosing_class(call))
        if callee is not None:
            return callee
        if isinstance(f, ast.Attribute):
            ci = self.class_of(self.type_of(fi, f.value, env))
            if ci is not None:
                return ci.methods.get(f.attr)
        return None

    def method_callee(self, fi, call: ast.Call):
        """FuncInfo an attribute call resolves to — the call-graph resolver
        first, then typed-receiver lookup. None when the type is unknown."""
        return self._callee_of(fi, call, self.local_env(fi))

    def receiver_class(self, fi, call: ast.Call) -> Optional[ClassInfo]:
        if not isinstance(call.func, ast.Attribute):
            return None
        return self.class_of(self.type_of(fi, call.func.value))


def get_types(program) -> Types:
    t = program.cache.get(_TYPES_CACHE)
    if t is None:
        t = Types(program)
        program.cache[_TYPES_CACHE] = t
    return t
