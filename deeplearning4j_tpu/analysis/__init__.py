"""jaxlint — AST-based JAX/TPU-correctness static analysis for this repo.

Graph compilers (TF's grappler validators, TVM's relay passes) ship
graph-level lint because the worst accelerator bugs are invisible to unit
tests: a host sync inside a hot jitted step still *passes*, it is just 10x
slower on real hardware; a constant PRNG key still "samples", it just
samples the same thing forever. jaxlint is the equivalent for our jit/pjit
idiom.

Usage::

    python -m deeplearning4j_tpu.analysis deeplearning4j_tpu/ [--json]

or programmatically::

    from deeplearning4j_tpu.analysis import analyze_paths, analyze_source
    findings = analyze_paths(["deeplearning4j_tpu/"])

Suppress a finding with ``# jaxlint: disable=<rule>`` on the offending line
(``disable-next=`` / ``disable-file=`` variants exist). Rules are documented
in ``deeplearning4j_tpu/analysis/README.md``.
"""

from .callgraph import Program, build_program
from .compilesurface import (check_budget, compute_surface, load_budget,
                             render_report, site_bound)
from .engine import (Finding, Rule, analyze_paths, analyze_source,
                     iter_py_files, render_json, render_text)
from .errorflow import ErrorModel, get_error_model
from .errorsurface import check_budget as check_error_budget
from .errorsurface import compute_surface as compute_error_surface
from .errorsurface import load_budget as load_error_budget
from .locks import LockModel, get_lock_model
from .rules import ALL_RULES, rules_by_name
from .sarif import (fingerprints, load_baseline, new_findings, render_sarif,
                    to_sarif, write_baseline)
from .shapes import Interp, function_shapes
from .typeinfo import Types, get_types

__all__ = ["Finding", "Rule", "ALL_RULES", "rules_by_name", "analyze_paths",
           "analyze_source", "iter_py_files", "render_json", "render_text",
           "Program", "build_program", "to_sarif", "render_sarif",
           "fingerprints", "write_baseline", "load_baseline", "new_findings",
           "Types", "get_types", "LockModel", "get_lock_model",
           "Interp", "function_shapes", "compute_surface", "render_report",
           "site_bound", "check_budget", "load_budget",
           "ErrorModel", "get_error_model", "compute_error_surface",
           "check_error_budget", "load_error_budget"]
