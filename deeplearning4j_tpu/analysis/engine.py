"""jaxlint engine — rule registry, suppression handling, reports.

The analyzer is pure stdlib-``ast``: it never imports jax (or the package
under analysis), so CI can run it in milliseconds before paying the jax
import + trace cost of the test suite, and a broken runtime import can never
take the linter down with it.

Since v2 the engine is whole-program: :func:`analyze_paths` parses every
file up front into a :class:`~.callgraph.Program` (cross-module call graph,
jit closure, PRNG/donation summaries) and hands each rule a
:class:`FileContext` that carries both the per-file view and the program.
:func:`analyze_source` builds a one-file program, so single-file analysis
keeps working — it just sees no cross-module edges.

Suppression grammar (pylint-style, per physical line):

    x = float(n)              # jaxlint: disable=host-sync
    # jaxlint: disable-next=broad-except
    except Exception:
    # jaxlint: disable-file=float64-dtype     (anywhere in the file)

``disable=all`` silences every rule for that line.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*(disable|disable-next|disable-file)=([A-Za-z0-9_\-, ]+)")

#: directories never descended into when a path argument is a directory
SKIP_DIRS = {"__pycache__", "_build", ".git", ".ipynb_checkpoints"}


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Rule:
    """Base class for jaxlint rules.

    Subclasses set ``name`` (the kebab-case id used in reports and
    suppression comments), ``description`` (one line, shown by
    ``--list-rules``) and implement :meth:`check` yielding findings for one
    parsed file.
    """

    name: str = ""
    description: str = ""

    def check(self, ctx: "FileContext") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "FileContext", node: ast.AST, message: str) -> Finding:
        return Finding(self.name, ctx.path, getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), message)


class FileContext:
    """Everything a rule needs about one file: source, AST, import aliases,
    jit-context map — plus the whole program it was analyzed as part of.
    Built once per file, shared across rules."""

    def __init__(self, path: str, source: str, program=None):
        from .callgraph import Program
        from .jitgraph import JitContext

        if program is None:
            program = Program([(path, source)])
        err = program.parse_errors.get(path)
        if err is not None:
            raise err
        mi = program.module_for(path)
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.program = program
        self.module_info = mi
        self.tree = mi.tree
        self.imports = mi.imports
        self.jit = JitContext(program, mi)

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted path of a Name/Attribute chain (alias-aware),
        e.g. ``np.asarray`` -> ``numpy.asarray``. None if not resolvable."""
        return self.imports.resolve(node)

    @property
    def is_kernel_module(self) -> bool:
        return self.jit.kernel_module


def _suppressions(source: str) -> tuple[Dict[int, Set[str]], Set[str]]:
    """(per-line disabled rule sets, file-level disabled rules)."""
    per_line: Dict[int, Set[str]] = {}
    per_file: Set[str] = set()
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        kind, rules = m.group(1), {r.strip() for r in m.group(2).split(",") if r.strip()}
        if kind == "disable":
            per_line.setdefault(i, set()).update(rules)
        elif kind == "disable-next":
            per_line.setdefault(i + 1, set()).update(rules)
        else:  # disable-file
            per_file.update(rules)
    return per_line, per_file


def _suppressed(f: Finding, per_line: Dict[int, Set[str]], per_file: Set[str]) -> bool:
    if "all" in per_file or f.rule in per_file:
        return True
    rules = per_line.get(f.line)
    return bool(rules) and ("all" in rules or f.rule in rules)


def _check_file(path: str, source: str, program,
                rules: Sequence[Rule]) -> List[Finding]:
    try:
        ctx = FileContext(path, source, program)
    except SyntaxError as e:
        return [Finding("parse-error", path, e.lineno or 0, e.offset or 0,
                        f"could not parse: {e.msg}")]
    per_line, per_file = _suppressions(source)
    out: List[Finding] = []
    for rule in rules:
        for f in rule.check(ctx):
            if not _suppressed(f, per_line, per_file):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def analyze_source(source: str, path: str = "<string>",
                   rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Run ``rules`` (default: all registered) over one source string. The
    file is analyzed as a one-module program: cross-module rules degrade to
    their same-module behavior."""
    if rules is None:
        from .rules import ALL_RULES
        rules = ALL_RULES
    return _check_file(path, source, None, rules)


def _excluded(path: str, patterns: Sequence[str]) -> bool:
    norm = os.path.normpath(path).replace(os.sep, "/")
    parts = norm.split("/")
    for pat in patterns:
        if fnmatch.fnmatch(norm, pat) or \
                any(fnmatch.fnmatch(p, pat) for p in parts):
            return True
    return False


def iter_py_files(paths: Iterable[str],
                  exclude: Sequence[str] = ()) -> Iterator[str]:
    """Walk ``paths`` deterministically (sorted dirs and files, input order
    preserved) yielding ``.py`` files. ``exclude`` globs match against the
    normalized path or any single path component."""
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in SKIP_DIRS and not _excluded(
                        os.path.join(root, d), exclude))
                for fn in sorted(files):
                    fp = os.path.join(root, fn)
                    if fn.endswith(".py") and not _excluded(fp, exclude):
                        yield fp
        elif p.endswith(".py") and not _excluded(p, exclude):
            yield p


def read_sources(paths: Iterable[str],
                 exclude: Sequence[str] = ()) -> List[Tuple[str, str]]:
    out = []
    for fp in iter_py_files(paths, exclude):
        with open(fp, "r", encoding="utf-8") as fh:
            out.append((fp, fh.read()))
    return out


def analyze_paths(paths: Iterable[str],
                  rules: Optional[Sequence[Rule]] = None,
                  exclude: Sequence[str] = ()) -> List[Finding]:
    """Whole-program analysis over every ``.py`` file under ``paths``."""
    if rules is None:
        from .rules import ALL_RULES
        rules = ALL_RULES
    from .callgraph import Program

    sources = read_sources(paths, exclude)
    program = Program(sources)
    out: List[Finding] = []
    for fp, src in sources:
        out.extend(_check_file(fp, src, program, rules))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def render_text(findings: Sequence[Finding]) -> str:
    body = "\n".join(f.render() for f in findings)
    tail = f"\n{len(findings)} finding(s)" if findings else "jaxlint: clean"
    return (body + tail) if body else tail


def render_json(findings: Sequence[Finding]) -> str:
    return json.dumps({"count": len(findings),
                       "findings": [f.to_dict() for f in findings]}, indent=2)
