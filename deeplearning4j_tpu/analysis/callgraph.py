"""Whole-program call graph for jaxlint — cross-module import and alias
resolution over every analyzed file.

jaxlint v1 stopped at module boundaries: jit context propagated only through
same-module calls by bare name, so a helper in ``nn/`` reached exclusively
from another module's jitted step was invisible to every rule. This module
replaces that approximation with a :class:`Program` — all analyzed files
parsed once, a module table keyed by dotted name (derived from file paths,
suffix-matched so absolute and relative invocations agree), per-module alias
maps covering ``import``/``from``-imports including relative ones and one
level of ``__init__`` re-exports, and a call-edge resolver that understands
bare names, ``self.method()``, and aliased cross-module attributes.

On top of the graph, the program computes the facts interprocedural rules
query:

- the **jit closure**: every function reachable (through resolvable call
  edges, across modules) from a jit/pjit/shard_map/pmap root or defined in
  an ``ops/`` kernel module;
- **PRNG consumption summaries**: per function parameter, how many
  independent ``jax.random`` draws consume it without an intervening
  ``split``/``fold_in`` — propagated through call sites to a fixpoint
  (capped at 2: the analysis only distinguishes 0 / 1 / "reused");
- the **donation table**: which callables (decorated, ``jax.jit(fn, ...)``
  wrap-assigned to a name or a ``self.`` attribute) donate which parameters.

Everything is stdlib ``ast``; nothing here imports jax or the code under
analysis.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .dataflow import ForwardScan, assign_names

# module roots whose canonical names we track through aliases (per-file
# resolution of jax/numpy/stdlib names; the cross-module alias map in
# ModuleInfo is separate and tracks *analyzed* modules)
_CANON_MODULES = {
    "numpy": "numpy",
    "jax": "jax",
    "jax.numpy": "jax.numpy",
    "jax.random": "jax.random",
    "random": "random",
    "datetime": "datetime",
    "time": "time",
    "functools": "functools",
    "contextlib": "contextlib",
    "threading": "threading",
    "collections": "collections",
    "jax.experimental.pjit": "jax.experimental.pjit",
    "jax.experimental.shard_map": "jax.experimental.shard_map",
}

JIT_WRAPPERS = {"jax.jit", "jax.pjit", "pjit", "jax.experimental.pjit.pjit"}

#: transforms that trace their operand but take no donation kwargs —
#: functions wrapped by these are jit context, not donation sites
TRACE_ONLY_WRAPPERS = {"jax.shard_map", "shard_map", "jax.pmap",
                       "jax.experimental.shard_map.shard_map"}


class ImportMap:
    """Maps local names to canonical dotted paths via one file's imports."""

    def __init__(self, tree: ast.Module):
        self.aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in _CANON_MODULES or a.name.split(".")[0] in _CANON_MODULES:
                        self.aliases[(a.asname or a.name.split(".")[0])] = (
                            a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    full = f"{node.module}.{a.name}"
                    root = node.module.split(".")[0]
                    if root in _CANON_MODULES:
                        self.aliases[a.asname or a.name] = full

    def resolve(self, node: ast.AST) -> Optional[str]:
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))


def is_jit_expr(node: ast.AST, resolve) -> bool:
    """True for expressions evaluating to a jit transform: ``jax.jit``,
    ``partial(jax.jit, ...)`` — in decorator position or as a wrap callee."""
    q = resolve(node)
    if q in JIT_WRAPPERS:
        return True
    if isinstance(node, ast.Call):
        fq = resolve(node.func)
        if fq in JIT_WRAPPERS:
            return True
        if fq == "functools.partial" and node.args and resolve(node.args[0]) in JIT_WRAPPERS:
            return True
    return False


def is_trace_expr(node: ast.AST, resolve) -> bool:
    """jit transforms plus trace-only wrappers (shard_map, pmap)."""
    if is_jit_expr(node, resolve):
        return True
    q = resolve(node)
    if q in TRACE_ONLY_WRAPPERS:
        return True
    if isinstance(node, ast.Call):
        fq = resolve(node.func)
        if fq in TRACE_ONLY_WRAPPERS:
            return True
        if fq == "functools.partial" and node.args \
                and resolve(node.args[0]) in TRACE_ONLY_WRAPPERS:
            return True
    return False


def jit_call_kwargs(node: ast.AST, resolve) -> Optional[List[str]]:
    """If ``node`` is a jit transform *call* (``jax.jit(...)``,
    ``partial(jax.jit, ...)``), the keyword names passed to it; else None."""
    if not isinstance(node, ast.Call):
        return None
    fq = resolve(node.func)
    if fq in JIT_WRAPPERS:
        return [k.arg for k in node.keywords if k.arg]
    if fq == "functools.partial" and node.args and resolve(node.args[0]) in JIT_WRAPPERS:
        return [k.arg for k in node.keywords if k.arg]
    return None


def _jit_donation(node: ast.AST, resolve) -> Tuple[Optional[List[int]],
                                                   Optional[List[str]]]:
    """Literal donate_argnums / donate_argnames of a jit expr, if present."""
    if not isinstance(node, ast.Call):
        return None, None
    if jit_call_kwargs(node, resolve) is None:
        return None, None
    nums: Optional[List[int]] = None
    names: Optional[List[str]] = None
    for k in node.keywords:
        v = k.value
        if k.arg == "donate_argnums":
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums = [v.value]
            elif isinstance(v, (ast.Tuple, ast.List)):
                nums = [e.value for e in v.elts
                        if isinstance(e, ast.Constant) and isinstance(e.value, int)]
        elif k.arg == "donate_argnames":
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names = [v.value]
            elif isinstance(v, (ast.Tuple, ast.List)):
                names = [e.value for e in v.elts
                         if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return nums, names


def module_name_for(path: str) -> Tuple[str, bool]:
    """(dotted module name, is_package) for a file path. The name is built
    from the trailing path components that are valid identifiers, so
    ``deeplearning4j_tpu/parallel/mesh.py`` analyzed from the repo root gets
    exactly the name its absolute imports use; absolute invocations are
    reconciled by suffix matching in :meth:`Program.lookup_module`."""
    parts = os.path.normpath(path).replace(os.sep, "/").split("/")
    last = parts[-1]
    if last.endswith(".py"):
        last = last[:-3]
    is_pkg = last == "__init__"
    comps = parts[:-1] + ([] if is_pkg else [last])
    mod: List[str] = []
    for c in reversed(comps):
        if c.isidentifier():
            mod.append(c)
        else:
            break
    return ".".join(reversed(mod)), is_pkg


class FuncInfo:
    """One function or method definition in the program."""

    __slots__ = ("module", "node", "name", "qual", "cls", "params", "jit",
                 "donated_idx", "donated_names", "prng_uses")

    def __init__(self, module: "ModuleInfo", node: ast.AST, cls: Optional[str]):
        self.module = module
        self.node = node
        self.name = node.name
        self.cls = cls
        self.qual = f"{cls}.{node.name}" if cls else node.name
        args = node.args
        params = [a.arg for a in list(args.posonlyargs) + list(args.args)]
        if cls and params and params[0] in ("self", "cls"):
            params = params[1:]
        #: positional parameter names as seen by callers (self dropped)
        self.params: List[str] = params
        self.jit = False
        self.donated_idx: Set[int] = set()
        self.donated_names: Set[str] = set()
        #: param name -> 0 (untouched/opaque) | 1 (consumed once) | 2 (reused)
        self.prng_uses: Dict[str, int] = {}

    @property
    def donates(self) -> bool:
        return bool(self.donated_idx or self.donated_names)

    def donated_params(self) -> Set[str]:
        out = set(self.donated_names)
        for i in self.donated_idx:
            if i < len(self.params):
                out.add(self.params[i])
        return out

    def __repr__(self):
        return f"<FuncInfo {self.module.module}:{self.qual}>"


class ModuleInfo:
    """One analyzed file: AST, import maps, function tables, parents."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.module, self.is_package = module_name_for(path)
        self.kernel = "ops" in os.path.normpath(path).split(os.sep)
        self.imports = ImportMap(tree)

        #: local name -> dotted target (module, or module.attr) — every
        #: import, not just canonical ones; used for cross-module resolution
        self.aliases: Dict[str, str] = {}
        #: module-level string constants (axis names etc.): name -> value
        self.str_consts: Dict[str, str] = {}
        #: "f" / "Cls.m" -> FuncInfo (top-level defs and methods)
        self.functions: Dict[str, FuncInfo] = {}
        #: every def in the file by bare name, outermost-first (v1 semantics)
        self.local_funcs: Dict[str, FuncInfo] = {}
        self.all_funcs: List[FuncInfo] = []
        #: (FuncInfo, jit expr) for every way a local function gets jitted
        self.jit_applications: List[Tuple[FuncInfo, ast.AST]] = []
        #: caller-visible donating callables: "name" / "Cls.attr" -> FuncInfo
        self.donating_names: Dict[str, FuncInfo] = {}

        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

        self._collect_aliases()
        self._collect_functions()

    # -- construction -----------------------------------------------------
    def _rel_base(self, level: int) -> Optional[str]:
        base = self.module if self.is_package else \
            ".".join(self.module.split(".")[:-1])
        for _ in range(level - 1):
            if not base:
                return None
            base = ".".join(base.split(".")[:-1])
        return base or None

    def _collect_aliases(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.aliases[a.asname] = a.name
                    else:
                        self.aliases.setdefault(a.name.split(".")[0],
                                                a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                if node.level == 0:
                    base = node.module
                else:
                    base = self._rel_base(node.level)
                    if node.module:
                        base = f"{base}.{node.module}" if base else node.module
                if not base:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = f"{base}.{a.name}"
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and isinstance(stmt.value, ast.Constant) \
                    and isinstance(stmt.value.value, str):
                self.str_consts[stmt.targets[0].id] = stmt.value.value

    def _collect_functions(self):
        def visit(node, cls: Optional[str], top: bool):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fi = FuncInfo(self, child, cls)
                    self.all_funcs.append(fi)
                    if top or cls:
                        self.functions.setdefault(fi.qual, fi)
                    self.local_funcs.setdefault(fi.name, fi)
                    visit(child, None, False)
                elif isinstance(child, ast.ClassDef):
                    # nested classes (the servers' closure-scoped Handler
                    # classes) still register methods under their class name
                    visit(child, child.name, False)
                else:
                    visit(child, cls, top and isinstance(node, ast.Module))

        visit(self.tree, None, True)

    def enclosing_class(self, node: ast.AST) -> Optional[str]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = self.parents.get(cur)
        return None

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cur = self.parents.get(cur)
        return cur


class Program:
    """All analyzed files as one unit: module table, call resolution, and
    the whole-program facts (jit closure, PRNG summaries, donation table).
    """

    _MAX_ALIAS_HOPS = 6

    def __init__(self, sources: Iterable[Tuple[str, str]]):
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_path: Dict[str, ModuleInfo] = {}
        self.parse_errors: Dict[str, SyntaxError] = {}
        #: scratch space for rules to memoize program-wide facts
        self.cache: Dict[str, object] = {}
        for path, source in sources:
            try:
                tree = ast.parse(source, filename=path)
            except SyntaxError as e:
                self.parse_errors[path] = e
                continue
            mi = ModuleInfo(path, source, tree)
            self.modules[mi.module] = mi
            self.by_path[os.path.normpath(path)] = mi
        self._suffixes: Dict[str, Optional[ModuleInfo]] = {}
        for name, mi in self.modules.items():
            parts = name.split(".")
            for i in range(len(parts)):
                suf = ".".join(parts[i:])
                if suf in self.modules:
                    continue  # exact names always win
                # ambiguous suffixes resolve to nothing
                self._suffixes[suf] = None if suf in self._suffixes else mi
        self._compute_jit()
        self._compute_donations()
        self._compute_prng_summaries()

    # -- resolution -------------------------------------------------------
    def module_for(self, path: str) -> Optional[ModuleInfo]:
        return self.by_path.get(os.path.normpath(path))

    def lookup_module(self, dotted: str) -> Optional[ModuleInfo]:
        return self.modules.get(dotted) or self._suffixes.get(dotted)

    def resolve_dotted(self, dotted: str, _hops: int = 0) -> Optional[FuncInfo]:
        """``pkg.mod.fn`` / ``pkg.mod.Cls.m`` -> FuncInfo, chasing re-export
        aliases (``from .mesh import make_mesh`` in an ``__init__``)."""
        if _hops > self._MAX_ALIAS_HOPS:
            return None
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mi = self.lookup_module(".".join(parts[:cut]))
            if mi is None:
                continue
            rest = parts[cut:]
            fi = mi.functions.get(".".join(rest))
            if fi is not None:
                return fi
            tgt = mi.aliases.get(rest[0])
            if tgt is not None:
                return self.resolve_dotted(".".join([tgt] + rest[1:]), _hops + 1)
            return None
        return None

    def resolve_call(self, mi: ModuleInfo, func: ast.AST,
                     cls: Optional[str] = None) -> Optional[FuncInfo]:
        """Resolve a call's callee expression to a FuncInfo, or None.

        Handles: bare names (any def in the same file, v1 semantics),
        ``self.method()`` within a class, and dotted paths through the
        module's import aliases (``mesh.make_mesh`` / ``make_mesh`` after a
        from-import, including relative imports and __init__ re-exports).
        """
        if isinstance(func, ast.Name):
            fi = mi.local_funcs.get(func.id)
            if fi is not None:
                return fi
            tgt = mi.aliases.get(func.id)
            return self.resolve_dotted(tgt) if tgt else None
        if isinstance(func, ast.Attribute):
            parts: List[str] = []
            node = func
            while isinstance(node, ast.Attribute):
                parts.append(node.attr)
                node = node.value
            if not isinstance(node, ast.Name):
                return None
            parts.reverse()
            if node.id == "self":
                if len(parts) == 1:
                    if cls is None:
                        cls = mi.enclosing_class(func)
                    if cls:
                        return mi.functions.get(f"{cls}.{parts[0]}")
                return None
            if len(parts) == 1 and node.id in mi.functions:
                # Cls.method called through the class
                return mi.functions.get(f"{node.id}.{parts[0]}")
            tgt = mi.aliases.get(node.id)
            if tgt is not None:
                return self.resolve_dotted(".".join([tgt] + parts))
            return None
        return None

    def map_call_args(self, call: ast.Call, callee: FuncInfo
                      ) -> List[Tuple[str, ast.expr]]:
        """(parameter name, argument expr) pairs for resolvable positions of
        a call site — starred args stop positional matching, ``**kw`` is
        skipped."""
        out: List[Tuple[str, ast.expr]] = []
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                break
            if i < len(callee.params):
                out.append((callee.params[i], a))
        for k in call.keywords:
            if k.arg and k.arg in callee.params:
                out.append((k.arg, k.value))
        return out

    # -- jit closure ------------------------------------------------------
    def _compute_jit(self):
        roots: List[FuncInfo] = []
        for mi in self.modules.values():
            resolve = mi.imports.resolve
            if mi.kernel:
                roots.extend(mi.all_funcs)
            for fi in mi.all_funcs:
                for dec in fi.node.decorator_list:
                    if is_trace_expr(dec, resolve):
                        roots.append(fi)
                    if is_jit_expr(dec, resolve):
                        mi.jit_applications.append((fi, dec))
            for node in ast.walk(mi.tree):
                if not (isinstance(node, ast.Call)
                        and is_trace_expr(node.func, resolve)):
                    continue
                if not (node.args and isinstance(node.args[0], ast.Name)):
                    continue
                fi = mi.local_funcs.get(node.args[0].id)
                if fi is None:
                    continue
                roots.append(fi)
                if is_jit_expr(node.func, resolve) or (
                        jit_call_kwargs(node, resolve) is not None):
                    mi.jit_applications.append(
                        (fi, node.func if isinstance(node.func, ast.Call)
                         else node))
        work = list(roots)
        for fi in work:
            fi.jit = True
        while work:
            fi = work.pop()
            mi = fi.module
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = self.resolve_call(mi, node.func,
                                           mi.enclosing_class(node))
                if callee is not None and not callee.jit:
                    callee.jit = True
                    work.append(callee)

    # -- donation ---------------------------------------------------------
    def _compute_donations(self):
        for mi in self.modules.values():
            resolve = mi.imports.resolve
            for fi, expr in mi.jit_applications:
                nums, names = _jit_donation(expr, resolve)
                if nums:
                    fi.donated_idx.update(nums)
                if names:
                    fi.donated_names.update(names)
                if fi.donates:
                    self._bind_donating_name(mi, fi)
            # name = jax.jit(fn, donate_argnums=...) / self.X = jax.jit(...)
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                    continue
                v = node.value
                if not (isinstance(v, ast.Call) and v.args):
                    continue
                nums, names = _jit_donation(v, resolve)
                if not (nums or names):
                    continue
                inner = v.args[0]
                # unwrap jax.jit(jax.shard_map(fn, ...), donate_argnums=...)
                while isinstance(inner, ast.Call) and inner.args and \
                        is_trace_expr(inner.func, resolve):
                    inner = inner.args[0]
                if not isinstance(inner, ast.Name):
                    continue
                fi = mi.local_funcs.get(inner.id)
                if fi is None:
                    continue
                if nums:
                    fi.donated_idx.update(nums)
                if names:
                    fi.donated_names.update(names)
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    mi.donating_names[t.id] = fi
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and t.value.id == "self":
                    cls = mi.enclosing_class(node)
                    if cls:
                        mi.donating_names[f"{cls}.{t.attr}"] = fi

    @staticmethod
    def _bind_donating_name(mi: ModuleInfo, fi: FuncInfo):
        mi.donating_names.setdefault(fi.qual, fi)
        mi.donating_names.setdefault(fi.name, fi)

    def donating_callee(self, mi: ModuleInfo, call: ast.Call
                        ) -> Optional[FuncInfo]:
        """The donating FuncInfo a call site invokes, or None."""
        f = call.func
        if isinstance(f, ast.Name):
            fi = mi.donating_names.get(f.id)
            if fi is not None:
                return fi
        elif isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self":
            cls = mi.enclosing_class(call)
            if cls:
                fi = mi.donating_names.get(f"{cls}.{f.attr}")
                if fi is not None:
                    return fi
        fi = self.resolve_call(mi, f, mi.enclosing_class(call))
        return fi if fi is not None and fi.donates else None

    # -- PRNG summaries ---------------------------------------------------
    _SAMPLER_EXEMPT = {"PRNGKey", "key", "split", "fold_in", "wrap_key_data",
                       "key_data", "clone", "key_impl", "bits"}

    class _DrawCount(ForwardScan):
        """Max draws per key name along any path — exclusive ``if d ==
        "normal": return normal(key) ... return uniform(key)`` initializer
        dispatch counts as one draw, not two."""

        def __init__(self, resolve, exempt):
            super().__init__()
            self._resolve = resolve
            self._exempt = exempt

        def visit_expr(self, expr, state):
            for node in ast.walk(expr):
                if isinstance(node, ast.Call) and node.args \
                        and isinstance(node.args[0], ast.Name):
                    q = self._resolve(node.func)
                    if q and q.startswith("jax.random.") \
                            and q.rsplit(".", 1)[1] not in self._exempt:
                        n = node.args[0].id
                        state[n] = state.get(n, 0) + 1
            return iter(())

    def _compute_prng_summaries(self):
        """Per-function raw facts: which params are split, which are rebound
        (opaque to the analysis), how many jax.random draws consume each
        directly, and which call sites forward a param to another analyzed
        function. Transitive consumption is resolved at query time by
        :meth:`prng_param_uses` (counts saturate at 2: 0 = untouched,
        1 = consumed once, 2 = reused without a split)."""
        self._prng_callsites: Dict[FuncInfo, List[Tuple[str, FuncInfo, str]]] = {}
        self._prng_facts: Dict[FuncInfo, Tuple[Set[str], Set[str],
                                               Dict[str, int]]] = {}
        for mi in self.modules.values():
            resolve = mi.imports.resolve
            for fi in mi.all_funcs:
                params = set(fi.params)
                if not params:
                    continue
                reassigned: Set[str] = set()
                split: Set[str] = set()
                sites: List[Tuple[str, FuncInfo, str]] = []
                # path-sensitive local draw counts (exclusive branches merge
                # with max, early-return branches are excluded)
                counts: Dict[str, int] = {}
                scan = self._DrawCount(resolve, self._SAMPLER_EXEMPT)
                for _ in scan.scan(fi.node.body, counts):
                    pass
                direct = {p: c for p, c in counts.items() if p in params}
                for node in ast.walk(fi.node):
                    if isinstance(node, (ast.Assign, ast.AugAssign,
                                         ast.AnnAssign, ast.For)):
                        tgts = node.targets if isinstance(node, ast.Assign) \
                            else [node.target]
                        for t in tgts:
                            reassigned.update(
                                n for n in assign_names(t) if n in params)
                    if not isinstance(node, ast.Call):
                        continue
                    q = resolve(node.func)
                    argname = node.args[0].id if node.args and \
                        isinstance(node.args[0], ast.Name) else None
                    if q and q.startswith("jax.random."):
                        if argname in params \
                                and q.rsplit(".", 1)[1] in ("split", "fold_in"):
                            split.add(argname)
                    else:
                        callee = self.resolve_call(mi, node.func,
                                                   mi.enclosing_class(node))
                        if callee is not None and callee is not fi:
                            for pname, arg in self.map_call_args(node, callee):
                                if isinstance(arg, ast.Name) \
                                        and arg.id in params:
                                    sites.append((arg.id, callee, pname))
                self._prng_facts[fi] = (split, reassigned, direct)
                self._prng_callsites[fi] = sites
        # resolve transitive summaries only after every module's facts exist
        for fi in self._prng_facts:
            for p in fi.params:
                fi.prng_uses[p] = self.prng_param_uses(fi, p)

    def prng_param_uses(self, fi: FuncInfo, param: str,
                        _seen: Optional[Set[Tuple[int, str]]] = None) -> int:
        """How many independent jax.random draws consume ``param`` when the
        function is called — 0 (never / opaque), 1 (once, or split first so
        downstream use is well-formed), 2 (reused without a split).
        Transitive through call sites that forward the param."""
        if _seen is None:
            _seen = set()
        key = (id(fi), param)
        if key in _seen:
            return 0
        _seen.add(key)
        facts = self._prng_facts.get(fi)
        if facts is None:
            return 0
        split, reassigned, direct = facts
        if param in reassigned:
            return 0  # rebound locally: nothing provable about the original
        if param in split:
            return 1  # split gates every downstream draw
        uses = direct.get(param, 0)
        for argname, callee, pname in self._prng_callsites.get(fi, []):
            if uses >= 2:
                break
            if argname == param:
                uses += self.prng_param_uses(callee, pname, _seen)
        return min(uses, 2)

    def prng_callee_uses(self, mi: ModuleInfo, call: ast.Call
                         ) -> List[Tuple[str, FuncInfo, int]]:
        """For one call site: (caller-side arg name, callee, consumption)
        for every bare-Name argument the callee draws from. Consumption 2
        means the callee (transitively) reuses the key without splitting."""
        callee = self.resolve_call(mi, call.func, mi.enclosing_class(call))
        if callee is None:
            return []
        out = []
        for pname, arg in self.map_call_args(call, callee):
            if not isinstance(arg, ast.Name):
                continue
            uses = self.prng_param_uses(callee, pname)
            if uses:
                out.append((arg.id, callee, uses))
        return out

    # -- constants --------------------------------------------------------
    def resolve_const_str(self, mi: ModuleInfo, node: ast.AST) -> Optional[str]:
        """Resolve a Name/Attribute (or string literal) to a module-level
        string constant, chasing import aliases — ``mesh.DATA_AXIS`` or a
        from-imported ``DATA_AXIS`` both resolve to ``"data"``."""
        if isinstance(node, ast.Constant):
            return node.value if isinstance(node.value, str) else None
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        parts.append(cur.id)
        parts.reverse()
        if len(parts) == 1 and parts[0] in mi.str_consts:
            return mi.str_consts[parts[0]]
        tgt = mi.aliases.get(parts[0])
        if tgt is None:
            return None
        return self._const_from_dotted(".".join([tgt] + parts[1:]), 1)

    def _const_from_dotted(self, dotted: str, _hops: int) -> Optional[str]:
        if _hops > self._MAX_ALIAS_HOPS:
            return None
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mi = self.lookup_module(".".join(parts[:cut]))
            if mi is None:
                continue
            rest = parts[cut:]
            if len(rest) == 1 and rest[0] in mi.str_consts:
                return mi.str_consts[rest[0]]
            tgt = mi.aliases.get(rest[0])
            if tgt is not None:
                return self._const_from_dotted(
                    ".".join([tgt] + rest[1:]), _hops + 1)
            return None
        return None

    # -- convenience ------------------------------------------------------
    def jit_func_nodes(self, mi: ModuleInfo) -> Set[ast.AST]:
        return {fi.node for fi in mi.all_funcs if fi.jit}


def build_program(sources: Sequence[Tuple[str, str]]) -> Program:
    return Program(sources)
