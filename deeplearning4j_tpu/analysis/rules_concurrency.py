"""jaxlint concurrency & resource-discipline rules.

The serving stack's correctness rests on hand-enforced disciplines — lock
ordering, "reserve under the lock, transfer outside it", lease/allocation
pairing, bounded metric label sets. These rules turn each discipline into
a whole-program check over the typed call graph (:mod:`.typeinfo`) and
the lock model (:mod:`.locks`):

- ``lock-order-cycle`` — cycles in the program's lock-acquisition-order
  graph (potential ABBA deadlocks);
- ``blocking-call-under-lock`` — I/O, sleeps, device syncs, subprocess,
  ``Event.wait``/``Thread.join`` executed (directly or transitively)
  while a lock is held;
- ``acquire-release`` — allocations/leases released on every path
  including exceptions, context managers actually entered, must-use
  results actually used;
- ``property-vs-call`` — ``@property`` attributes called like methods,
  and bound methods truth-tested without being called (the PR 12
  ``entry.resident()`` drain-bug family, both directions);
- ``metric-docs-drift`` — metric families missing from ``obs/README.md``
  or emitted with diverging label sets across call sites.

All findings ride the normal engine: suppressible per line, SARIF'd,
baselined. Functions that *deliberately* block under a lock opt out with
``# jaxlint: sanction=blocking-call-under-lock`` on their ``def`` line
(see :mod:`.locks` for semantics).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from .engine import FileContext, Finding, Rule
from .locks import get_lock_model
from .rules import register
from .typeinfo import dotted_expr, get_types


@register
class LockOrderCycleRule(Rule):
    """Cycles in the lock-acquisition-order graph.

    If thread 1 takes A then B while thread 2 takes B then A, each can
    hold one lock and wait forever on the other — the classic ABBA
    deadlock, invisible to tests unless the interleaving actually fires.
    The lock model records an edge A -> B whenever a function acquires B
    (directly or through any resolvable callee, across modules) while
    holding A; a cycle among the edges is a potential deadlock. Lock
    identity is nominal — ``module.Class.attr`` — so two instances of one
    class share an identity and self-edges are not reported (an RLock
    re-enter and a two-instance ABBA are indistinguishable statically).
    """

    name = "lock-order-cycle"
    description = ("cycle in the whole-program lock-acquisition graph "
                   "(potential ABBA deadlock)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        model = get_lock_model(ctx.program)
        for comp in model.cycles():
            in_comp = set(comp)
            edges = sorted(
                (w, a, b) for (a, b), w in model.order_edges.items()
                if a in in_comp and b in in_comp)
            if not edges:
                continue
            (path, line, via), a, b = edges[0]
            if os.path.normpath(path) != os.path.normpath(ctx.path):
                continue
            detail = "; ".join(
                f"{ea} -> {eb} ({wp}:{wl}, {wv})"
                for (wp, wl, wv), ea, eb in edges[:4])
            yield Finding(
                self.name, ctx.path, line, 0,
                f"lock-order cycle between {', '.join(comp)} — threads "
                f"taking these locks in opposite orders can deadlock "
                f"(ABBA). Witnesses: {detail}. Fix by imposing one "
                f"acquisition order or narrowing one critical section")


@register
class BlockingUnderLockRule(Rule):
    """Blocking work executed while a lock is held.

    A lock held across a sleep, a socket round-trip, a device transfer,
    a ``subprocess`` call, or an ``Event.wait``/``Thread.join`` turns one
    slow operation into a stall for *every* thread contending on that
    lock — the registry freeze and watchdog false-positives of PR 8's
    postmortems. The check is transitive over the typed call graph: a
    helper three calls deep that sleeps is charged to the caller holding
    the lock, with the witness chain in the message. ``Condition.wait``
    on the *held* condition is exempt (the wait releases it — the
    sanctioned wait-loop idiom). Deliberately-blocking helpers opt out
    with ``# jaxlint: sanction=blocking-call-under-lock`` plus a written
    justification.
    """

    name = "blocking-call-under-lock"
    description = ("I/O / sleep / device sync / Event.wait / Thread.join "
                   "reachable while a lock is held")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        model = get_lock_model(ctx.program)
        for fi in ctx.module_info.all_funcs:
            if model.sanctioned(fi, self.name):
                continue
            direct = {id(s.node): s for s in model.direct_blocks(fi)}
            callee_at = {id(call): callee
                         for call, callee in model.call_edges.get(fi, ())}
            for ev in model.events(fi):
                if ev[0] != "call":
                    continue
                _, node, held = ev
                if not held:
                    continue
                site = direct.get(id(node))
                if site is not None:
                    eff = [h for h in held if h != site.exempt_lock]
                    if eff:
                        yield self.finding(
                            ctx, node,
                            f"{site.desc} while holding {', '.join(eff)} "
                            f"— every thread contending on the lock stalls "
                            f"behind it; move the blocking work outside "
                            f"the critical section (copy-then-release), "
                            f"or sanction the helper if deliberate")
                    continue
                callee = callee_at.get(id(node))
                if callee is None:
                    continue
                chain = model.block_chain.get(callee)
                if chain and not model.sanctioned(callee, self.name):
                    yield self.finding(
                        ctx, node,
                        f"call blocks while holding {', '.join(held)}: "
                        f"{' -> '.join(chain)} — release the lock before "
                        f"the slow work, or sanction the helper "
                        f"(# jaxlint: sanction={self.name}) with a "
                        f"justification")


#: (class-name suffix, acquire method) -> release method names. Receivers
#: are resolved nominally, so look-alike ``ensure``/``alloc`` methods on
#: unrelated classes never match.
_ACQ_PROTOCOLS: Dict[Tuple[str, str], Tuple[str, ...]] = {
    ("BlockAllocator", "alloc"): ("free", "release"),
    ("SlotPages", "ensure"): ("release", "free"),
}

#: (class-name suffix, method) whose boolean/token result must be used —
#: a bare-statement call silently burns the budget/allocation
_MUST_USE: Set[Tuple[str, str]] = {
    ("RetryBudget", "spend"),
    ("TokenBucket", "take"),
    ("BlockAllocator", "alloc"),
    ("SlotPages", "ensure"),
}


@register
class AcquireReleaseRule(Rule):
    """Resource acquisitions must be released on all paths.

    The PR 12 drain bug's family: a lease/allocation taken and then
    leaked on an early-error path. Three checks, all over nominally
    typed receivers:

    1. an allocation (``BlockAllocator.alloc``, ``SlotPages.ensure``)
       bound to a local must be released (``free``/``release``) or have
       its ownership transferred (returned, stored, passed on) — on the
       normal path, on early returns, and when a call between acquire
       and release can raise (release must sit in a ``finally`` or an
       exception handler);
    2. a ``@contextmanager`` callee (``ModelRegistry.lease``) must
       actually be entered with ``with`` — a bare call builds the
       generator and leases nothing;
    3. must-use results (``RetryBudget.spend``, ``TokenBucket.take``)
       discarded as a bare statement are silently burned tokens.
    """

    name = "acquire-release"
    description = ("allocation/lease not released on every path (incl. "
                   "exceptions), contextmanager not entered, or must-use "
                   "result discarded")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        types = get_types(ctx.program)
        mi = ctx.module_info
        for fi in mi.all_funcs:
            yield from self._check_fn(ctx, types, fi)

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _recv_suffix(types, fi, call: ast.Call) -> Optional[str]:
        ci = types.receiver_class(fi, call)
        return ci.name if ci is not None else None

    def _check_fn(self, ctx, types, fi) -> Iterator[Finding]:
        mi = fi.module
        acquisitions = []  # (stmt, name, release names, class name)
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute):
                cname = self._recv_suffix(types, fi, node.value)
                key = (cname, node.value.func.attr)
                if key in _ACQ_PROTOCOLS:
                    acquisitions.append((node, node.targets[0].id,
                                         _ACQ_PROTOCOLS[key], cname))
            elif isinstance(node, ast.Call):
                callee = types.method_callee(fi, node)
                parent = mi.parents.get(node)
                if callee is not None and self._is_ctxmanager(callee):
                    yield from self._check_cm_use(ctx, fi, node, callee,
                                                  parent)
                if isinstance(node.func, ast.Attribute) \
                        and isinstance(parent, ast.Expr):
                    cname = self._recv_suffix(types, fi, node)
                    if (cname, node.func.attr) in _MUST_USE:
                        yield Finding(
                            self.name, ctx.path, node.lineno,
                            node.col_offset,
                            f"result of {cname}.{node.func.attr}() is "
                            f"discarded — the token/allocation is spent "
                            f"either way; branch on the result or bind it")
        for acq_stmt, name, releases, cname in acquisitions:
            yield from self._check_pairing(ctx, fi, acq_stmt, name,
                                           releases, cname)

    @staticmethod
    def _is_ctxmanager(callee) -> bool:
        node = getattr(callee, "node", None)
        if node is None:
            return False
        mi = callee.module
        return any(dotted_expr(mi, d) == "contextlib.contextmanager"
                   for d in node.decorator_list)

    def _check_cm_use(self, ctx, fi, call, callee, parent
                      ) -> Iterator[Finding]:
        mi = fi.module
        if isinstance(parent, ast.withitem):
            return
        if isinstance(parent, ast.Expr):
            yield Finding(
                self.name, ctx.path, call.lineno, call.col_offset,
                f"'{callee.qual}' is a @contextmanager but the call is a "
                f"bare statement — the generator is built and discarded, "
                f"nothing is leased/entered; use `with "
                f"{callee.name}(...):`")
            return
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                and isinstance(parent.targets[0], ast.Name):
            bound = parent.targets[0].id
            for n in ast.walk(fi.node):
                if isinstance(n, ast.withitem) \
                        and isinstance(n.context_expr, ast.Name) \
                        and n.context_expr.id == bound:
                    return
                if isinstance(n, ast.Call):
                    f = n.func
                    if isinstance(f, ast.Attribute) \
                            and isinstance(f.value, ast.Name) \
                            and f.value.id == bound \
                            and f.attr in ("__enter__",):
                        return
                if isinstance(n, ast.Return) and n.value is not None \
                        and any(isinstance(x, ast.Name) and x.id == bound
                                for x in ast.walk(n.value)):
                    return  # ownership transferred to the caller
            yield Finding(
                self.name, ctx.path, call.lineno, call.col_offset,
                f"'{callee.qual}' is a @contextmanager assigned to "
                f"'{bound}' but never entered with `with` — the lease "
                f"body never runs")

    def _check_pairing(self, ctx, fi, acq_stmt, name, releases, cname
                       ) -> Iterator[Finding]:
        mi = fi.module
        acq_line = acq_stmt.lineno
        release_nodes: List[ast.Call] = []
        escape_nodes: List[ast.AST] = []
        for n in ast.walk(fi.node):
            if getattr(n, "lineno", 0) <= acq_line \
                    and n is not acq_stmt:
                continue
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
                uses = any(isinstance(a, ast.Name) and a.id == name
                           for a in list(n.args)
                           + [k.value for k in n.keywords])
                if not uses:
                    continue
                if n.func.attr in releases:
                    release_nodes.append(n)
                else:
                    escape_nodes.append(n)  # ownership transferred
            elif isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)) \
                    and n.value is not None:
                if any(isinstance(x, ast.Name) and x.id == name
                       for x in ast.walk(n.value)):
                    escape_nodes.append(n)
            elif isinstance(n, ast.Assign):
                for t in n.targets:
                    if not (isinstance(t, ast.Name) and t.id == name) \
                            and any(isinstance(x, ast.Name)
                                    and x.id == name
                                    for x in ast.walk(n.value)):
                        escape_nodes.append(n)  # aliased/stored
        settled = release_nodes + escape_nodes
        if not settled:
            yield Finding(
                self.name, ctx.path, acq_line, acq_stmt.col_offset,
                f"'{name}' holds a {cname} allocation that is never "
                f"released ({'/'.join(releases)}) nor handed off — the "
                f"blocks leak for the process lifetime")
            return
        first_settle = min(getattr(n, "lineno", 10 ** 9) for n in settled)
        protected = self._exception_protected(fi, acq_stmt, releases, name)
        risky = self._first_risky(fi, acq_stmt, first_settle, settled)
        if risky is not None and not protected:
            what = ("an exception in "
                    f"'{ast.unparse(risky.func) if isinstance(risky, ast.Call) else 'this path'}'"
                    if isinstance(risky, ast.Call) else "a raise")
            yield Finding(
                self.name, ctx.path, risky.lineno, risky.col_offset,
                f"'{name}' ({cname} allocation, line {acq_line}) is "
                f"released on the normal path but leaks if {what} "
                f"propagates before the release — wrap the region in "
                f"try/finally or release in the handler")

    @staticmethod
    def _exception_protected(fi, acq_stmt, releases, name) -> bool:
        """True when a ``try`` at/after the acquisition releases or hands
        off ``name`` in its ``finally`` or an exception handler — covers
        both ``x = alloc()`` inside the try and the standard
        acquire-then-``try`` idiom where the acquisition precedes it."""
        def settles(body) -> bool:
            for n in body:
                for x in ast.walk(n):
                    if isinstance(x, ast.Call) \
                            and isinstance(x.func, ast.Attribute) \
                            and x.func.attr in releases \
                            and any(isinstance(a, ast.Name)
                                    and a.id == name for a in x.args):
                        return True
            return False

        for t in ast.walk(fi.node):
            if not isinstance(t, ast.Try):
                continue
            if t.end_lineno is not None and t.end_lineno < acq_stmt.lineno:
                continue  # the whole try ended before the acquisition
            if settles(t.finalbody) or any(settles(h.body)
                                           for h in t.handlers):
                return True
        return False

    @staticmethod
    def _first_risky(fi, acq_stmt, first_settle: int, settled
                     ) -> Optional[ast.AST]:
        """First call/raise strictly between the acquisition and the
        first release/hand-off — the statement whose exception would
        leak the resource."""
        settled_ids = {id(s) for s in settled}
        best = None
        for n in ast.walk(fi.node):
            ln = getattr(n, "lineno", 0)
            if not (acq_stmt.lineno < ln < first_settle):
                continue
            if id(n) in settled_ids:
                continue
            if isinstance(n, (ast.Call, ast.Raise)):
                if best is None or ln < best.lineno:
                    best = n
        return best


@register
class PropertyVsCallRule(Rule):
    """``@property`` called like a method / bound method used like a value.

    Both directions of the PR 12 drain bug: ``entry.resident()`` raised
    ``TypeError: 'bool' object is not callable`` (400 on every drain)
    because ``resident`` is a property; the mirror bug — ``if
    entry.resident:`` where ``resident`` is a *method* — is always
    truthy and silently disables the branch. Receivers are resolved
    nominally (constructor bindings, annotations, typed returns), so a
    ``resident`` property on one class never taints a same-named method
    elsewhere.
    """

    name = "property-vs-call"
    description = ("@property invoked with (), or zero-arg method "
                   "truth-tested/compared without being called")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        types = get_types(ctx.program)
        for fi in ctx.module_info.all_funcs:
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute):
                    ci = types.class_of(
                        types.type_of(fi, node.func.value))
                    if ci is not None \
                            and node.func.attr in ci.properties:
                        yield self.finding(
                            ctx, node,
                            f"'{node.func.attr}' is a @property of "
                            f"{ci.name} — calling it invokes the "
                            f"*returned value* (TypeError at runtime); "
                            f"drop the parentheses")
                else:
                    for expr in self._bool_contexts(node):
                        yield from self._check_bare(ctx, types, fi, expr)

    @staticmethod
    def _bool_contexts(node: ast.AST) -> Iterator[ast.expr]:
        if isinstance(node, (ast.If, ast.While)):
            yield node.test
        elif isinstance(node, ast.IfExp):
            yield node.test
        elif isinstance(node, ast.Assert):
            yield node.test
        elif isinstance(node, ast.BoolOp):
            yield from node.values
        elif isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            yield node.operand
        elif isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            none = any(isinstance(s, ast.Constant) and s.value is None
                       for s in sides)
            if not none and all(isinstance(op, (ast.Eq, ast.NotEq, ast.Gt,
                                                ast.Lt, ast.GtE, ast.LtE))
                                for op in node.ops):
                yield from sides

    def _check_bare(self, ctx, types, fi, expr) -> Iterator[Finding]:
        if not isinstance(expr, ast.Attribute):
            return
        ci = types.class_of(types.type_of(fi, expr.value))
        if ci is None or expr.attr.startswith("_"):
            return
        m = ci.methods.get(expr.attr)
        if m is not None and not m.params:
            yield self.finding(
                ctx, expr,
                f"'{expr.attr}' is a zero-arg method of {ci.name} — the "
                f"bound method is always truthy, so this test never "
                f"varies; call it: {expr.attr}()")


# --------------------------------------------------------------------------
# metric-docs-drift

_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
_DRIFT_CACHE = "metric-docs-drift:findings"
_MUTATORS = {"update", "setdefault", "pop", "clear"}


def _site_label_keys(mi, call: ast.Call) -> Optional[FrozenSet[str]]:
    """Label keys a metric call site pins down statically: a frozenset
    for literal dicts (possibly via a single un-mutated ``labels = {...}``
    local), the empty frozenset for no-labels calls, None when dynamic
    (helper-built dicts, mutated locals, ** spreads)."""
    cands = list(call.args[1:2]) + [k.value for k in call.keywords
                                    if k.arg == "labels"]
    if not cands:
        return frozenset()

    def keys_of(d: ast.Dict) -> Optional[FrozenSet[str]]:
        out = []
        for k in d.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out.append(k.value)
            else:
                return None  # ** spread or computed key
        return frozenset(out)

    e = cands[0]
    if isinstance(e, ast.Dict):
        return keys_of(e)
    if isinstance(e, ast.Name):
        fn = mi.enclosing_function(call)
        if fn is None:
            return None
        assigns = [n for n in ast.walk(fn)
                   if isinstance(n, ast.Assign) and len(n.targets) == 1
                   and isinstance(n.targets[0], ast.Name)
                   and n.targets[0].id == e.id]
        if len(assigns) != 1 or not isinstance(assigns[0].value, ast.Dict):
            return None
        for n in ast.walk(fn):  # conditional labels["model"] = ... etc.
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                tgts = n.targets if isinstance(n, ast.Assign) else [n.target]
                for t in tgts:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == e.id:
                        return None
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.value.id == e.id \
                    and n.func.attr in _MUTATORS:
                return None
        return keys_of(assigns[0].value)
    return None


def _doc_text(program) -> Optional[str]:
    """Concatenated text of every ``obs/README.md`` reachable by walking
    up from the analyzed files. None when no such file exists on disk
    (single-fixture tests): the documentation check is skipped, label
    consistency still runs."""
    paths = set()
    for mi in program.modules.values():
        d = os.path.dirname(os.path.normpath(mi.path))
        while True:
            cand = os.path.join(d, "obs", "README.md")
            if os.path.isfile(cand):
                paths.add(cand)
            if os.path.basename(d) == "obs":
                cand = os.path.join(d, "README.md")
                if os.path.isfile(cand):
                    paths.add(cand)
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    if not paths:
        return None
    text = []
    for p in sorted(paths):
        with open(p, "r", encoding="utf-8") as fh:
            text.append(fh.read())
    return "\n".join(text)


def _drift_findings(program) -> List[Tuple[str, int, int, str]]:
    cached = program.cache.get(_DRIFT_CACHE)
    if cached is not None:
        return cached
    sites: Dict[str, List[Tuple[str, int, int,
                                Optional[FrozenSet[str]]]]] = {}
    for mi in sorted(program.modules.values(), key=lambda m: m.path):
        for node in ast.walk(mi.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_FACTORIES
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            fam = node.args[0].value
            sites.setdefault(fam, []).append(
                (mi.path, node.lineno, node.col_offset,
                 _site_label_keys(mi, node)))
    doc = _doc_text(program)
    out: List[Tuple[str, int, int, str]] = []
    for fam in sorted(sites):
        slist = sorted(sites[fam], key=lambda s: (s[0], s[1]))
        if doc is not None and fam not in doc:
            p, ln, col, _ = slist[0]
            out.append((p, ln, col,
                        f"metric family '{fam}' is not documented in "
                        f"obs/README.md — every scraped family needs a "
                        f"row there (name, labels, meaning) or dashboards "
                        f"and alerts drift from the code"))
        keysets = [s for s in slist if s[3] is not None]
        distinct = {s[3] for s in keysets}
        if len(distinct) > 1:
            counts: Dict[FrozenSet[str], int] = {}
            for s in keysets:
                counts[s[3]] = counts.get(s[3], 0) + 1
            majority = max(sorted(distinct, key=lambda k: sorted(k)),
                           key=lambda k: counts[k])
            anchor = next(s for s in keysets if s[3] == majority)
            for p, ln, col, keys in keysets:
                if keys == majority:
                    continue
                out.append((p, ln, col,
                            f"metric family '{fam}' emitted with label "
                            f"set {{{', '.join(sorted(keys))}}} here but "
                            f"{{{', '.join(sorted(majority))}}} at "
                            f"{anchor[0]}:{anchor[1]} — a silent labelset "
                            f"fork; one family must keep one label set"))
    program.cache[_DRIFT_CACHE] = out
    return out


@register
class MetricDocsDriftRule(Rule):
    """Metric families undocumented or with forked label sets.

    ``obs/README.md`` is the contract dashboards and alerts are built
    against; a family the code emits but the README never mentions is
    telemetry nobody can find, and the same family emitted with two
    different label sets (``{model}`` here, ``{model, replica}`` there)
    splits one logical series into disjoint groups that ``sum()`` and
    ``rate()`` silently mis-aggregate. Sites whose label dict is built
    dynamically (helper calls, mutated locals) are skipped for the
    consistency check — only provably-literal forks are reported.
    """

    name = "metric-docs-drift"
    description = ("metric family missing from obs/README.md, or same "
                   "family emitted with diverging label sets")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        norm = os.path.normpath(ctx.path)
        for path, line, col, msg in _drift_findings(ctx.program):
            if os.path.normpath(path) == norm:
                yield Finding(self.name, ctx.path, line, col, msg)
