"""Static compile-surface analysis: executable-cardinality bounds per
jit site.

The serving contract this tree documents dynamically
(``serve_compile_misses_total``, the ``_sigs`` sets) is made checkable
at lint time: enumerate every jit application in the program, find the
call sites that feed each one, run the abstract shape interpreter
(:mod:`.shapes`) over the calling functions, and classify every traced
argument dimension by provenance. The product of the bounded factors is
a *static executable-cardinality bound* for the site:

- ``literal`` / ``config`` dims contribute 1 (fixed for a server
  lifetime);
- ``bucket`` dims contribute ``|table|`` — numeric when the table is a
  source literal, symbolic (``|prompt_buckets|``) when the table is a
  boot-time knob;
- ``sym`` / ``top`` dims contribute ``?`` (statically unknown — *not*
  proven unbounded, but not proven bounded either);
- ``unbounded`` dims make the whole site ``unbounded`` — the
  recompile-storm shape.

Opaque arguments (weights pytrees, unannotated request objects) carry
no visible dims; they are listed per call site for human review but
excluded from the product — the budget file's ``why`` strings are where
their invariance argument lives.

``scripts/compile_budget.json`` commits the allowed bound per site; CI
diffs the computed report against it (:func:`check_budget`) and fails
on any regression: a new jit site without a budget entry, a new factor,
a numeric bound above budget, or a bounded site going ``?``/unbounded.
Tightening never fails.
"""

from __future__ import annotations

import ast
import json
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import shapes as S
from .callgraph import FuncInfo, ModuleInfo, Program, jit_call_kwargs

_SURFACE_CACHE = "compilesurface:model"


# ------------------------------------------------------------- model

class CallSite:
    """One resolved call into a jit site, with classified arguments."""

    def __init__(self, mi: ModuleInfo, call: ast.Call, caller: Optional[FuncInfo]):
        self.mi = mi
        self.call = call
        self.caller = caller
        self.args: List[dict] = []       # per-arg report rows
        self.factors: Dict[str, Optional[int]] = {}
        self.unbounded_traced: List[str] = []  # unbounded traced dims
        self.unbounded_static: List[str] = []  # unbounded static_argnums values
        self.unknown = False             # any ?-classified dim

    @property
    def unbounded(self) -> List[str]:
        return self.unbounded_traced + self.unbounded_static

    @property
    def path(self) -> str:
        return self.mi.path

    @property
    def line(self) -> int:
        return self.call.lineno


class JitSite:
    """One jit application: the wrapped function (when resolvable), its
    caller-visible binding names, and static/donated positions."""

    def __init__(self, mi: ModuleInfo, fi: Optional[FuncInfo],
                 expr: ast.AST, line: int):
        self.mi = mi
        self.fi = fi
        self.expr = expr
        self.line = line
        self.bindings: Set[str] = set()   # "name" / "Cls.attr"
        self.static_idx: Set[int] = set()
        self.static_names: Set[str] = set()
        self.donate_idx: Set[int] = set()
        self.callsites: List[CallSite] = []

    @property
    def site_id(self) -> str:
        name = self.fi.qual if self.fi is not None else \
            (sorted(self.bindings)[0] if self.bindings else f"L{self.line}")
        return f"{self.mi.module}:{name}"

    def param_name(self, i: int) -> str:
        if self.fi is not None and i < len(self.fi.params):
            return self.fi.params[i]
        return f"arg{i}"

    def is_static(self, i: int, name: str) -> bool:
        return i in self.static_idx or name in self.static_names


def _static_spec(expr: ast.AST, resolve) -> Tuple[Set[int], Set[str]]:
    """Literal static_argnums/static_argnames on a jit transform expr."""
    idx: Set[int] = set()
    names: Set[str] = set()
    if not isinstance(expr, ast.Call):
        return idx, names
    for k in expr.keywords:
        v = k.value
        if k.arg == "static_argnums":
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                idx.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                idx.update(e.value for e in v.elts
                           if isinstance(e, ast.Constant)
                           and isinstance(e.value, int))
        elif k.arg == "static_argnames":
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                names.update(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, str))
    return idx, names


def _enclosing_assign(mi: ModuleInfo, node: ast.AST) -> Optional[ast.Assign]:
    cur = mi.parents.get(node)
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = mi.parents.get(cur)
    return cur if isinstance(cur, ast.Assign) else None


def _binding_of_target(mi: ModuleInfo, t: ast.AST,
                       node: ast.AST) -> Optional[str]:
    if isinstance(t, ast.Name):
        return t.id
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
            and t.value.id == "self":
        cls = mi.enclosing_class(node)
        if cls:
            return f"{cls}.{t.attr}"
    return None


def _chase_local_aliases(mi: ModuleInfo, site: JitSite,
                         around: ast.AST) -> None:
    """Within the function enclosing a jit application, follow
    ``other = name`` / ``self.attr = name`` rebinds of the jitted
    callable (the ``forward = fwd; self._fwd = forward`` idiom)."""
    fn = mi.enclosing_function(around)
    if fn is None:
        return
    local = {b for b in site.bindings if "." not in b}
    if site.fi is not None:
        local.add(site.fi.name)
    for _ in range(2):
        grew = False
        for stmt in ast.walk(fn):
            if not (isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Name)
                    and stmt.value.id in local):
                continue
            for t in stmt.targets:
                b = _binding_of_target(mi, t, stmt)
                if b and b not in site.bindings:
                    site.bindings.add(b)
                    if "." not in b:
                        local.add(b)
                    grew = True
        if not grew:
            break


def _collect_sites(program: Program) -> List[JitSite]:
    sites: List[JitSite] = []
    seen: Set[Tuple[int, int]] = set()
    for mi in program.modules.values():
        resolve = mi.imports.resolve
        for fi, expr in mi.jit_applications:
            key = (id(mi), id(expr))
            if key in seen:
                continue
            seen.add(key)
            site = JitSite(mi, fi, expr, getattr(expr, "lineno", fi.node.lineno))
            site.static_idx, site.static_names = _static_spec(expr, resolve)
            site.donate_idx = set(fi.donated_idx)
            # decorator application: callers use the def's own names
            site.bindings.add(fi.qual)
            site.bindings.add(fi.name)
            # wrap application: the assignment target is the binding
            assign = _enclosing_assign(mi, expr)
            if assign is not None:
                for t in assign.targets:
                    b = _binding_of_target(mi, t, assign)
                    if b:
                        site.bindings.add(b)
            _chase_local_aliases(mi, site, fi.node)
            sites.append(site)
        # jit wraps whose operand is not a bare local Name (lambdas,
        # attribute chains) never reach jit_applications; surface them
        # as unresolved sites so the budget file still has to name them
        for node in ast.walk(mi.tree):
            if not (isinstance(node, ast.Call)
                    and jit_call_kwargs(node, resolve) is not None
                    and node.args):
                continue
            if isinstance(node.args[0], ast.Name) and \
                    mi.local_funcs.get(node.args[0].id) is not None:
                continue  # already a jit_application
            key = (id(mi), id(node))
            if key in seen:
                continue
            seen.add(key)
            site = JitSite(mi, None, node, node.lineno)
            site.static_idx, site.static_names = _static_spec(node, resolve)
            assign = _enclosing_assign(mi, node)
            if assign is not None:
                for t in assign.targets:
                    b = _binding_of_target(mi, t, assign)
                    if b:
                        site.bindings.add(b)
            _chase_local_aliases(mi, site, node)
            sites.append(site)
    return sites


# --------------------------------------------------- argument classify

def _leaf_dims(av: S.AV) -> Tuple[Optional[List[S.Dim]], str]:
    """(dims, kind) for one traced argument. kind: array|scalar|tuple|
    opaque. Tuples recurse (pytree leaves concatenated)."""
    if isinstance(av, S.ArrayVal):
        return list(av.shape), "array"
    if isinstance(av, S.ScalarVal):
        return [], "scalar"
    if isinstance(av, S.TupleVal):
        dims: List[S.Dim] = []
        for it in av.items:
            d, k = _leaf_dims(it)
            if d is None:
                return None, "opaque"
            dims.extend(d)
        return dims, "tuple"
    return None, "opaque"


def _value_dim(av: S.AV) -> S.Dim:
    """Value-cardinality provenance for a static_argnums position."""
    if isinstance(av, S.ScalarVal):
        return av.dim
    if isinstance(av, S.ParamVal):
        return S.config_dim(av.name) if av.config else S.sym_dim(av.name)
    return S.top_dim()


def _classify_callsite(program: Program, site: JitSite,
                       cs: CallSite) -> None:
    interp = S.Interp.get(program)
    if cs.caller is not None:
        fs = interp.function_shapes(cs.caller)
    else:
        fs = None

    def av_of(node: ast.AST) -> S.AV:
        return fs.at(node) if fs is not None else S.OPAQUE

    for i, a in enumerate(cs.call.args):
        if isinstance(a, ast.Starred):
            break
        pname = site.param_name(i)
        av = av_of(a)
        if site.is_static(i, pname):
            d = _value_dim(av)
            row = {"param": pname, "kind": "static",
                   "value": d.render()}
            cs.args.append(row)
            if d.kind == S.UNBOUNDED:
                cs.unbounded_static.append(f"{pname}={d.render()}")
            elif d.kind == S.BUCKET:
                cs.factors.setdefault(f"|{d.table}|", d.size)
            elif d.kind in (S.SYM, S.TOP):
                cs.unknown = True
            continue
        dims, kind = _leaf_dims(av)
        row = {"param": pname, "kind": kind}
        if dims is not None:
            row["shape"] = [d.render() for d in dims]
            if isinstance(av, S.ArrayVal):
                row["dtype"] = av.dtype
            for d in dims:
                if d.kind == S.UNBOUNDED:
                    cs.unbounded_traced.append(f"{pname}:{d.render()}")
                elif d.kind == S.BUCKET:
                    key = f"|{d.table}|"
                    prev = cs.factors.get(key)
                    cs.factors[key] = d.size if prev is None else prev
                elif d.kind in (S.SYM, S.TOP):
                    cs.unknown = True
        if isinstance(av, S.ScalarVal):
            row["weak"] = bool(av.weak)
            row["value"] = av.dim.render()
            row["dtype"] = av.dtype
        cs.args.append(row)
    for k in cs.call.keywords:
        if k.arg is None:
            continue
        av = av_of(k.value)
        dims, kind = _leaf_dims(av)
        row = {"param": k.arg, "kind": kind}
        if dims is not None:
            row["shape"] = [d.render() for d in dims]
            for d in dims:
                if d.kind == S.UNBOUNDED:
                    cs.unbounded_traced.append(f"{k.arg}:{d.render()}")
                elif d.kind == S.BUCKET:
                    cs.factors.setdefault(f"|{d.table}|", d.size)
                elif d.kind in (S.SYM, S.TOP):
                    cs.unknown = True
        cs.args.append(row)


def _find_callsites(program: Program, sites: List[JitSite]) -> None:
    # A binding name can carry several jit sites (`self._decode` is
    # rebound to the paged or dense executable depending on the boot
    # path) — a call through that name must count against every site
    # sharing it, so each site's bound covers the shapes it could see.
    by_name: Dict[Tuple[int, str], List[JitSite]] = {}
    by_fi: Dict[int, JitSite] = {}
    for site in sites:
        for b in site.bindings:
            by_name.setdefault((id(site.mi), b), []).append(site)
        if site.fi is not None:
            by_fi[id(site.fi)] = site
    for mi in program.modules.values():
        interp = S.Interp.get(program)
        node2fi = interp.node_to_fi(mi)
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            hits: List[JitSite] = []
            f = node.func
            if isinstance(f, ast.Name):
                hits = list(by_name.get((id(mi), f.id), ()))
            elif isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and f.value.id == "self":
                cls = mi.enclosing_class(node)
                if cls:
                    hits = list(by_name.get((id(mi), f"{cls}.{f.attr}"), ()))
            if not hits:
                callee = program.resolve_call(mi, f, mi.enclosing_class(node))
                if callee is not None and id(callee) in by_fi:
                    hits = [by_fi[id(callee)]]
            for site in hits:
                # the jit application itself is not a call *into* the site
                if node is site.expr or (isinstance(site.expr, ast.Call)
                                         and node in ast.walk(site.expr)):
                    continue
                enc = mi.enclosing_function(node)
                caller = node2fi.get(id(enc)) if enc is not None else None
                if site.fi is not None and enc is site.fi.node:
                    continue  # recursive self-reference, not a dispatch
                cs = CallSite(mi, node, caller)
                _classify_callsite(program, site, cs)
                site.callsites.append(cs)


def compute_surface(program: Program) -> List[JitSite]:
    """All jit sites with classified call sites (memoized per program)."""
    sites = program.cache.get(_SURFACE_CACHE)
    if sites is None:
        sites = _collect_sites(program)
        _find_callsites(program, sites)
        program.cache[_SURFACE_CACHE] = sites
    return sites


# ------------------------------------------------------------- bounds

def site_bound(site: JitSite) -> Tuple[str, Optional[int], Dict[str, Optional[int]]]:
    """(canonical bound string, numeric bound or None, factors).

    Bound string grammar: ``"1"``, ``"|a|*|b|"`` (sorted factors, with
    ``?`` appended when some dim is statically unknown), ``"unbounded"``,
    bare ``"?"``, or ``"no-callsites"``.
    """
    if not site.callsites:
        return "no-callsites", None, {}
    factors: Dict[str, Optional[int]] = {}
    unknown = False
    for cs in site.callsites:
        if cs.unbounded:
            return "unbounded", None, {}
        unknown = unknown or cs.unknown
        for k, v in cs.factors.items():
            prev = factors.get(k)
            factors[k] = v if prev is None else prev
    parts = sorted(factors)
    if unknown:
        parts.append("?")
    if not parts:
        return "1", 1, factors
    numeric: Optional[int] = 1
    for k in sorted(factors):
        v = factors[k]
        numeric = None if (v is None or numeric is None) else numeric * v
    if unknown:
        numeric = None
    return "*".join(parts), numeric, factors


def render_report(program: Program, sites: Sequence[JitSite]) -> dict:
    out_sites = []
    for site in sorted(sites, key=lambda s: s.site_id):
        bound, numeric, factors = site_bound(site)
        row = {
            "site": site.site_id,
            "path": site.mi.path,
            "line": site.line,
            "bindings": sorted(site.bindings),
            "bound": bound,
            "numeric": numeric,
        }
        if site.static_idx or site.static_names:
            row["static"] = sorted(
                [str(i) for i in site.static_idx]
                + sorted(site.static_names))
        if site.donate_idx:
            row["donate_argnums"] = sorted(site.donate_idx)
        row["callsites"] = [
            {"path": cs.path, "line": cs.line,
             "caller": (cs.caller.qual if cs.caller is not None else None),
             "args": cs.args,
             **({"unbounded": cs.unbounded} if cs.unbounded else {})}
            for cs in site.callsites]
        out_sites.append(row)
    return {"version": 1, "tool": "jaxlint-compile-surface",
            "sites": out_sites}


# ------------------------------------------------------------- budget

def _parse_bound(s: str) -> Tuple[bool, bool, Set[str], Optional[int]]:
    """bound string -> (unbounded, unknown, symbolic factors, numeric)."""
    s = (s or "").strip()
    if s == "unbounded":
        return True, False, set(), None
    if s in ("?", "no-callsites"):
        return False, True, set(), None
    factors: Set[str] = set()
    unknown = False
    numeric: Optional[int] = 1
    for part in s.split("*"):
        part = part.strip()
        if not part:
            continue
        if part == "?":
            unknown = True
            numeric = None
        elif part.isdigit():
            numeric = None if numeric is None else numeric * int(part)
        else:
            factors.add(part)
            numeric = None
    return False, unknown, factors, numeric


def check_budget(report: dict, budget: dict) -> List[str]:
    """Violations of the committed budget; empty means the gate passes.

    A site regresses when it goes unbounded, introduces a ``?`` or a
    symbolic factor the budget does not allow, or exceeds a numeric
    budget (``max``). Tightening is always allowed. New sites must be
    added to the budget (with a ``why``) before CI passes.

    A budget entry naming a site the tree no longer has is ALSO a
    failure: a stale entry silently stops guarding anything (a renamed
    site re-enters as "new site" only until someone pastes the old bound
    under the new name, and the prebuild manifest would enumerate
    executables nobody can ever serve). Tightening by *deleting* the
    entry is the allowed fix.
    """
    allowed: Dict[str, dict] = budget.get("sites", {})
    out: List[str] = []
    seen: Set[str] = set()
    for row in report.get("sites", []):
        site = row["site"]
        seen.add(site)
        entry = allowed.get(site)
        if entry is None:
            out.append(f"{site}: new jit site with no budget entry "
                       f"(bound {row['bound']}) — add it to the budget "
                       "with a why:")
            continue
        b_unb, b_unk, b_factors, b_num = _parse_bound(
            entry.get("bound", ""))
        c_unb, c_unk, c_factors, c_num = _parse_bound(row["bound"])
        if c_unb and not b_unb:
            out.append(f"{site}: computed bound is unbounded, budget "
                       f"allows {entry.get('bound')!r}")
            continue
        if b_unb:
            continue
        if c_unk and not (b_unk or b_unb):
            out.append(f"{site}: computed bound {row['bound']!r} has "
                       f"statically-unknown factors, budget allows "
                       f"{entry.get('bound')!r}")
            continue
        extra = c_factors - b_factors
        if extra and not b_unk:
            out.append(f"{site}: computed bound {row['bound']!r} "
                       f"introduces factor(s) {sorted(extra)} beyond "
                       f"budget {entry.get('bound')!r}")
            continue
        max_n = entry.get("max")
        if max_n is not None and row.get("numeric") is not None \
                and row["numeric"] > max_n:
            out.append(f"{site}: numeric bound {row['numeric']} exceeds "
                       f"budget max {max_n}")
    for site in sorted(set(allowed) - seen):
        out.append(f"{site}: stale budget entry — no such jit site in the "
                   f"analyzed tree (bound {allowed[site].get('bound')!r}); "
                   "delete the entry (tightening) or fix the site name")
    return out


def run(paths: Sequence[str], exclude: Sequence[str] = ()) -> Tuple[dict, Program]:
    """Analyze ``paths`` and return (report dict, program)."""
    from .engine import read_sources

    sources = read_sources(paths, exclude)
    program = Program(sources)
    sites = compute_surface(program)
    return render_report(program, sites), program


def load_budget(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or "sites" not in data:
        raise ValueError("budget file must be {'sites': {...}}")
    return data
