"""Import-alias resolution and jit-context detection for jaxlint.

Jit context — "code that runs under a trace" — is where host-device sync
and Python side effects actually hurt, so the host-sync / side-effect rules
only fire there. A function is considered jit-context when it is:

1. decorated with ``@jax.jit`` / ``@pjit`` / ``@partial(jax.jit, ...)``,
2. wrapped somewhere in the same module: ``jax.jit(fn)`` or
   ``functools.partial(jax.jit, ...)(fn)``,
3. lexically nested inside a jit-context function (closures traced with it),
4. reachable from a jit-context function through same-module calls by bare
   name (one-module approximation of the call graph), or
5. defined in a *kernel module* — any file under an ``ops/`` directory: op
   kernels exist to be called from jitted steps, so the whole module is
   treated as traced code.

This is deliberately an approximation: cross-module reachability is not
modelled. It is tuned so that everything it flags in this repo is a real
hazard, and false negatives are accepted over false-positive noise.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

# module roots whose canonical names we track through aliases
_CANON_MODULES = {
    "numpy": "numpy",
    "jax": "jax",
    "jax.numpy": "jax.numpy",
    "jax.random": "jax.random",
    "random": "random",
    "datetime": "datetime",
    "time": "time",
    "functools": "functools",
    "jax.experimental.pjit": "jax.experimental.pjit",
}

JIT_WRAPPERS = {"jax.jit", "jax.pjit", "pjit", "jax.experimental.pjit.pjit"}


class ImportMap:
    """Maps local names to canonical dotted paths via the file's imports."""

    def __init__(self, tree: ast.Module):
        self.aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in _CANON_MODULES or a.name.split(".")[0] in _CANON_MODULES:
                        self.aliases[(a.asname or a.name.split(".")[0])] = (
                            a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    full = f"{node.module}.{a.name}"
                    root = node.module.split(".")[0]
                    if root in _CANON_MODULES:
                        self.aliases[a.asname or a.name] = full

    def resolve(self, node: ast.AST) -> Optional[str]:
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))


def _is_jit_expr(node: ast.AST, resolve) -> bool:
    """True for expressions evaluating to a jit transform: ``jax.jit``,
    ``partial(jax.jit, ...)`` — used both in decorator position and as the
    callee of a wrap call."""
    q = resolve(node)
    if q in JIT_WRAPPERS:
        return True
    if isinstance(node, ast.Call):
        fq = resolve(node.func)
        if fq in JIT_WRAPPERS:
            return True
        if fq == "functools.partial" and node.args and resolve(node.args[0]) in JIT_WRAPPERS:
            return True
    return False


def jit_call_kwargs(node: ast.AST, resolve) -> Optional[List[str]]:
    """If ``node`` is a jit transform *call* (``jax.jit(...)``,
    ``partial(jax.jit, ...)``), the keyword names passed to it; else None."""
    if not isinstance(node, ast.Call):
        return None
    fq = resolve(node.func)
    if fq in JIT_WRAPPERS:
        return [k.arg for k in node.keywords if k.arg]
    if fq == "functools.partial" and node.args and resolve(node.args[0]) in JIT_WRAPPERS:
        return [k.arg for k in node.keywords if k.arg]
    return None


class JitContext:
    """Per-file jit-context map. ``in_jit(node)`` answers whether a node sits
    inside traced code; ``jit_applications`` lists every (function def,
    jit expr) pair for rules that inspect jit options (donation)."""

    def __init__(self, tree: ast.Module, path: str, imports: ImportMap):
        self.kernel_module = "ops" in os.path.normpath(path).split(os.sep)
        resolve = imports.resolve

        funcs: Dict[str, ast.AST] = {}          # bare name -> def node
        parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.setdefault(node.name, node)
        self._funcs = funcs
        self._parents = parents

        # (def node, jit expr node or None): every way a function gets jitted
        self.jit_applications: List[Tuple[ast.AST, Optional[ast.AST]]] = []

        roots: Set[ast.AST] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_jit_expr(dec, resolve):
                        roots.add(node)
                        self.jit_applications.append((node, dec))
            elif isinstance(node, ast.Call) and _is_jit_expr(node.func, resolve):
                # jax.jit(fn, ...) / partial(jax.jit, ...)(fn)
                if node.args and isinstance(node.args[0], ast.Name):
                    fn = funcs.get(node.args[0].id)
                    if fn is not None:
                        roots.add(fn)
                        self.jit_applications.append((fn, node.func if
                                                      isinstance(node.func, ast.Call) else node))

        # same-module call-graph closure by bare name
        work = list(roots)
        reached: Set[ast.AST] = set(roots)
        while work:
            fn = work.pop()
            for node in ast.walk(fn):
                callee = None
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    callee = funcs.get(node.func.id)
                if callee is not None and callee not in reached:
                    reached.add(callee)
                    work.append(callee)
        if self.kernel_module:
            reached.update(funcs.values())
        self._jit_funcs = reached

        # line intervals of traced code (nested defs are inside by construction)
        self._intervals = sorted(
            (f.lineno, getattr(f, "end_lineno", f.lineno)) for f in reached)

    def in_jit(self, node: ast.AST) -> bool:
        line = getattr(node, "lineno", None)
        if line is None:
            return False
        return any(lo <= line <= hi for lo, hi in self._intervals)

    def enclosing_function(self, node: ast.AST):
        cur = self._parents.get(node)
        while cur is not None and not isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cur = self._parents.get(cur)
        return cur
