"""Jit-context detection for jaxlint — whole-program since v2.

Jit context — "code that runs under a trace" — is where host-device sync
and Python side effects actually hurt, so the host-sync / side-effect rules
only fire there. A function is jit context when it is:

1. decorated with ``@jax.jit`` / ``@pjit`` / ``@partial(jax.jit, ...)`` or a
   trace-only wrapper (``jax.shard_map``, ``jax.pmap``),
2. wrapped anywhere in its module: ``jax.jit(fn)``, ``jax.shard_map(fn)``,
   ``functools.partial(jax.jit, ...)(fn)``,
3. lexically nested inside a jit-context function (closures traced with it),
4. reachable from a jit-context function through **resolvable call edges
   across all analyzed modules** — bare names, ``self.method()``, and
   aliased imports, including relative imports and ``__init__`` re-exports
   (the whole-program call graph in :mod:`.callgraph`), or
5. defined in a *kernel module* — any file under an ``ops/`` directory: op
   kernels exist to be called from jitted steps, so the whole module is
   treated as traced code.

v1 stopped at module boundaries (same-module bare-name reachability only).
The remaining approximations: calls through instance attributes other than
``self`` (``model.score(...)``) and values returned from factories are not
resolved — false negatives are still preferred over false-positive noise.

The import-alias machinery and jit-expression helpers live in
:mod:`.callgraph`; they are re-exported here for compatibility.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from .callgraph import (JIT_WRAPPERS, TRACE_ONLY_WRAPPERS,  # noqa: F401
                        ImportMap, ModuleInfo, Program, is_jit_expr,
                        is_trace_expr, jit_call_kwargs)

# compat alias for pre-v2 imports
_is_jit_expr = is_jit_expr


class JitContext:
    """Per-file view of the whole-program jit closure. ``in_jit(node)``
    answers whether a node sits inside traced code; ``jit_applications``
    lists every (function def node, jit expr) pair for rules that inspect
    jit options (donation)."""

    def __init__(self, program: Program, mi: ModuleInfo):
        self.program = program
        self.module = mi
        self.kernel_module = mi.kernel
        self.jit_applications: List[Tuple[ast.AST, Optional[ast.AST]]] = [
            (fi.node, expr) for fi, expr in mi.jit_applications]
        self._jit_funcs: Set[ast.AST] = program.jit_func_nodes(mi)
        # line intervals of traced code (nested defs are inside by construction)
        self._intervals = sorted(
            (f.lineno, getattr(f, "end_lineno", f.lineno))
            for f in self._jit_funcs)

    def in_jit(self, node: ast.AST) -> bool:
        line = getattr(node, "lineno", None)
        if line is None:
            return False
        return any(lo <= line <= hi for lo, hi in self._intervals)

    def enclosing_function(self, node: ast.AST):
        return self.module.enclosing_function(node)
