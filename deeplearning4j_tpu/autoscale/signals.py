"""Autoscale input signals — burn, queue depth, KV pressure on one clock.

The autoscaler decides from exactly three observables the stack already
exports, sampled together so they can never disagree about *when* they
were true:

- **burn rate** per (model, slo_class) from :class:`~..obs.slo.SloBurn`
  (the router's model-keyed tracker — the number an SLO dashboard alerts
  on, and therefore the number scaling must answer to);
- **queue depth** from each replica's membership self-report (the beat
  payload's ``queue_depth`` — work admitted but not yet served);
- **KV-block pressure** from the same self-report (``kv_utilization`` —
  the memory half of saturation; a fleet can be latency-fine and one
  burst away from ``queue_full`` sheds).

Every timestamp comes from the injected ``clock``. This module NEVER
reads wall time — the same discipline as membership leases and the burn
wheel — so a fake clock makes the whole control loop bit-reproducible:
same signal history + same clock ⇒ the same :class:`Sample` window ⇒
the same policy decision, in tests, in sim replays, across processes.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Deque, Dict, List, NamedTuple

from ..cluster.membership import DEAD


class Sample(NamedTuple):
    """One observation of fleet load, taken at ``t`` on the injected clock.

    ``burn`` folds the per-model detail to the worst burn per SLO class —
    the class's budget is spent by its worst model, and scaling adds
    capacity fleet-wide. ``burn_detail`` keeps the per-(model, class)
    numbers as decision evidence.
    """

    t: float
    burn: Dict[str, float]          # slo_class -> worst burn across models
    burn_detail: Dict[str, float]   # "model/slo_class" -> burn (evidence)
    queue_depth: int                # summed replica self-reported depth
    kv_pressure: float              # worst replica KV-block utilization
    alive: int                      # non-dead replicas in membership


class _SampleWindow:
    """The rolling window + sustain predicate every signal reader shares.

    Subclasses produce :class:`Sample`\\ s however they like (fleet scrape,
    training step times) and push them through :meth:`_push`; the policy
    only ever consumes :meth:`window` / :meth:`sustained`, so one reader
    is substitutable for another by construction."""

    def __init__(self, window_s: float):
        if window_s <= 0:
            raise ValueError("need window_s > 0")
        self.window_s = float(window_s)
        self._samples: Deque[Sample] = deque()

    def _push(self, s: Sample) -> Sample:
        """Append one sample and age out everything past the window."""
        self._samples.append(s)
        horizon = s.t - self.window_s
        while self._samples and self._samples[0].t < horizon:
            self._samples.popleft()
        return s

    def window(self) -> List[Sample]:
        """The retained samples, oldest first."""
        return list(self._samples)

    def sustained(self, pred: Callable[[Sample], bool], for_s: float,
                  now: float) -> bool:
        """True iff the window reaches back at least ``for_s`` seconds AND
        every sample inside the trailing ``for_s`` satisfies ``pred`` —
        one spiky sample can never trigger, and neither can a window too
        young to know what "sustained" means yet."""
        if not self._samples:
            return False
        horizon = now - float(for_s)
        if self._samples[0].t > horizon:
            return False  # not enough history to call anything sustained
        inside = [s for s in self._samples if s.t >= horizon]
        return bool(inside) and all(pred(s) for s in inside)


class SignalReader(_SampleWindow):
    """Samples the autoscaler's inputs into a rolling window.

    ``slo`` is any object with the :class:`~..obs.slo.SloBurn` snapshot
    surface, ``membership`` anything with the
    :class:`~..cluster.membership.Membership` read surface. ``window_s``
    bounds how much history is retained — it only needs to cover the
    policy's longest sustain window.
    """

    def __init__(self, *, slo, membership, clock: Callable[[], float],
                 burn_window: str = "1m", window_s: float = 120.0):
        super().__init__(window_s)
        self._slo = slo
        self._membership = membership
        self._clock = clock
        self.burn_window = str(burn_window)

    def sample(self) -> Sample:
        """Take one observation, append it, and age out old ones."""
        now = float(self._clock())
        burn: Dict[str, float] = {}
        burn_detail: Dict[str, float] = {}
        for model, classes in sorted(self._slo.snapshot().items()):
            for cls, stats in sorted(classes.items()):
                b = float((stats.get("burn") or {}).get(self.burn_window,
                                                        0.0))
                burn_detail[f"{model}/{cls}"] = b
                if b > burn.get(cls, -1.0):
                    burn[cls] = b
        queue_depth = 0
        kv = 0.0
        alive = 0
        for rid in self._membership.ids():
            if self._membership.state(rid) == DEAD:
                continue
            p = self._membership.payload(rid)
            queue_depth += int(p.get("queue_depth") or 0)
            kv = max(kv, float(p.get("kv_utilization") or 0.0))
            alive += 1
        return self._push(Sample(now, burn, burn_detail, queue_depth, kv,
                                 alive))


class StepTimeSignalReader(_SampleWindow):
    """Step-time regression as SLO burn — the training-side signal source.

    The elastic trainer has no request SLO; its contract is a **step-time
    budget**. Each observed step maps to burn ``step_time / budget_s``
    under the single ``"train"`` class, so the stock
    :class:`~.policy.AutoscalePolicy` applies UNCHANGED: burn >= 1.0
    (steps slower than budget) sustained over the out-window scales the
    mesh out; burn deep inside the hysteresis band (steps comfortably
    under budget) sustained over the in-window scales it in. Timestamps
    come from the injected clock — the elastic trainer passes its logical
    step clock, so sustain/cooldown windows are measured in *steps* and
    the whole loop stays deterministic under test.
    """

    def __init__(self, *, budget_s: float, clock: Callable[[], float],
                 window_s: float = 120.0):
        super().__init__(window_s)
        if budget_s <= 0:
            raise ValueError("need budget_s > 0")
        self.budget_s = float(budget_s)
        self._clock = clock

    def observe(self, step_time_s: float, *, alive: int = 1) -> Sample:
        """Record one training step's duration as a burn sample."""
        now = float(self._clock())
        burn = float(step_time_s) / self.budget_s
        return self._push(Sample(now, {"train": burn},
                                 {"train/train": burn}, 0, 0.0,
                                 int(alive)))
