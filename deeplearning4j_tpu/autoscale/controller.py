"""Autoscale actuation — spawn, warm, drain, retire through ``cluster/``.

:class:`AutoscaleController` closes the loop the rest of the stack left
open: ``obs/`` measures burn, ``cluster/`` routes around death, ``aot/``
makes a cold boot warm — this module is the thing that *changes the
fleet size* in response. One :meth:`tick` is one control turn:

1. drive a membership round (``poll_once``) so the signals are fresh;
2. reap managed replicas the failure detector declared dead — their
   membership record and state-gauge series are removed (no ghost
   scrapes) and the policy sees the smaller fleet, so a floor breach
   repairs itself on the very same tick;
3. sample the :class:`~.signals.SignalReader`, ask the
   :class:`~.policy.AutoscalePolicy` for a verdict;
4. actuate: **scale-out** provisions through the injected factory
   (behind the ``autoscale.spawn`` chaos seam — a fired fault is a
   failed provision the controller survives and retries), AOT-prewarms
   every registered model from the shared store (zero compiles),
   registers with the router, and waits for the first membership beat so
   placement re-plans over the newcomer before the tick ends.
   **Scale-in** picks the emptiest replica, removes it from membership
   FIRST (no new traffic), drains each resident model over the
   replica's own ``/v1/admin/drain`` (the pager's lease discipline: an
   in-flight batch finishes against its params), then stops the server;
5. commit the policy cooldown **only if actuation succeeded**, update
   the gauges, stamp a flight-recorder event, and append one canonical
   JSON line to the decision log — the byte-identity surface replayed
   by the determinism test.

Every decision is observable three ways: gauges
(``autoscale_replicas_desired`` / ``_actual``), counters
(``autoscale_decisions_total{direction,reason}``), and timings
(``autoscale_scale_seconds{direction}`` — scale-out includes the warm
page-in and the wait for the first beat, which is the number that tells
you whether elastic capacity arrives inside an SLO window or after it).
"""

from __future__ import annotations

import http.client
import json
import logging
import threading
import time
from typing import Callable, Dict, List, Optional
from urllib.parse import urlsplit

from ..chaos import faults as _faults
from ..cluster.membership import ALIVE, DEAD
from ..obs import flight as _flight
from .policy import IN, OUT, AutoscalePolicy, ScaleDecision
from .signals import SignalReader

log = logging.getLogger(__name__)

_DECISIONS_HELP = "autoscale policy verdicts by direction and reason"
_SCALE_S_HELP = ("seconds to actuate one scale step (out: spawn + warm "
                 "page-in + first membership beat; in: drain + stop)")


class AutoscaleController:
    """Elastic fleet control over one :class:`~..cluster.router.ClusterRouter`.

    ``factory(replica_id)`` provisions one replica and returns a
    :class:`~..cluster.replica.ReplicaHandle`-shaped handle (``base_url``,
    ``fleet``, ``alive()``, ``stop()``, ``kill()``); the smoke's factory
    builds a FleetServer sharing the AOT store, a production one would
    call a scheduler. ``clock`` feeds the signal window and the decision
    log (inject a fake for bit-reproducible runs); actuation *durations*
    are measured on ``time.perf_counter`` because they describe real
    work, not simulated time, and never feed back into decisions.
    """

    def __init__(self, router, factory: Callable[[str], object], *,
                 policy: Optional[AutoscalePolicy] = None,
                 signals: Optional[SignalReader] = None,
                 clock: Optional[Callable[[], float]] = None,
                 id_prefix: str = "as-", beat_wait_s: float = 5.0,
                 sleep: Callable[[float], None] = time.sleep,
                 forecaster=None):
        self.router = router
        self.factory = factory
        self.metrics = router.metrics
        self._clock = clock if clock is not None else time.monotonic
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.signals = signals if signals is not None else SignalReader(
            slo=router.slo, membership=router.membership, clock=self._clock)
        #: Optional :class:`~..obs.forecast.BurnForecaster`-shaped hook
        #: (``forecast_burn(slo_class) -> Forecast | None``); when set,
        #: every tick hands the policy a per-class burn forecast so it
        #: can pre-spawn ahead of a predicted ramp.
        self.forecaster = forecaster
        self.id_prefix = str(id_prefix)
        self.beat_wait_s = float(beat_wait_s)
        self._sleep = sleep
        self._lock = threading.Lock()       # managed set + tick serialization
        self._managed: Dict[str, object] = {}
        self._spawned = 0                   # monotonic id counter
        self._ticks = 0
        self._last: Optional[ScaleDecision] = None
        self.decision_log: List[str] = []
        self._min_seen: Optional[int] = None
        self._max_seen: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if hasattr(router, "autoscaler"):
            router.autoscaler = self        # surfaces on /v1/cluster

    # ------------------------------------------------------------- fleet view
    def adopt(self, replica_id: str, handle) -> None:
        """Take ownership of an already-running replica (the seed fleet a
        drill boots before handing control to the autoscaler)."""
        with self._lock:
            self._managed[replica_id] = handle
            self._note_size_locked()

    def _actual_locked(self) -> int:
        """Managed replicas the failure detector still counts."""
        n = 0
        for rid in self._managed:
            try:
                if self.router.membership.state(rid) != DEAD:
                    n += 1
            except KeyError:
                pass
        return n

    def _note_size_locked(self) -> None:
        n = self._actual_locked()
        if self._min_seen is None or n < self._min_seen:
            self._min_seen = n
        if self._max_seen is None or n > self._max_seen:
            self._max_seen = n

    def replica_stats(self) -> Dict[str, int]:
        """``{min, max, final}`` managed-fleet sizes over this controller's
        lifetime — the block the sim scorer stamps into its report."""
        with self._lock:
            final = self._actual_locked()
            return {"min": final if self._min_seen is None else self._min_seen,
                    "max": final if self._max_seen is None else self._max_seen,
                    "final": final}

    def snapshot(self) -> dict:
        """Autoscaler state for ``/v1/cluster``."""
        with self._lock:
            return {
                "managed": sorted(self._managed),
                "actual": self._actual_locked(),
                "ticks": self._ticks,
                "decisions": len(self.decision_log),
                "policy": self.policy.snapshot(),
                "last_decision": (json.loads(self._last.to_json())
                                  if self._last is not None else None),
            }

    def decision_log_bytes(self) -> bytes:
        """The full decision log, one canonical JSON line per tick — two
        processes fed the same trace, seed, and fake clock must produce
        byte-identical output here."""
        with self._lock:
            return ("\n".join(self.decision_log) + "\n").encode("utf-8") \
                if self.decision_log else b""

    # ------------------------------------------------------------------- tick
    def tick(self, poll: bool = True) -> ScaleDecision:
        """One control turn: poll, reap, sample, decide, actuate, record.

        Scale-in actuation is split around the lock: victims are picked,
        unmanaged, and unrouted under ``_lock`` (the decision stays
        atomic), but the per-model ``/v1/admin/drain`` round-trips and
        the handle stop run *outside* it — a drain can take its full
        30 s timeout, and holding the lock that long freezes every
        ``snapshot()``/``decision_log_bytes()`` reader (the /v1/cluster
        surface). Ticks themselves stay serial: the production loop is
        one thread, and the drills call ``tick()`` sequentially."""
        with self._lock:
            if poll:
                self.router.poll_once()
            retired = self._reap_dead_locked()
            s = self.signals.sample()
            now = s.t
            current = self._actual_locked()
            forecast = None
            if self.forecaster is not None:
                # pure in-memory store reads — safe under the tick lock
                forecast = {
                    cls: self.forecaster.forecast_burn(cls)
                    for cls in sorted(self.policy.burn_out)}
            decision = self.policy.decide(self.signals, current, now,
                                          forecast=forecast)
            self.metrics.counter(
                "autoscale_decisions_total",
                {"direction": decision.direction, "reason": decision.reason},
                help=_DECISIONS_HELP).inc()
            actuated = 0
            plan: List[dict] = []
            if decision.direction == OUT and decision.amount > 0:
                actuated = self._scale_out_locked(decision.amount)
            elif decision.direction == IN and decision.amount > 0:
                plan = self._plan_scale_in_locked(decision.amount)
        if plan:
            actuated = self._execute_scale_in(plan)
        with self._lock:
            if actuated:
                # cooldowns arm only on success: a failed spawn leaves the
                # policy free to retry on the very next tick
                self.policy.commit(decision, now)
            actual = self._actual_locked()
            desired = current + (actuated if decision.direction == OUT
                                 else -actuated)
            self.metrics.gauge(
                "autoscale_replicas_desired",
                help="fleet size the last committed decision asked for"
            ).set(desired)
            self.metrics.gauge(
                "autoscale_replicas_actual",
                help="managed replicas the failure detector counts"
            ).set(actual)
            self._note_size_locked()
            if _flight.ACTIVE is not None:
                _flight.ACTIVE.record_event(
                    "autoscale", decision.direction, detail=decision.reason,
                    amount=decision.amount, current=current, actual=actual)
            self.decision_log.append(json.dumps(
                {"tick": self._ticks, "current": current, "actual": actual,
                 "actuated": actuated, "retired": retired,
                 "decision": json.loads(decision.to_json())},
                sort_keys=True, separators=(",", ":")))
            self._ticks += 1
            self._last = decision
            return decision

    def _reap_dead_locked(self) -> List[str]:
        """Retire managed replicas the failure detector declared dead:
        membership record + state-gauge series go away (scrapes must not
        show ghosts), the handle's threads are reclaimed, and the policy
        sees the smaller fleet on this same tick (``below_min`` repair
        bypasses cooldown)."""
        gone: List[str] = []
        for rid in sorted(self._managed):
            try:
                state = self.router.membership.state(rid)
            except KeyError:
                state = DEAD  # not in membership at all: nothing routes to it
            if state != DEAD:
                continue
            handle = self._managed.pop(rid)
            try:
                self.router.remove_replica(rid)
            except KeyError:
                pass
            try:
                handle.kill()  # already dead; this only reclaims threads
            except Exception:  # reaping must not die of a messy corpse  # jaxlint: disable=broad-except
                log.exception("post-mortem cleanup of %s", rid)
            self.metrics.counter(
                "autoscale_retired_total", {"cause": "dead"},
                help="managed replicas retired, by cause").inc()
            if _flight.ACTIVE is not None:
                _flight.ACTIVE.record_event("autoscale", "reaped",
                                            replica=rid)
            log.warning("reaped dead managed replica %s", rid)
            gone.append(rid)
        return gone

    # -------------------------------------------------------------- scale-out
    def _scale_out_locked(self, amount: int) -> int:
        done = 0
        for _ in range(int(amount)):
            rid = f"{self.id_prefix}{self._spawned}"
            t0 = time.perf_counter()
            try:
                if _faults.ACTIVE is not None:
                    _faults.ACTIVE.hit("autoscale.spawn", scope=rid)
                handle = self.factory(rid)
            except Exception:  # a failed provision is a retriable event  # jaxlint: disable=broad-except
                log.exception("spawn of %s failed", rid)
                self.metrics.counter(
                    "autoscale_spawn_failures_total",
                    help="scale-out provisions that failed (retried on a "
                         "later tick)").inc()
                break
            self._spawned += 1
            self._managed[rid] = handle
            self._prewarm(handle)
            self.router.add_replica(rid, handle.base_url)
            if not self._await_first_beat(rid):
                log.warning("replica %s spawned but no beat within %.1fs; "
                            "membership will track it from here", rid,
                            self.beat_wait_s)
            self.metrics.histogram(
                "autoscale_scale_seconds", {"direction": "out"},
                help=_SCALE_S_HELP).observe(time.perf_counter() - t0)
            if _flight.ACTIVE is not None:
                _flight.ACTIVE.record_event("autoscale", "spawned",
                                            replica=rid)
            done += 1
        return done

    @staticmethod
    def _prewarm(handle) -> None:
        """AOT-warm page-in of every registered model: the shared store
        already holds the executables, so ``ensure`` costs a weight
        transfer, not a compile. Best-effort — a model that fails to warm
        pages in lazily on first traffic instead."""
        fleet = getattr(handle, "fleet", None)
        if fleet is None:
            return
        for name in fleet.names():
            try:
                fleet.ensure(name)
            except Exception:  # lazy page-in remains the fallback  # jaxlint: disable=broad-except
                log.exception("prewarm of %s failed", name)

    def _await_first_beat(self, rid: str) -> bool:
        """Poll until the newcomer's first self-report lands ALIVE in
        membership (which also re-plans placement over it)."""
        attempts = max(1, int(self.beat_wait_s / 0.05))
        for attempt in range(attempts):
            try:
                self.router.poll_once()
                if (self.router.membership.state(rid) == ALIVE
                        and self.router.membership.payload(rid)):
                    return True
            except KeyError:
                pass
            if attempt + 1 < attempts:
                self._sleep(0.05)
        return False

    # --------------------------------------------------------------- scale-in
    def _plan_scale_in_locked(self, amount: int) -> List[dict]:
        """Pick victims and atomically unmanage + unroute them. Returns
        the drain work list :meth:`_execute_scale_in` runs lock-free."""
        plan: List[dict] = []
        for rid in self._pick_victims_locked(int(amount)):
            handle = self._managed.pop(rid)
            try:
                base_url = self.router.membership.base_url(rid)
                models = sorted(
                    self.router.membership.payload(rid).get("models") or {})
            except KeyError:
                base_url, models = None, []
            # order matters: stop routing FIRST, then drain — anything
            # admitted before removal finishes against leased params
            try:
                self.router.remove_replica(rid)
            except KeyError:
                pass
            plan.append({"rid": rid, "handle": handle,
                         "base_url": base_url, "models": models})
        return plan

    def _execute_scale_in(self, plan: List[dict]) -> int:
        """Drain and stop already-unrouted victims. Runs WITHOUT the
        controller lock: nothing here touches controller state, and the
        HTTP drains can legitimately take their full timeout."""
        done = 0
        for item in plan:
            rid, handle = item["rid"], item["handle"]
            t0 = time.perf_counter()
            for name in item["models"]:
                if item["base_url"] is None:
                    break
                try:
                    self._drain_model(item["base_url"], name)
                except OSError:
                    self._drain_counter("error").inc()
                    log.warning("drain of %s on %s failed; stop() drains "
                                "what remains", name, rid)
            try:
                handle.stop()  # graceful: lease-drains leftovers, closes
            except Exception:  # retirement must not wedge the tick  # jaxlint: disable=broad-except
                log.exception("stop of %s failed", rid)
            self.metrics.histogram(
                "autoscale_scale_seconds", {"direction": "in"},
                help=_SCALE_S_HELP).observe(time.perf_counter() - t0)
            self.metrics.counter(
                "autoscale_retired_total", {"cause": "scale_in"},
                help="managed replicas retired, by cause").inc()
            if _flight.ACTIVE is not None:
                _flight.ACTIVE.record_event("autoscale", "retired",
                                            replica=rid)
            log.info("scaled in replica %s", rid)
            done += 1
        return done

    def _pick_victims_locked(self, amount: int) -> List[str]:
        """The emptiest managed replicas first (self-reported queue depth,
        replica id as the deterministic tiebreak)."""
        loads = []
        for rid in self._managed:
            try:
                if self.router.membership.state(rid) == DEAD:
                    continue
                depth = int(self.router.membership.payload(rid)
                            .get("queue_depth") or 0)
            except KeyError:
                continue
            loads.append((depth, rid))
        loads.sort()
        return [rid for _, rid in loads[:amount]]

    def _drain_model(self, base_url: str, name: str) -> None:
        """Ask the replica itself to drain one model — the same
        ``/v1/admin/drain`` lease discipline the router's demotion path
        uses, so no in-flight batch loses its params."""
        u = urlsplit(base_url)
        conn = http.client.HTTPConnection(u.hostname, u.port, timeout=30.0)
        try:
            conn.request("POST", "/v1/admin/drain",
                         body=json.dumps({"model": name}).encode("utf-8"),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            self._drain_counter("ok" if resp.status == 200 else "error").inc()
            if resp.status != 200:
                log.warning("drain of %s at %s answered %d", name, base_url,
                            resp.status)
        finally:
            conn.close()

    def _drain_counter(self, outcome: str):
        return self.metrics.counter(
            "autoscale_drains_total", {"outcome": outcome},
            help="scale-in /v1/admin/drain requests, by outcome")

    # -------------------------------------------------------------- lifecycle
    def start(self, interval_s: float = 1.0) -> "AutoscaleController":
        """Run :meth:`tick` on a background loop (the production mode; the
        drills call ``tick()`` directly for determinism)."""
        if self._thread is not None:
            raise RuntimeError("autoscale controller already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, args=(float(interval_s),),
            name="autoscale-controller", daemon=True)
        self._thread.start()
        return self

    def _loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.tick()
            except Exception:  # the control loop must not die of one bad tick  # jaxlint: disable=broad-except
                log.exception("autoscale tick failed")

    def stop(self) -> None:
        """Stop the background loop (managed replicas keep running — the
        autoscaler going away must never take capacity with it)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
