"""autoscale/ — SLO-burn-driven elastic replica autoscaling.

The control loop that turns the observability the stack already emits
into fleet-size decisions: :mod:`.signals` samples burn / queue depth /
KV pressure on one injectable clock, :mod:`.policy` turns the window
into a typed :class:`~.policy.ScaleDecision` (sustain windows, separate
out/in cooldowns, hysteresis, min/max clamps), and :mod:`.controller`
actuates through ``cluster/``: spawn → AOT-warm → first beat on the way
out, drain-then-retire on the way in. Deterministic end to end — same
trace + seed + fake clock ⇒ byte-identical decision log.
"""

from .controller import AutoscaleController
from .policy import (DEFAULT_BURN_OUT, HOLD, IN, OUT, AutoscalePolicy,
                     ScaleDecision)
from .signals import Sample, SignalReader, StepTimeSignalReader

__all__ = [
    "AutoscaleController",
    "AutoscalePolicy",
    "DEFAULT_BURN_OUT",
    "HOLD",
    "IN",
    "OUT",
    "Sample",
    "ScaleDecision",
    "SignalReader",
    "StepTimeSignalReader",
]
