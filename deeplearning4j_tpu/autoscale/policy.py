"""Scale decisions — the pure half of the autoscaler.

:class:`AutoscalePolicy` turns a :class:`~.signals.SignalReader` window
into a typed :class:`ScaleDecision`. It is deliberately free of side
effects on the fleet: no spawning, no draining, no clock reads — the
controller samples, asks, actuates, and only then :meth:`~AutoscalePolicy
.commit`\\ s the decision so a *failed* actuation never burns a cooldown.

Four mechanisms keep a noisy burn signal from oscillating the fleet:

- **sustain windows** — a trigger must hold for ``sustain_out_s`` (or
  ``sustain_in_s``) of consecutive samples; one spiky sample is a
  ``hold(spike)``, never a scale;
- **cooldowns** — after a committed scale-out (scale-in) no further
  scale-out (scale-in) for ``cooldown_out_s`` (``cooldown_in_s``); the
  fleet gets to *observe the effect* of a step before taking another;
- **hysteresis** — scale-in does not arm at "below the scale-out
  threshold" but at ``threshold * hysteresis`` (default well under
  half), so a signal hovering near the threshold sits in the dead band
  and holds instead of flapping out/in/out;
- **clamps** — ``min_replicas``/``max_replicas`` bound every step; the
  floor is a hard capacity constraint, so ``below_min`` repair (a dead
  replica under a min of two) bypasses cooldown.

Every decision — including every hold — carries the evidence that
produced it, JSON-safe and 6-dp rounded, and serializes canonically via
:meth:`ScaleDecision.to_json`: the byte-identity surface the determinism
test diffs across processes.
"""

from __future__ import annotations

import json
from typing import Dict, NamedTuple, Optional

from .signals import Sample

OUT = "out"
IN = "in"
HOLD = "hold"

#: Default scale-out burn thresholds per SLO class. Burn 1.0 = spending
#: error budget exactly as fast as the SLO allows; gold scales the moment
#: it burns at budget, looser classes tolerate proportionally more.
DEFAULT_BURN_OUT: Dict[str, float] = {"gold": 1.0, "standard": 2.0,
                                      "batch": 4.0}


def _r(x: float) -> float:
    """6-dp evidence rounding — same precision rule as sim scoring."""
    return round(float(x), 6)


class ScaleDecision(NamedTuple):
    """One policy verdict plus the inputs that produced it.

    ``reason`` is typed: a trigger (``burn``, ``queue``, ``forecast``,
    ``idle``, ``below_min``, ``above_max``) or a hold cause (``steady``,
    ``spike``, ``cooldown_out``, ``cooldown_in``, ``max_clamp``,
    ``min_clamp``).
    """

    direction: str   # "out" | "in" | "hold"
    amount: int      # replicas to add/remove (0 on hold)
    reason: str
    evidence: dict   # JSON-safe, 6-dp rounded policy inputs

    def to_json(self) -> str:
        """Canonical serialization — the decision log's byte-identity
        surface (sorted keys, no whitespace)."""
        return json.dumps({"direction": self.direction,
                           "amount": self.amount,
                           "reason": self.reason,
                           "evidence": self.evidence},
                          sort_keys=True, separators=(",", ":"))


class AutoscalePolicy:
    """Per-class burn thresholds + sustain + cooldown + hysteresis.

    ``queue_high``/``queue_low`` are per-alive-replica queue-depth
    watermarks: queueing is a saturation signal even before any SLO
    burns (and the only one for traffic with no burn tracking).
    """

    #: Constructor knobs resolvable from a tuned config's ``autoscale``
    #: group (see :func:`~..aot.tuned.tuned_group`).
    KNOBS = frozenset({
        "min_replicas", "max_replicas", "burn_out", "hysteresis",
        "queue_high", "queue_low", "sustain_out_s", "sustain_in_s",
        "cooldown_out_s", "cooldown_in_s", "step_out", "step_in",
        "forecast_confidence",
    })

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 4,
                 burn_out: Optional[Dict[str, float]] = None,
                 hysteresis: float = 0.3,
                 queue_high: float = 16.0, queue_low: float = 1.0,
                 sustain_out_s: float = 2.0, sustain_in_s: float = 10.0,
                 cooldown_out_s: float = 30.0, cooldown_in_s: float = 60.0,
                 step_out: int = 1, step_in: int = 1,
                 forecast_confidence: float = 0.5):
        if not 1 <= int(min_replicas) <= int(max_replicas):
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        if not 0.0 < float(hysteresis) < 1.0:
            raise ValueError("need 0 < hysteresis < 1 (scale-in arms at "
                             "burn <= threshold * hysteresis)")
        if float(queue_low) > float(queue_high):
            raise ValueError("need queue_low <= queue_high")
        if int(step_out) < 1 or int(step_in) < 1:
            raise ValueError("steps must be >= 1")
        for name, v in (("sustain_out_s", sustain_out_s),
                        ("sustain_in_s", sustain_in_s),
                        ("cooldown_out_s", cooldown_out_s),
                        ("cooldown_in_s", cooldown_in_s)):
            if float(v) < 0.0:
                raise ValueError(f"need {name} >= 0")
        if not 0.0 <= float(forecast_confidence) <= 1.0:
            raise ValueError("need 0 <= forecast_confidence <= 1")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.burn_out = {str(k): float(v)
                         for k, v in (burn_out or DEFAULT_BURN_OUT).items()}
        if not self.burn_out:
            raise ValueError("burn_out must name at least one SLO class")
        self.hysteresis = float(hysteresis)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.sustain_out_s = float(sustain_out_s)
        self.sustain_in_s = float(sustain_in_s)
        self.cooldown_out_s = float(cooldown_out_s)
        self.cooldown_in_s = float(cooldown_in_s)
        self.step_out = int(step_out)
        self.step_in = int(step_in)
        self.forecast_confidence = float(forecast_confidence)
        self._last_out_t: Optional[float] = None
        self._last_in_t: Optional[float] = None

    @classmethod
    def from_config(cls, config: Optional[dict],
                    **overrides) -> "AutoscalePolicy":
        """Build from a tuned config's ``autoscale`` knob group (unknown
        keys ignored — forward compatibility with newer tuners), with
        explicit keyword overrides winning."""
        from ..aot.tuned import tuned_group
        opts = {k: v for k, v in tuned_group(config, "autoscale").items()
                if k in cls.KNOBS}
        opts.update(overrides)
        return cls(**opts)

    # -------------------------------------------------------- predicates
    def _hot(self, s: Sample) -> Optional[str]:
        """The scale-out trigger this sample shows (``burn`` before
        ``queue`` — the SLO is the contract, the queue a leading
        indicator), or None."""
        for cls in sorted(self.burn_out):
            if s.burn.get(cls, 0.0) >= self.burn_out[cls]:
                return "burn"
        if s.queue_depth / max(1, s.alive) >= self.queue_high:
            return "queue"
        return None

    def _idle(self, s: Sample) -> bool:
        """Below the hysteresis band: every tracked class burns well under
        its threshold AND per-replica queues are drained."""
        for cls, thr in self.burn_out.items():
            if s.burn.get(cls, 0.0) > thr * self.hysteresis:
                return False
        return s.queue_depth / max(1, s.alive) <= self.queue_low

    def _forecast_breach(self, forecast) -> Optional[str]:
        """First tracked class (sorted) whose forecast predicts burning at
        or past its scale-out threshold with enough confidence, or None.
        ``forecast`` maps class -> :class:`~..obs.forecast.Forecast`."""
        if not forecast:
            return None
        for cls in sorted(self.burn_out):
            f = forecast.get(cls)
            if (f is not None
                    and f.confidence >= self.forecast_confidence
                    and f.value >= self.burn_out[cls]):
                return cls
        return None

    # ---------------------------------------------------------- decision
    def decide(self, signals, current: int, now: float,
               forecast=None) -> ScaleDecision:
        """One verdict from the signal window. Pure in the signals — no
        sampling, no clock reads, no state writes; cooldowns advance only
        via :meth:`commit` after the controller actually actuated.

        ``forecast`` (optional) maps SLO class -> a typed
        :class:`~..obs.forecast.Forecast` of that class's burn at the
        forecaster's horizon. A confident predicted breach pre-spawns
        *before* the ramp trips the live thresholds; the sustain /
        cooldown / clamp machinery is unchanged, and a ``None`` forecast
        reproduces the legacy decision stream byte for byte.
        """
        window = signals.window()
        last = window[-1] if window else None
        ev = {
            "t": _r(now),
            "current": int(current),
            "samples": len(window),
            "burn": {k: _r(v)
                     for k, v in (sorted(last.burn.items()) if last else [])},
            "queue_depth": int(last.queue_depth) if last else 0,
            "kv_pressure": _r(last.kv_pressure) if last else 0.0,
        }
        if forecast is not None:
            ev["forecast"] = {
                str(cls): {"horizon_s": _r(f.horizon_s),
                           "value": _r(f.value),
                           "confidence": _r(f.confidence)}
                for cls, f in sorted(forecast.items()) if f is not None}

        def verdict(direction: str, amount: int, reason: str,
                    **extra) -> ScaleDecision:
            ev.update(extra)
            return ScaleDecision(direction, int(amount), reason, ev)

        # capacity-bound repair outranks everything, including cooldowns:
        # min_replicas is a floor the fleet must hold even right after a
        # scale event (the dead-replica-under-load drill lands here)
        if current < self.min_replicas:
            return verdict(OUT, self.min_replicas - current, "below_min")
        if current > self.max_replicas:
            return verdict(IN, current - self.max_replicas, "above_max")

        hot_now = last is not None and self._hot(last) is not None
        if hot_now and signals.sustained(
                lambda s: self._hot(s) is not None, self.sustain_out_s, now):
            trigger = self._hot(last)
            if current >= self.max_replicas:
                return verdict(HOLD, 0, "max_clamp", trigger=trigger)
            if self._cooling(self._last_out_t, self.cooldown_out_s, now):
                return verdict(HOLD, 0, "cooldown_out", trigger=trigger)
            return verdict(OUT, min(self.step_out,
                                    self.max_replicas - current), trigger)
        if hot_now:
            return verdict(HOLD, 0, "spike")

        # predictive pre-spawn: not hot NOW, but a confident forecast says
        # a tracked class breaches its threshold within the horizon — act
        # while there is still spawn+warm latency to hide. Same clamps and
        # cooldown as a reactive scale-out; no sustain window (the horizon
        # plays that role, and the forecaster's confidence floor gates
        # noise the way sustain gates spikes).
        fc_cls = self._forecast_breach(forecast)
        if fc_cls is not None:
            if current >= self.max_replicas:
                return verdict(HOLD, 0, "max_clamp", trigger="forecast",
                               forecast_class=fc_cls)
            if self._cooling(self._last_out_t, self.cooldown_out_s, now):
                return verdict(HOLD, 0, "cooldown_out", trigger="forecast",
                               forecast_class=fc_cls)
            return verdict(OUT, min(self.step_out,
                                    self.max_replicas - current),
                           "forecast", forecast_class=fc_cls)

        if (last is not None and self._idle(last)
                and signals.sustained(self._idle, self.sustain_in_s, now)):
            if current <= self.min_replicas:
                return verdict(HOLD, 0, "min_clamp")
            if self._cooling(self._last_in_t, self.cooldown_in_s, now):
                return verdict(HOLD, 0, "cooldown_in")
            return verdict(IN, min(self.step_in,
                                   current - self.min_replicas), "idle")
        return verdict(HOLD, 0, "steady")

    @staticmethod
    def _cooling(last_t: Optional[float], cooldown_s: float,
                 now: float) -> bool:
        return last_t is not None and (now - last_t) < cooldown_s

    def commit(self, decision: ScaleDecision, now: float) -> None:
        """Arm the scaled direction's cooldown — called by the controller
        after a SUCCESSFUL actuation only, so a spawn that failed (chaos,
        resource exhaustion) leaves the policy free to retry next tick."""
        if decision.direction == OUT:
            self._last_out_t = float(now)
        elif decision.direction == IN:
            self._last_in_t = float(now)

    def snapshot(self) -> dict:
        """JSON-safe config + cooldown state for ``/v1/cluster``."""
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "burn_out": dict(sorted(self.burn_out.items())),
            "hysteresis": self.hysteresis,
            "queue_high": self.queue_high,
            "queue_low": self.queue_low,
            "sustain_s": {"out": self.sustain_out_s,
                          "in": self.sustain_in_s},
            "cooldown_s": {"out": self.cooldown_out_s,
                           "in": self.cooldown_in_s},
            "step": {"out": self.step_out, "in": self.step_in},
            "forecast_confidence": self.forecast_confidence,
            "last_scale_t": {"out": self._last_out_t,
                             "in": self._last_in_t},
        }
