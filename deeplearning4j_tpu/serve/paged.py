"""Paged KV-cache block management for the continuous batcher.

The dense layout gives every decode slot a private ``(1, capacity, ...)``
KV buffer, so HBM cost is ``O(slots x capacity)`` whether or not tokens are
live, and a request can never be longer than the buffer it was born with.
The paged layout (vLLM's PagedAttention scheme, adapted to the fixed-shape
XLA contract) carves KV memory into fixed-size **token blocks** in one
shared pool per attention layer:

- ``k_pool`` / ``v_pool``: ``(num_blocks, block_size, Hkv, hd)`` device
  arrays, donated through every decode tick / prefill chunk (loop-carried,
  never copied);
- a per-slot **block table** ``(slots, max_blocks)`` int32 mapping logical
  block ``p // block_size`` to a physical block — a *traced operand* of
  the one compiled decode step, so growing/retiring sequences never
  changes a shape and never recompiles anything;
- physical **block 0 is reserved as the trash block**: unallocated table
  entries point at it, so the fixed-shape decode step can write every
  slot every tick (inactive slots scribble on trash) and right-padded
  prefill garbage lands there too. Nothing ever unmasked-reads block 0.

HBM cost becomes ``O(allocated blocks)`` — proportional to live tokens —
and per-request capacity is a *logical* limit (``max_blocks x
block_size``), decoupled from any dense buffer.

Sharing is first-class: every live block carries a **refcount**, so one
physical block can back the same prefix in many slots at once. The
:class:`PrefixCache` maps ``(params generation, rolling sha256 of
whole-block token runs)`` to physical blocks, holding one reference per
cached block; prefill adopts the longest cached run (refcount++) and
computes only the suffix. Only *whole* blocks are ever shared and decode
writes land in a slot's private tail block, so copy-on-write triggers
exactly when a slot must write into a block someone else still references
(a forked tail). All of it is pure host-side bookkeeping: integer free
lists and hash maps, no device work here — the batcher performs the one
CoW block copy on its own thread.

The device-side layout contract (how positions map into pools, the trash
block, append/read semantics) lives in ``nn/generation.py`` next to
``cache_append`` / ``cache_read``; this module only decides *which*
physical blocks a slot owns.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .errors import CapacityError

TRASH_BLOCK = 0  # physical block 0 is never allocated; see module docstring


class BlockAllocator:
    """Refcounted free-list allocator over physical block ids
    ``1..num_blocks-1``.

    ``alloc`` hands out blocks at refcount 1; ``retain`` adds a reference
    (prefix adoption, forks); ``release`` drops one and returns the block
    to the free list when the count hits zero. LIFO reuse (a freed block
    is the next handed out) keeps the working set compact. Releasing a
    free block (double release) or the trash block stays a hard error —
    a refcount bug here is silent KV corruption, never something to limp
    past. Pure host-side and NOT thread-safe by itself — the batcher
    serializes calls under its own lock.
    """

    def __init__(self, num_blocks: int,
                 reclaimer: Optional[Callable[[int], int]] = None):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (1 usable + trash), "
                             f"got {num_blocks}")
        self.num_blocks = int(num_blocks)
        # LIFO: low ids at the tail so fresh pools fill from block 1 up
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._refs: Dict[int, int] = {}
        # last-ditch supply: asked to make `n` more blocks reclaimable
        # before alloc gives up (the prefix cache's LRU plugs in here, so
        # cached-but-unreferenced runs are reclaimed before anyone sheds)
        self._reclaimer = reclaimer

    def set_reclaimer(self, fn: Optional[Callable[[int], int]]) -> None:
        self._reclaimer = fn

    @property
    def usable(self) -> int:
        """Total allocatable blocks (excludes the trash block)."""
        return self.num_blocks - 1

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def used(self) -> int:
        return len(self._refs)

    def refcount(self, block: int) -> int:
        """Current references on ``block`` (0 == free)."""
        return self._refs.get(int(block), 0)

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` blocks at refcount 1 or raise :class:`CapacityError`
        (taking none).

        Callers gate admission on worst-case commitment, so exhaustion here
        means a bookkeeping bug — but it stays a *typed* failure either way.
        A registered reclaimer (prefix-cache LRU) is asked to free the
        shortfall first, so cached-but-idle blocks never starve live work.
        """
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free) and self._reclaimer is not None:
            self._reclaimer(n - len(self._free))
        if n > len(self._free):
            raise CapacityError(
                f"KV block pool exhausted: need {n}, {len(self._free)} of "
                f"{self.usable} free")
        ids = [self._free.pop() for _ in range(n)]
        for b in ids:
            self._refs[b] = 1
        return ids

    def retain(self, ids) -> None:
        """Add one reference to each live block (sharing a prefix/fork)."""
        for b in ids:
            b = int(b)
            if b == TRASH_BLOCK:
                raise ValueError("attempted to retain the trash block")
            if b not in self._refs:
                raise ValueError(f"retain of free block {b}")
            self._refs[b] += 1

    def release(self, ids) -> None:
        """Drop one reference per block; a block hitting zero goes back to
        the free list. Double release stays a hard error."""
        for b in ids:
            b = int(b)
            if b == TRASH_BLOCK:
                raise ValueError("attempted to release the trash block")
            c = self._refs.get(b)
            if c is None:
                raise ValueError(f"double free of block {b}")
            if c == 1:
                del self._refs[b]
                self._free.append(b)
            else:
                self._refs[b] = c - 1

    def free(self, ids) -> None:
        """Alias of :meth:`release` (the pre-refcount name)."""
        self.release(ids)


def build_pools(model, num_blocks: int, block_size: int, dtype) -> Dict:
    """Zero-filled per-attention-layer block pools:
    ``{layer_key: {"k": (N, bs, Hkv, hd), "v": ...}}`` (device arrays)."""
    import jax.numpy as jnp

    from ..nn.generation import cache_spec

    spec = cache_spec(model)
    if not spec:
        raise ValueError("model has no attention layers to page")
    return {lk: {"k": jnp.zeros((num_blocks, block_size, hkv, hd), dtype),
                 "v": jnp.zeros((num_blocks, block_size, hkv, hd), dtype)}
            for lk, hkv, hd in spec}


def block_bytes(model, block_size: int, dtype) -> int:
    """Bytes of KV one block holds across ALL attention layers (k + v) —
    the unit the live-KV-bytes gauge counts in."""
    from ..nn.generation import cache_spec

    itemsize = np.dtype(dtype).itemsize
    return sum(2 * block_size * hkv * hd * itemsize
               for _, hkv, hd in cache_spec(model))


def blocks_needed(tokens: int, block_size: int) -> int:
    """Blocks covering ``tokens`` positions (ceil division)."""
    return -(-int(tokens) // int(block_size))


def prefix_hashes(tokens, block_size: int) -> List[bytes]:
    """Rolling sha256 over whole-block token runs.

    ``hashes[i]`` commits to tokens ``[0, (i+1)*block_size)`` — the entire
    run, not just block ``i`` — so two prompts share a cache entry only
    when every block before it matches too. Partial tail tokens are never
    hashed: only whole blocks are shareable.
    """
    toks = np.ascontiguousarray(np.asarray(tokens, np.int32))
    out: List[bytes] = []
    h = hashlib.sha256()
    for i in range(toks.shape[0] // int(block_size)):
        h.update(toks[i * block_size:(i + 1) * block_size].tobytes())
        out.append(h.digest())
    return out


class PrefixCache:
    """LRU of cached whole-block prefix runs, keyed on
    ``(params generation, rolling block-run sha256)``.

    The cache holds exactly ONE allocator reference per cached block, so a
    cached block survives its writer's retirement but is reclaimable the
    moment no slot references it. ``match`` finds the longest cached run
    for a prompt (pure lookup, no side effects — admission gates on the
    result before committing); ``adopt`` takes the references. A
    generation flip invalidates wholesale: stale-params KV can never be
    adopted, because every entry of the old generation is released before
    the first new-generation lookup returns.

    Not thread-safe by itself — the batcher serializes calls under its
    own lock, same as :class:`BlockAllocator`.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int,
                 max_blocks: Optional[int] = None):
        self._alloc = allocator
        self.block_size = int(block_size)
        # hard size bound (entries == blocks); None = bounded only by the
        # pool via the allocator's reclaimer
        self.max_blocks = int(max_blocks) if max_blocks is not None else None
        self.generation: Optional[int] = None
        self._runs: "OrderedDict[bytes, int]" = OrderedDict()
        self.evictions = 0
        self.flushes = 0

    def __len__(self) -> int:
        return len(self._runs)

    def blocks(self) -> List[int]:
        """Cached physical block ids (diagnostics/tests)."""
        return list(self._runs.values())

    def _ensure_generation(self, generation: int) -> None:
        if generation != self.generation:
            if self._runs:
                self.flush()
            self.generation = generation

    def flush(self) -> int:
        """Drop every entry, releasing the cache's references. Returns the
        number of entries released."""
        n = len(self._runs)
        if n:
            self._alloc.release(list(self._runs.values()))
            self._runs.clear()
            self.flushes += 1
        return n

    def match(self, hashes: Sequence[bytes], generation: int,
              limit: int) -> List[int]:
        """Longest cached run of full blocks from the start of the prompt
        (<= ``limit`` blocks), as physical ids. NO references are taken
        and no LRU state moves — call :meth:`adopt` once admission commits."""
        self._ensure_generation(generation)
        run: List[int] = []
        for h in hashes[:max(0, int(limit))]:
            b = self._runs.get(h)
            if b is None:
                break
            run.append(b)
        return run

    def adopt(self, hashes: Sequence[bytes], run: List[int]) -> None:
        """Take one reference per matched block and mark the run
        recently-used. ``run`` must be a fresh :meth:`match` result under
        the same lock."""
        if not run:
            return
        self._alloc.retain(run)
        for h in hashes[:len(run)]:
            self._runs.move_to_end(h)

    def insert(self, hashes: Sequence[bytes], blocks: Sequence[int],
               generation: int) -> int:
        """Cache a slot's full prompt blocks (the cache takes its own
        reference per newly inserted block). Entries already present keep
        their existing physical block — the newcomer's copy stays private
        and retires with its slot. Returns the number inserted."""
        self._ensure_generation(generation)
        ins = 0
        for h, b in zip(hashes, blocks):
            if h in self._runs:
                self._runs.move_to_end(h)
                continue
            if self.max_blocks is not None \
                    and len(self._runs) >= self.max_blocks \
                    and not self._evict_lru():
                break
            self._alloc.retain([b])
            self._runs[h] = b
            ins += 1
        return ins

    def _evict_lru(self) -> bool:
        """Drop the least-recently-used entry (size bound), releasing the
        cache's reference — the block itself is freed only if no slot
        still references it."""
        if not self._runs:
            return False
        _, b = self._runs.popitem(last=False)
        self._alloc.release([b])
        self.evictions += 1
        return True

    def reclaim(self, need: int) -> int:
        """Capacity pressure: free up to ``need`` blocks by evicting LRU
        entries whose ONLY reference is the cache (those actually return
        to the free list). Entries still adopted by live slots are left
        alone — evicting them would free nothing. This is the allocator's
        reclaimer hook, so idle cached runs are always reclaimed before
        any request sheds."""
        freed = 0
        if need <= 0:
            return 0
        for h in list(self._runs.keys()):
            if freed >= need:
                break
            b = self._runs[h]
            if self._alloc.refcount(b) == 1:
                del self._runs[h]
                self._alloc.release([b])
                self.evictions += 1
                freed += 1
        return freed

    def stats(self) -> dict:
        return {"entries": len(self._runs),
                "max_blocks": self.max_blocks,
                "evictions": self.evictions,
                "flushes": self.flushes,
                "generation": self.generation}


class SlotPages:
    """One slot's view of the pool: its blocks, in logical order, plus
    which of them are *shared* (held via ``retain`` — adopted prefix runs
    or fork parents' blocks — rather than privately allocated).

    ``ensure(tokens)`` grows the mapping to cover ``tokens`` positions,
    allocating lazily — so the pool's *used* count tracks live tokens, not
    requested worst cases. The batcher writes the returned new block ids
    into its host block-table row. Releasing is uniform under refcounts:
    every block drops one reference, shared blocks simply survive in
    their other holders.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int):
        self._alloc = allocator
        self.block_size = int(block_size)
        self.blocks: List[int] = []
        self.shared: set = set()  # subset of blocks held by retain, not alloc

    def adopt(self, blocks: Sequence[int]) -> None:
        """Front-load already-retained shared blocks (prefix adoption).
        Must run before any private allocation."""
        if self.blocks:
            raise ValueError("adopt() must precede any allocation")
        self.blocks = [int(b) for b in blocks]
        self.shared.update(self.blocks)

    def ensure(self, tokens: int) -> List[int]:
        """Cover ``tokens`` positions; returns the NEWLY allocated ids."""
        need = blocks_needed(tokens, self.block_size) - len(self.blocks)
        if need <= 0:
            return []
        new = self._alloc.alloc(need)
        self.blocks.extend(new)
        return new

    def swap(self, idx: int, new_block: int) -> int:
        """Copy-on-write bookkeeping: replace the block at logical index
        ``idx`` with ``new_block`` (already allocated, private), dropping
        this slot's reference on the old one. Returns the old id — the
        caller has already copied its KV device-side."""
        old = self.blocks[idx]
        self.blocks[idx] = int(new_block)
        self.shared.discard(old)
        self._alloc.release([old])
        return old

    def release(self) -> None:
        """Copy-free retirement: drop one reference on every block; fully
        private blocks go straight back to the free list."""
        if self.blocks:
            self._alloc.release(self.blocks)
            self.blocks = []
            self.shared.clear()
