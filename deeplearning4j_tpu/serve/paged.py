"""Paged KV-cache block management for the continuous batcher.

The dense layout gives every decode slot a private ``(1, capacity, ...)``
KV buffer, so HBM cost is ``O(slots x capacity)`` whether or not tokens are
live, and a request can never be longer than the buffer it was born with.
The paged layout (vLLM's PagedAttention scheme, adapted to the fixed-shape
XLA contract) carves KV memory into fixed-size **token blocks** in one
shared pool per attention layer:

- ``k_pool`` / ``v_pool``: ``(num_blocks, block_size, Hkv, hd)`` device
  arrays, donated through every decode tick / prefill chunk (loop-carried,
  never copied);
- a per-slot **block table** ``(slots, max_blocks)`` int32 mapping logical
  block ``p // block_size`` to a physical block — a *traced operand* of
  the one compiled decode step, so growing/retiring sequences never
  changes a shape and never recompiles anything;
- physical **block 0 is reserved as the trash block**: unallocated table
  entries point at it, so the fixed-shape decode step can write every
  slot every tick (inactive slots scribble on trash) and right-padded
  prefill garbage lands there too. Nothing ever unmasked-reads block 0.

HBM cost becomes ``O(allocated blocks)`` — proportional to live tokens —
and per-request capacity is a *logical* limit (``max_blocks x
block_size``), decoupled from any dense buffer. The allocator below is
pure host-side bookkeeping: integer free lists, no device work, so slot
retirement is copy-free (free the ids, zero the table row).

The device-side layout contract (how positions map into pools, the trash
block, append/read semantics) lives in ``nn/generation.py`` next to
``cache_append`` / ``cache_read``; this module only decides *which*
physical blocks a slot owns.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .errors import CapacityError

TRASH_BLOCK = 0  # physical block 0 is never allocated; see module docstring


class BlockAllocator:
    """Free-list allocator over physical block ids ``1..num_blocks-1``.

    LIFO reuse (a freed block is the next handed out) keeps the working
    set compact. Pure host-side and NOT thread-safe by itself — the
    batcher serializes calls under its own lock.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (1 usable + trash), "
                             f"got {num_blocks}")
        self.num_blocks = int(num_blocks)
        # LIFO: low ids at the tail so fresh pools fill from block 1 up
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._live: set = set()

    @property
    def usable(self) -> int:
        """Total allocatable blocks (excludes the trash block)."""
        return self.num_blocks - 1

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def used(self) -> int:
        return len(self._live)

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` blocks or raise :class:`CapacityError` (taking none).

        Callers gate admission on worst-case commitment, so exhaustion here
        means a bookkeeping bug — but it stays a *typed* failure either way.
        """
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise CapacityError(
                f"KV block pool exhausted: need {n}, {len(self._free)} of "
                f"{self.usable} free")
        ids = [self._free.pop() for _ in range(n)]
        self._live.update(ids)
        return ids

    def free(self, ids) -> None:
        """Return blocks to the pool; double-free is a hard error."""
        for b in ids:
            b = int(b)
            if b == TRASH_BLOCK:
                raise ValueError("attempted to free the trash block")
            if b not in self._live:
                raise ValueError(f"double free of block {b}")
            self._live.discard(b)
            self._free.append(b)


def build_pools(model, num_blocks: int, block_size: int, dtype) -> Dict:
    """Zero-filled per-attention-layer block pools:
    ``{layer_key: {"k": (N, bs, Hkv, hd), "v": ...}}`` (device arrays)."""
    import jax.numpy as jnp

    from ..nn.generation import cache_spec

    spec = cache_spec(model)
    if not spec:
        raise ValueError("model has no attention layers to page")
    return {lk: {"k": jnp.zeros((num_blocks, block_size, hkv, hd), dtype),
                 "v": jnp.zeros((num_blocks, block_size, hkv, hd), dtype)}
            for lk, hkv, hd in spec}


def block_bytes(model, block_size: int, dtype) -> int:
    """Bytes of KV one block holds across ALL attention layers (k + v) —
    the unit the live-KV-bytes gauge counts in."""
    from ..nn.generation import cache_spec

    itemsize = np.dtype(dtype).itemsize
    return sum(2 * block_size * hkv * hd * itemsize
               for _, hkv, hd in cache_spec(model))


def blocks_needed(tokens: int, block_size: int) -> int:
    """Blocks covering ``tokens`` positions (ceil division)."""
    return -(-int(tokens) // int(block_size))


class SlotPages:
    """One slot's view of the pool: its allocated blocks, in logical order.

    ``ensure(tokens)`` grows the mapping to cover ``tokens`` positions,
    allocating lazily — so the pool's *used* count tracks live tokens, not
    requested worst cases. The batcher writes the returned new block ids
    into its host block-table row.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int):
        self._alloc = allocator
        self.block_size = int(block_size)
        self.blocks: List[int] = []

    def ensure(self, tokens: int) -> List[int]:
        """Cover ``tokens`` positions; returns the NEWLY allocated ids."""
        need = blocks_needed(tokens, self.block_size) - len(self.blocks)
        if need <= 0:
            return []
        new = self._alloc.alloc(need)
        self.blocks.extend(new)
        return new

    def release(self) -> None:
        """Copy-free retirement: hand every block back to the free list."""
        if self.blocks:
            self._alloc.free(self.blocks)
            self.blocks = []
