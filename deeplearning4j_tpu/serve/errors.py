"""Typed serving failures.

Every way a request can fail without a result is a distinct exception type
carrying a stable machine-readable ``cause`` tag (the label on the
``serve_shed_total{cause=...}`` counter) and the HTTP status the front-end
maps it to. Clients — and tests — branch on type/cause, never on message
text, and overload NEVER manifests as a hang: admission control raises
:class:`ShedError` immediately, expiry raises
:class:`DeadlineExceededError` at dispatch time.

The ``cause``/``http_status`` class attributes are also the *statically
checked* contract: jaxlint's v5 error-flow pass resolves them through the
class hierarchy and diffs every HTTP boundary's (exception → status)
mapping against the committed ``scripts/error_budget.json`` — changing a
status here (or answering a typed error with a contradicting literal at a
handler) fails CI until the budget is re-reviewed. See
``analysis/README.md``, "Error-flow model (v5)".
"""

from __future__ import annotations


class ServeError(RuntimeError):
    """Base class for typed serving failures."""

    cause: str = "internal"
    http_status: int = 500

    def __init__(self, message: str, cause: str = None):
        super().__init__(message)
        if cause is not None:
            self.cause = cause


class ShedError(ServeError):
    """Request refused at admission — bounded queue full (load shedding).

    Overload is answered instantly and cheaply: the client should back off
    and retry (HTTP 503).
    """

    cause = "queue_full"
    http_status = 503


class ServerClosingError(ShedError):
    """Request refused because the server is draining for shutdown."""

    cause = "shutting_down"


class WorkerStallError(ServeError):
    """In-flight work shed because the worker thread that owned it died or
    stalled past its heartbeat deadline. The watchdog (or the dying worker
    itself) answers every orphaned request with this instead of leaving
    its caller to hang; a crash-only restart takes over, so the request is
    safely retryable (HTTP 503)."""

    cause = "worker_stall"
    http_status = 503


class DrainTimeoutError(ServeError):
    """``shutdown(drain=True)`` hit its timeout with work still in flight
    (e.g. a wedged device call). The work is abandoned and answered with
    this typed error rather than hanging the shutdown — retry against
    another replica (HTTP 503)."""

    cause = "drain_timeout"
    http_status = 503


class DeadlineExceededError(ServeError):
    """The request's deadline passed before device work could start."""

    cause = "deadline"
    http_status = 504


class CapacityError(ServeError):
    """The request can never fit — e.g. prompt + max_new_tokens exceeds the
    generation KV-cache capacity, or a sequence is longer than the largest
    length bucket. Retrying will not help (HTTP 400)."""

    cause = "over_capacity"
    http_status = 400


class AotTraceError(ServeError):
    """Strict AOT mode hit a signature the persistent store does not
    cover. A strict replica is deployed on the contract that every
    executable was prebuilt from the static compile surface
    (``analysis/enumerate.py`` -> ``aot prebuild --from-surface``);
    tracing at request time would mean the deployed store diverged from
    the budgeted surface, so the miss is answered as a typed 503 —
    counted on ``serve_aot_strict_misses_total`` — and at boot time it
    fails readiness outright. Never a silent trace (HTTP 503)."""

    cause = "aot_trace"
    http_status = 503


class PublishError(ServeError):
    """A model publish aborted BEFORE the generation flip — e.g.
    precompiling/warming the candidate against the live bucket signatures
    failed. The previous generation keeps serving; registry history, lease
    accounting and the generation counter are untouched, so the caller can
    fix the candidate and re-publish."""

    cause = "publish_failed"
    http_status = 500
